"""Replicated quota coordination: leader-lease failover for the fleet
quota tier.

PR 15's :class:`~photon_ml_tpu.serving.fleet.QuotaCoordinator` is one
object in one process: its death freezes rebalancing until an operator
notices (hosts ride the degrade-to-last-lease contract, so admission
stays bounded — but it stays FROZEN).  This module makes the
coordinator a replicated service with bounded failover:

- :class:`CoordinatorReplica` — one coordinator replica over a SHARED
  store directory.  Exactly one replica is leader at a time, elected
  through a leader-lease file (``leader.json``: atomic
  write-temp + fsync + rename, then read-back to confirm — the same
  discipline every journal in this repo uses).  The leader answers
  ``renew`` by delegating to an inner ``QuotaCoordinator`` and
  JOURNALS every grant batch (``coordinator_journal.jsonl``,
  tuning/state.py fsync discipline) BEFORE the lease is returned;
  followers refuse with :class:`NotLeaderError` naming the leader.
- **Failover**: when the leader dies, its leader lease stops being
  renewed and expires after ``leader_ttl_s`` (default: half the quota
  lease TTL).  The next ``renew`` that reaches any live replica
  acquires the lease with a bumped term and REPLAYS the journal —
  seeding its grant table with the dead leader's outstanding grants
  (``QuotaCoordinator.restore_grant``) so the new leader's budget
  arithmetic never double-grants a slice that is still live on a
  host.  Total takeover time is bounded by ``leader_ttl_s`` + one
  host renew interval ≈ one quota lease TTL; meanwhile hosts degrade
  to their last lease, so over-admission stays within one lease
  window — the SAME bound a coordinator partition already has.
- :class:`ReplicatedQuotaCoordinator` — the host-facing client: same
  duck type as ``QuotaCoordinator`` (``renew`` + ``lease_ttl_s``), so
  ``LeaseClient`` composes unchanged.  Each renewal walks the replica
  set starting at the last known leader, follows ``NotLeaderError``
  hints, and raises UNAVAILABLE only when NO replica will serve — at
  which point the lease client degrades exactly as today.

Chaos seam: ``cluster.lease`` fires per replica attempt inside the
client (a fault is that replica unreachable — the client fails over;
every replica faulted is the full partition).  Metric family:
``cluster_*``.  docs/serving.md "Cluster" has the TTL math.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.io.checkpoint import fsync_file
from photon_ml_tpu.serving.fleet import QuotaCoordinator


class NotLeaderError(RuntimeError):
    """A follower replica refusing ``renew``; ``leader_hint`` names the
    replica id currently holding the leader lease (None when the lease
    is expired and the refusing replica lost the acquire race)."""

    def __init__(self, message: str, leader_hint: Optional[str] = None):
        super().__init__(message)
        self.leader_hint = leader_hint


LEADER_FILE = "leader.json"
JOURNAL_FILE = "coordinator_journal.jsonl"

#: Journal compaction threshold: past this many records the journal is
#: rewritten to the latest grant per (tenant, host) + the election
#: high-water — the replay state, nothing else.
_COMPACT_AFTER = 4096


class CoordinatorReplica:
    """One quota-coordinator replica over a shared ``store_dir``.

    All liveness bookkeeping rides the injectable monotonic ``clock``
    shared by the replica set (one process today; a shared clock
    service later — the election algebra does not change).  ``kill()``
    makes the replica refuse everything (the scripted coordinator
    crash); ``restart()`` brings it back as a FOLLOWER — it may win
    the next election, but never resumes a stale term."""

    def __init__(
        self,
        replica_id: str,
        store_dir: str,
        budgets,
        lease_ttl_s: float = 1.0,
        leader_ttl_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        fsync: bool = True,
    ):
        self.replica_id = str(replica_id)
        self.store_dir = store_dir
        self.lease_ttl_s = float(lease_ttl_s)
        #: leader-lease TTL: half the quota lease TTL by default, so
        #: leader expiry + one renew interval stays within ONE quota
        #: lease window (the failover bound in docs/serving.md).
        self.leader_ttl_s = (
            self.lease_ttl_s / 2.0
            if leader_ttl_s is None else float(leader_ttl_s)
        )
        self._budgets = budgets
        self._clock = clock
        self.fsync = fsync
        self.killed = False
        self.term = 0
        self.elections = 0
        self.renewals = 0
        self._coordinator: Optional[QuotaCoordinator] = None
        self._f = None
        self._written = 0
        self._lock = sanitizers.tracked(
            threading.Lock(), f"cluster.coordinator.{self.replica_id}"
        )
        os.makedirs(store_dir, exist_ok=True)
        self._leader_path = os.path.join(store_dir, LEADER_FILE)
        self._journal_path = os.path.join(store_dir, JOURNAL_FILE)

    # -- leader lease -------------------------------------------------------
    def _read_leader(self) -> Optional[dict]:
        try:
            with open(self._leader_path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            # A torn leader file is an expired lease: the writer died
            # mid-rename-window; the next acquire overwrites it.
            return None

    def _write_leader(self, record: dict) -> None:
        # Caller holds self._lock.  Atomic + durable, then READ BACK:
        # last-writer-wins between racing replicas, and the read-back
        # means a replica only believes an election it can see on disk.
        tmp = self._leader_path + f".{self.replica_id}.tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
            if self.fsync:
                fsync_file(f)
        os.replace(tmp, self._leader_path)

    def _ensure_leader(self, now: float) -> None:
        # Caller holds self._lock.  Raises NotLeaderError / RuntimeError
        # unless this replica holds (or just acquired) the leader lease.
        if self.killed:
            raise RuntimeError(
                f"UNAVAILABLE: coordinator replica {self.replica_id} "
                "is down"
            )
        current = self._read_leader()
        holder = None if current is None else current.get("leader")
        expired = (
            current is None
            or float(current.get("expires_at", 0.0)) <= now
        )
        if holder == self.replica_id and not expired:
            # Renew our own lease past half-TTL so a busy leader never
            # lets it lapse between renews.
            if float(current["expires_at"]) - now < self.leader_ttl_s / 2:
                current["expires_at"] = now + self.leader_ttl_s
                self._write_leader(current)
            return
        if not expired:
            raise NotLeaderError(
                f"replica {self.replica_id} is not the leader "
                f"(leader: {holder}, term {current.get('term')})",
                leader_hint=str(holder),
            )
        # Expired or vacant: try to take it.
        term = (0 if current is None else int(current.get("term", 0))) + 1
        self._write_leader({
            "leader": self.replica_id,
            "term": term,
            "expires_at": now + self.leader_ttl_s,
        })
        confirmed = self._read_leader()
        if confirmed is None or confirmed.get("leader") != self.replica_id:
            raise NotLeaderError(
                f"replica {self.replica_id} lost the acquire race "
                f"(winner: {None if confirmed is None else confirmed.get('leader')})",
                leader_hint=(
                    None if confirmed is None
                    else str(confirmed.get("leader"))
                ),
            )
        self._become_leader_locked(int(confirmed["term"]))

    def _become_leader_locked(self, term: int) -> None:
        # Caller holds self._lock.  Fresh coordinator seeded from the
        # journal: the previous leader's outstanding grants are the
        # starting budget arithmetic, not an empty table.
        self.term = term
        self.elections += 1
        coordinator = QuotaCoordinator(
            self._budgets, lease_ttl_s=self.lease_ttl_s,
            clock=self._clock,
        )
        replayed = 0
        for host, leases in self._replay_grants().items():
            for tenant, g in leases.items():
                coordinator.restore_grant(
                    tenant, host,
                    rate_rps=g["rate"],
                    demand_rps=g["demand"],
                    expires_at=g["expires_at"],
                )
                replayed += 1
        self._coordinator = coordinator
        self._append({
            "kind": "election",
            "term": term,
            "leader": self.replica_id,
            "replayed_grants": replayed,
            "wall_epoch": time.time(),
        })
        tel = telemetry_mod.current()
        tel.counter("cluster_elections_total").inc()
        tel.gauge("cluster_leader_term_count").set(term)
        tel.event(
            "cluster.leader_elected",
            replica=self.replica_id, term=term,
            replayed_grants=replayed,
        )

    # -- journal (tuning/state.py discipline) -------------------------------
    def _append(self, record: dict) -> None:
        # Caller holds self._lock.
        if self._f is None:
            self._f = open(self._journal_path, "a")
        self._f.write(json.dumps(record) + "\n")
        if self.fsync:
            fsync_file(self._f)
        else:
            self._f.flush()
        self._written += 1
        if self._written >= _COMPACT_AFTER:
            self._compact_locked()

    def _read_journal(self) -> List[dict]:
        # Caller holds self._lock.  Torn-tail tolerant.
        if not os.path.exists(self._journal_path):
            return []
        if self._f is not None:
            self._f.flush()
        with open(self._journal_path) as f:
            lines = f.read().splitlines()
        records = []
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    break  # torn tail: the write died mid-line
                raise
        return records

    def _replay_grants(self) -> Dict[str, dict]:
        # Caller holds self._lock.  Latest grant batch per host wins
        # (records are append-ordered).
        grants: Dict[str, dict] = {}
        for r in self._read_journal():
            if r.get("kind") == "grants":
                grants[str(r["host"])] = r["leases"]
        return grants

    def _compact_locked(self) -> None:
        # Caller holds self._lock.  Keep exactly the replay state: the
        # newest election record + the latest grant batch per host.
        records = self._read_journal()
        elections = [r for r in records if r.get("kind") == "election"]
        latest: Dict[str, dict] = {}
        for r in records:
            if r.get("kind") == "grants":
                latest[str(r["host"])] = r
        compacted = elections[-1:] + [
            latest[h] for h in sorted(latest)
        ]
        if self._f is not None:
            self._f.close()
            self._f = None
        tmp = self._journal_path + ".tmp"
        with open(tmp, "w") as f:
            for r in compacted:
                f.write(json.dumps(r) + "\n")
            if self.fsync:
                fsync_file(f)
        os.replace(tmp, self._journal_path)
        self._written = len(compacted)

    # -- the coordinator surface -------------------------------------------
    def renew(
        self, host_id: str, demands: Optional[dict] = None
    ) -> dict:
        """Leader: delegate to the inner coordinator, JOURNAL the grant
        batch, then return it — a grant is never live on a host without
        being durable first, so a failover replay can only be a
        superset of what hosts actually hold (over-admission bounded by
        the lease window, never unbounded).  Follower: refuse with the
        leader hint.  Killed: UNAVAILABLE."""
        now = self._clock()
        with self._lock:
            self._ensure_leader(now)
            leases = self._coordinator.renew(host_id, demands)
            self._append({
                "kind": "grants",
                "term": self.term,
                "host": str(host_id),
                "wall_epoch": time.time(),
                "leases": {
                    tenant: {
                        "rate": lease.rate_rps,
                        "demand": float((demands or {}).get(tenant, 0.0)),
                        "expires_at": lease.expires_at,
                    }
                    for tenant, lease in leases.items()
                },
            })
            self.renewals += 1
        return leases

    def is_leader(self) -> bool:
        now = self._clock()
        with self._lock:
            if self.killed:
                return False
            current = self._read_leader()
            return (
                current is not None
                and current.get("leader") == self.replica_id
                and float(current.get("expires_at", 0.0)) > now
            )

    # -- scripted failure ---------------------------------------------------
    def kill(self) -> None:
        """The scripted coordinator crash: refuse everything, drop the
        journal handle.  The leader lease is deliberately NOT released
        — a crashed leader cannot clean up after itself; failover must
        ride the lease expiry, which is exactly what the drill
        measures."""
        with self._lock:
            self.killed = True
            self._coordinator = None
            if self._f is not None:
                self._f.close()
                self._f = None
        telemetry_mod.current().event(
            "cluster.coordinator_killed", replica=self.replica_id,
        )

    def restart(self) -> "CoordinatorReplica":
        with self._lock:
            self.killed = False
        telemetry_mod.current().event(
            "cluster.coordinator_restarted", replica=self.replica_id,
        )
        return self

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def stats(self) -> dict:
        with self._lock:
            return {
                "replica_id": self.replica_id,
                "killed": self.killed,
                "term": self.term,
                "elections": self.elections,
                "renewals": self.renewals,
                "leader_ttl_s": self.leader_ttl_s,
                "lease_ttl_s": self.lease_ttl_s,
            }


class ReplicatedQuotaCoordinator:
    """Host-facing client over N :class:`CoordinatorReplica`\\ s.

    Duck-types ``QuotaCoordinator`` (``renew`` + ``lease_ttl_s``), so
    ``LeaseClient``/``attach_lease_client`` compose unchanged.  A
    renewal walks the replica set starting from the last known leader,
    follows :class:`NotLeaderError` hints, and surfaces UNAVAILABLE
    only when every replica refused — the lease client then degrades
    to the last lease, the standing partition contract."""

    def __init__(self, replicas: List[CoordinatorReplica]):
        if not replicas:
            raise ValueError(
                "ReplicatedQuotaCoordinator needs at least one replica"
            )
        ttls = {r.lease_ttl_s for r in replicas}
        if len(ttls) != 1:
            raise ValueError(
                f"replicas disagree on lease_ttl_s: {sorted(ttls)} — "
                "one replica set, one TTL"
            )
        self.replicas = list(replicas)
        self.lease_ttl_s = replicas[0].lease_ttl_s
        self._lock = sanitizers.tracked(
            threading.Lock(), "cluster.replicated_coordinator"
        )
        self._leader_id: Optional[str] = None
        self.renewals = 0
        self.failovers = 0

    def _attempt_order(self) -> List[CoordinatorReplica]:
        with self._lock:
            leader_id = self._leader_id
        ordered = sorted(
            self.replicas,
            key=lambda r: (r.replica_id != leader_id, r.replica_id),
        )
        return ordered

    def renew(
        self, host_id: str, demands: Optional[dict] = None
    ) -> dict:
        tel = telemetry_mod.current()
        errors: List[str] = []
        remaining = self._attempt_order()
        while remaining:
            replica = remaining.pop(0)
            try:
                # The partition seam, PER REPLICA: a fault here is this
                # host losing its path to this one replica — the walk
                # continues; every replica faulted is the full
                # partition (docs/robustness.md).
                chaos_mod.maybe_fail(
                    "cluster.lease",
                    host=str(host_id), replica=replica.replica_id,
                )
                leases = replica.renew(host_id, demands)
            except NotLeaderError as exc:
                errors.append(
                    f"{replica.replica_id}: not leader "
                    f"(hint: {exc.leader_hint})"
                )
                if exc.leader_hint is not None:
                    # Follow the hint: try the named leader next.
                    hinted = next(
                        (r for r in remaining
                         if r.replica_id == exc.leader_hint),
                        None,
                    )
                    if hinted is not None:
                        remaining.remove(hinted)
                        remaining.insert(0, hinted)
                continue
            except Exception as exc:  # noqa: BLE001 — walk on
                errors.append(
                    f"{replica.replica_id}: "
                    f"{type(exc).__name__}: {exc}"[:120]
                )
                continue
            with self._lock:
                previous = self._leader_id
                self._leader_id = replica.replica_id
                self.renewals += 1
                if previous is not None and \
                        previous != replica.replica_id:
                    self.failovers += 1
                    failover_from = previous
                else:
                    failover_from = None
            tel.counter("cluster_renewals_total").inc()
            if failover_from is not None:
                tel.counter("cluster_failovers_total").inc()
                tel.event(
                    "cluster.coordinator_failover",
                    new_leader=replica.replica_id,
                    old_leader=failover_from,
                )
            return leases
        raise RuntimeError(
            "UNAVAILABLE: no coordinator replica would renew "
            f"({'; '.join(errors)})"
        )

    def leader(self) -> Optional[str]:
        with self._lock:
            return self._leader_id

    def stats(self) -> dict:
        with self._lock:
            leader_id = self._leader_id
        return {
            "leader": leader_id,
            "renewals": self.renewals,
            "failovers": self.failovers,
            "lease_ttl_s": self.lease_ttl_s,
            "replicas": [r.stats() for r in self.replicas],
        }
