"""Cluster control plane: coordination, discovery, distribution.

The serving tier (``photon_ml_tpu/serving``) scales out to N hosts
behind a :class:`~photon_ml_tpu.serving.fleet.FleetRouter`, but three
pieces of its control plane assumed a single machine or a shared
filesystem.  This package removes those assumptions:

- **Replicated quota coordination** (``coordination.py``) — the
  :class:`~photon_ml_tpu.serving.fleet.QuotaCoordinator` becomes N
  journal-backed :class:`CoordinatorReplica`\\ s under a leader lease;
  a coordinator kill fails over within one lease TTL, and the grant
  journal replay bounds over-admission to one lease window.
- **Service discovery** (``membership.py``) — hosts register with a
  :class:`MembershipRegistry` and heartbeat to stay in it; a
  :class:`MembershipWatcher` converges the FleetRouter (and the
  FleetAggregator's scrape set) onto the discovered membership, so
  ``join`` and ``drain`` are registry operations, not config edits.
- **Model distribution** (``distribution.py``) — a cold host pulls the
  newest committed snapshot publication over HTTP
  (:func:`cold_start`), verifies every byte against the manifest
  checksums, and catches up by deltas via :class:`RemoteApplier` with
  per-subscriber acks; no shared filesystem on the serving path.

``python -m photon_ml_tpu.cluster --selfcheck`` replays the 3-host
drill (coordinator kill, host join + drain, publication cold start)
under open-loop load — docs/serving.md "Cluster".
"""

from photon_ml_tpu.cluster.coordination import (  # noqa: F401
    CoordinatorReplica,
    NotLeaderError,
    ReplicatedQuotaCoordinator,
)
from photon_ml_tpu.cluster.distribution import (  # noqa: F401
    FetchError,
    PublicationClient,
    PublicationServer,
    RemoteApplier,
    cold_start,
)
from photon_ml_tpu.cluster.membership import (  # noqa: F401
    HeartbeatAgent,
    MembershipRegistry,
    MembershipWatcher,
    RegistryClient,
)

__all__ = [
    "CoordinatorReplica",
    "FetchError",
    "HeartbeatAgent",
    "MembershipRegistry",
    "MembershipWatcher",
    "NotLeaderError",
    "PublicationClient",
    "PublicationServer",
    "RegistryClient",
    "RemoteApplier",
    "ReplicatedQuotaCoordinator",
    "cold_start",
]
