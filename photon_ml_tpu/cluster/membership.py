"""Service discovery: a registration/heartbeat membership registry.

PR 15's ``FleetRouter`` takes a STATIC host list; PR 17's
``FleetAggregator`` scrapes a fixed dict.  Real fleets churn: hosts
boot, die, drain, and come back, and nobody restarts the front door for
any of it.  This module is the discovery plane that replaces both
static lists:

- :class:`MembershipRegistry` — the source of truth: hosts
  ``register`` at startup (id + serving URL + optional metrics URL),
  ``heartbeat`` every interval, and are EXPIRED from the member set
  after ``heartbeat_ttl_s`` without a beat (expiry-on-read: the member
  view is correct the instant it is read, no sweeper thread to race).
  ``drain``/``leave`` are first-class: a draining member stays visible
  (so the router can finish its in-flight work) but is marked, and a
  left member disappears immediately.  ``serve()`` exposes the whole
  surface over HTTP so registration crosses machines.
- :class:`RegistryClient` — one client for both transports: hand it a
  registry OBJECT (in-process: tests, selfcheck, single box) or a base
  URL string (HTTP: real fleets).  The protocol is identical — the
  discovery algebra does not change when it crosses a socket
  (QuotaCoordinator's design note, one tier down).
- :class:`HeartbeatAgent` — the host-side beat loop: registers, beats
  every ``interval_s`` through the ``cluster.heartbeat`` chaos seam,
  and RE-REGISTERS automatically when the registry answers "unknown"
  (a registry restart or an expiry during a stall must not strand a
  live host — the agent heals its own membership).
- :class:`MembershipWatcher` — closes the loop to PR 15/17: diffs the
  discovered member set against a live :class:`FleetRouter`'s hosts
  and calls ``router.join`` / ``router.drain`` to converge, and feeds
  the same membership to ``FleetAggregator.sync_membership`` so the
  ops plane follows the fleet instead of a config file.

Metric family: ``cluster_*`` (docs/telemetry.md).  Chaos seam:
``cluster.heartbeat`` (a fault is a lost beat — enough of them expires
the host, the watcher drains it from the router, and the agent's
re-register brings it back).  docs/serving.md "Cluster" has the
membership + failover diagram and the TTL math.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.serving.fleet import _http_json


class MembershipRegistry:
    """The authoritative member set, with expiry-on-read.

    A member is ``{host_id, url, metrics_url, state, registered_wall_epoch,
    heartbeats}``; ``state`` is ``"alive"`` or ``"draining"``.  Liveness
    bookkeeping rides the injectable monotonic ``clock`` (never wall
    time — a clock step must not expire the fleet)."""

    def __init__(
        self,
        heartbeat_ttl_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if heartbeat_ttl_s <= 0:
            raise ValueError(
                f"heartbeat_ttl_s must be > 0, got {heartbeat_ttl_s}"
            )
        self.heartbeat_ttl_s = float(heartbeat_ttl_s)
        self._clock = clock
        self._lock = sanitizers.tracked(
            threading.Lock(), "cluster.membership"
        )
        #: host_id -> member record (plus internal ``last_beat_t``).
        self._members: Dict[str, dict] = {}
        self._server = None
        self._thread: Optional[threading.Thread] = None

    # -- the protocol ------------------------------------------------------
    def register(
        self,
        host_id: str,
        url: str,
        metrics_url: Optional[str] = None,
    ) -> dict:
        """Admit (or re-admit) a host.  Registering an id that is
        already a member REPLACES its record — the newest registration
        wins, which is what a restarted host needs."""
        host_id = str(host_id)
        now = self._clock()
        with self._lock:
            rejoin = host_id in self._members
            self._members[host_id] = {
                "host_id": host_id,
                "url": str(url).rstrip("/"),
                "metrics_url": (
                    str(metrics_url).rstrip("/") if metrics_url else None
                ),
                "state": "alive",
                "registered_wall_epoch": time.time(),
                "heartbeats": 0,
                "last_beat_t": now,
            }
            count = len(self._members)
        tel = telemetry_mod.current()
        tel.counter("cluster_joins_total").inc()
        tel.gauge("cluster_members_count").set(count)
        tel.event(
            "cluster.member_registered",
            host=host_id, url=url, rejoin=rejoin,
        )
        return self._public(self._members[host_id])

    def heartbeat(self, host_id: str) -> bool:
        """Refresh a member's liveness.  Returns ``False`` for an id
        that is not (or no longer) a member — the caller must
        re-register; beating cannot resurrect an expired host because
        its registration record (URL, metrics URL) is gone."""
        host_id = str(host_id)
        now = self._clock()
        with self._lock:
            self._expire_locked(now)
            member = self._members.get(host_id)
            if member is None:
                return False
            member["last_beat_t"] = now
            member["heartbeats"] += 1
        telemetry_mod.current().counter("cluster_heartbeats_total").inc()
        return True

    def drain(self, host_id: str) -> bool:
        """Mark a member draining: still visible (the router needs to
        see it to drain it gracefully), no longer a routing target once
        the watcher converges.  Returns ``False`` for an unknown id."""
        with self._lock:
            member = self._members.get(str(host_id))
            if member is None:
                return False
            member["state"] = "draining"
        tel = telemetry_mod.current()
        tel.counter("cluster_drains_total").inc()
        tel.event("cluster.member_draining", host=str(host_id))
        return True

    def leave(self, host_id: str) -> bool:
        """Remove a member immediately (the graceful-shutdown path —
        a leaving host should not wait out its own TTL)."""
        with self._lock:
            member = self._members.pop(str(host_id), None)
            count = len(self._members)
        if member is None:
            return False
        tel = telemetry_mod.current()
        tel.counter("cluster_leaves_total").inc()
        tel.gauge("cluster_members_count").set(count)
        tel.event("cluster.member_left", host=str(host_id))
        return True

    def members(self) -> Dict[str, dict]:
        """The CURRENT member set (expired hosts removed as a side
        effect of reading — the view is correct at read time)."""
        now = self._clock()
        with self._lock:
            self._expire_locked(now)
            return {
                hid: self._public(m) for hid, m in self._members.items()
            }

    def _expire_locked(self, now: float) -> None:
        # Caller holds self._lock.
        expired = [
            hid for hid, m in self._members.items()
            if now - m["last_beat_t"] > self.heartbeat_ttl_s
        ]
        if not expired:
            return
        for hid in expired:
            del self._members[hid]
        count = len(self._members)
        tel = telemetry_mod.current()
        tel.counter("cluster_expirations_total").inc(len(expired))
        tel.gauge("cluster_members_count").set(count)
        for hid in expired:
            tel.event(
                "cluster.member_expired",
                host=hid, ttl_s=self.heartbeat_ttl_s,
            )

    @staticmethod
    def _public(member: dict) -> dict:
        return {k: v for k, v in member.items() if k != "last_beat_t"}

    def stats(self) -> dict:
        with self._lock:
            states = [m["state"] for m in self._members.values()]
        return {
            "heartbeat_ttl_s": self.heartbeat_ttl_s,
            "members": len(states),
            "alive": states.count("alive"),
            "draining": states.count("draining"),
        }

    # -- HTTP --------------------------------------------------------------
    def serve(
        self, host: str = "127.0.0.1", port: int = 0
    ) -> "MembershipRegistry":
        """Expose the registry over HTTP on a daemon thread (POST
        ``/register`` ``/heartbeat`` ``/drain`` ``/leave``, GET
        ``/members`` ``/healthz``).  ``port=0`` binds an ephemeral
        port; read :attr:`base_url` back."""
        if self._server is not None:
            return self
        server = _RegistryServer((host, port), _RegistryHandler)
        server.registry = self
        self._server = server
        self._thread = threading.Thread(
            target=server.serve_forever,
            name="cluster-registry-http", daemon=True,
        )
        self._thread.start()
        return self

    @property
    def base_url(self) -> str:
        if self._server is None:
            raise RuntimeError("registry is not serving (call serve())")
        h, p = self._server.server_address[:2]
        return f"http://{h}:{p}"

    def close(self, timeout: float = 5.0) -> None:
        server, thread = self._server, self._thread
        self._server, self._thread = None, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=timeout)


class _RegistryServer(ThreadingHTTPServer):
    daemon_threads = True
    registry: MembershipRegistry


class _RegistryHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        pass  # request logging rides telemetry, not stderr

    def _send_json(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 — stdlib casing
        registry = self.server.registry
        if self.path == "/members":
            self._send_json(200, {"members": registry.members()})
        elif self.path == "/healthz":
            self._send_json(200, {"status": "ok", **registry.stats()})
        else:
            self._send_json(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 — stdlib casing
        registry = self.server.registry
        n = int(self.headers.get("Content-Length") or 0)
        try:
            payload = json.loads(self.rfile.read(n) or b"{}")
        except json.JSONDecodeError as exc:
            self._send_json(400, {"error": f"bad JSON body: {exc}"})
            return
        host_id = payload.get("host_id")
        if not host_id:
            self._send_json(400, {"error": "host_id is required"})
            return
        if self.path == "/register":
            member = registry.register(
                host_id, payload.get("url", ""),
                metrics_url=payload.get("metrics_url"),
            )
            self._send_json(200, {"member": member})
        elif self.path == "/heartbeat":
            ok = registry.heartbeat(host_id)
            # 410 Gone = "re-register": the contract the agent heals on.
            self._send_json(
                200 if ok else 410,
                {"ok": ok, "host_id": host_id},
            )
        elif self.path == "/drain":
            ok = registry.drain(host_id)
            self._send_json(200 if ok else 404, {"ok": ok})
        elif self.path == "/leave":
            ok = registry.leave(host_id)
            self._send_json(200 if ok else 404, {"ok": ok})
        else:
            self._send_json(404, {"error": f"no route {self.path}"})


class RegistryClient:
    """One membership client for both transports.

    ``registry`` is either a :class:`MembershipRegistry` (in-process)
    or a base-URL string (HTTP).  Methods mirror the registry surface;
    HTTP transport failures raise (the caller — usually the
    :class:`HeartbeatAgent` — owns the retry/degrade policy)."""

    def __init__(self, registry, timeout_s: float = 5.0):
        self.timeout_s = float(timeout_s)
        if isinstance(registry, str):
            self._url: Optional[str] = registry.rstrip("/")
            self._local: Optional[MembershipRegistry] = None
        else:
            self._url = None
            self._local = registry

    def _post(self, route: str, payload: dict) -> tuple[int, dict]:
        return _http_json(
            "POST", self._url + route, payload, timeout_s=self.timeout_s
        )

    def register(
        self, host_id: str, url: str, metrics_url: Optional[str] = None
    ) -> dict:
        if self._local is not None:
            return self._local.register(host_id, url, metrics_url)
        status, obj = self._post("/register", {
            "host_id": host_id, "url": url, "metrics_url": metrics_url,
        })
        if status != 200:
            raise RuntimeError(
                f"register({host_id}) -> HTTP {status}: {obj}"
            )
        return obj["member"]

    def heartbeat(self, host_id: str) -> bool:
        if self._local is not None:
            return self._local.heartbeat(host_id)
        status, obj = self._post("/heartbeat", {"host_id": host_id})
        if status not in (200, 410):
            raise RuntimeError(
                f"heartbeat({host_id}) -> HTTP {status}: {obj}"
            )
        return bool(obj.get("ok"))

    def drain(self, host_id: str) -> bool:
        if self._local is not None:
            return self._local.drain(host_id)
        _status, obj = self._post("/drain", {"host_id": host_id})
        return bool(obj.get("ok"))

    def leave(self, host_id: str) -> bool:
        if self._local is not None:
            return self._local.leave(host_id)
        _status, obj = self._post("/leave", {"host_id": host_id})
        return bool(obj.get("ok"))

    def members(self) -> Dict[str, dict]:
        if self._local is not None:
            return self._local.members()
        status, obj = _http_json(
            "GET", self._url + "/members", timeout_s=self.timeout_s
        )
        if status != 200:
            raise RuntimeError(f"members() -> HTTP {status}: {obj}")
        return obj["members"]


class HeartbeatAgent:
    """The host-side membership loop: register once, then beat.

    A missed beat (registry down, network fault, the
    ``cluster.heartbeat`` chaos seam) only increments a failure
    counter — the host keeps serving; liveness is the REGISTRY's
    verdict, not the agent's.  A beat answered "unknown" re-registers
    on the next cycle, so an expiry during a stall (or a registry
    restart that lost the member set) heals without operator action.
    ``interval_s`` defaults to half the registry TTL so one missed
    beat never expires a healthy host."""

    def __init__(
        self,
        registry,
        host_id: str,
        url: str,
        metrics_url: Optional[str] = None,
        interval_s: Optional[float] = None,
        heartbeat_ttl_s: Optional[float] = None,
    ):
        self.client = (
            registry if isinstance(registry, RegistryClient)
            else RegistryClient(registry)
        )
        self.host_id = str(host_id)
        self.url = url
        self.metrics_url = metrics_url
        if interval_s is None:
            ttl = (
                heartbeat_ttl_s
                if heartbeat_ttl_s is not None
                else getattr(
                    self.client._local, "heartbeat_ttl_s", 2.0
                )
            )
            interval_s = ttl / 2.0
        self.interval_s = float(interval_s)
        self.beats = 0
        self.beat_failures = 0
        self.reregisters = 0
        self._registered = False
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat_once(self) -> bool:
        """One register-or-beat cycle; returns True when the registry
        acknowledged this host as a live member."""
        tel = telemetry_mod.current()
        try:
            # The liveness seam: a fault here is this host's beat lost
            # on the wire (docs/robustness.md).
            chaos_mod.maybe_fail("cluster.heartbeat", host=self.host_id)
            if not self._registered:
                self.client.register(
                    self.host_id, self.url, self.metrics_url
                )
                self._registered = True
                return True
            if self.client.heartbeat(self.host_id):
                self.beats += 1
                return True
            # Known protocol verdict: the registry dropped us (expiry
            # or restart) — re-register on the NEXT cycle, so a flappy
            # registry sees beats, not a register storm.
            self._registered = False
            self.reregisters += 1
            tel.counter("cluster_reregister_total").inc()
            tel.event(
                "cluster.agent_reregistering", host=self.host_id,
            )
            return False
        except Exception as exc:  # noqa: BLE001 — degrade, never die
            self.beat_failures += 1
            tel.counter("cluster_heartbeat_failures_total").inc()
            tel.event(
                "cluster.heartbeat_failed",
                host=self.host_id,
                error=f"{type(exc).__name__}: {exc}"[:200],
            )
            return False

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "HeartbeatAgent":
        if self._thread is None:
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._loop,
                name=f"cluster-heartbeat-{self.host_id}", daemon=True,
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        # First beat immediately: a host should be discoverable before
        # its first interval elapses, not after.
        while True:
            self.beat_once()
            if self._stop_evt.wait(self.interval_s):
                return

    def stop(self, timeout: float = 5.0, leave: bool = True) -> None:
        self._stop_evt.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=timeout)
        if leave and self._registered:
            try:
                self.client.leave(self.host_id)
            except Exception:  # noqa: BLE001 — expiry will catch up
                pass
            self._registered = False

    def stats(self) -> dict:
        return {
            "host_id": self.host_id,
            "registered": self._registered,
            "beats": self.beats,
            "beat_failures": self.beat_failures,
            "reregisters": self.reregisters,
        }


class MembershipWatcher:
    """Converge a live :class:`FleetRouter` (and optionally a
    :class:`FleetAggregator`) onto the discovered member set.

    Each ``poll_once``: read ``members()``, then

    - a member URL the router does not route yet -> ``router.join``
      (the host enters as down-until-ready, so a warming host never
      costs a request);
    - a routed URL whose member is gone or draining -> ``router.drain``
      (graceful: in-flight completes; drain timeouts are retried next
      poll);
    - the aggregator, when given, gets the full
      ``{host_id: metrics_url}`` view via ``sync_membership`` so ops
      series follow the fleet (stale hosts marked, then dropped).

    A registry read failure keeps the LAST converged state — the same
    degrade-don't-die contract as the lease client; discovery going
    dark must not drain a healthy fleet."""

    def __init__(
        self,
        registry,
        router,
        aggregator=None,
        interval_s: float = 0.25,
        drain_timeout_s: float = 5.0,
    ):
        self.client = (
            registry if isinstance(registry, RegistryClient)
            else RegistryClient(registry)
        )
        self.router = router
        self.aggregator = aggregator
        self.interval_s = float(interval_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.polls = 0
        self.poll_failures = 0
        self.joined = 0
        self.drained = 0
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> bool:
        """One convergence round; returns False when the registry read
        failed (last converged state kept)."""
        tel = telemetry_mod.current()
        try:
            members = self.client.members()
        except Exception as exc:  # noqa: BLE001 — degrade, never die
            self.poll_failures += 1
            tel.event(
                "cluster.watcher_poll_failed",
                error=f"{type(exc).__name__}: {exc}"[:200],
            )
            return False
        self.polls += 1
        target_urls = {
            m["url"] for m in members.values() if m["state"] == "alive"
        }
        routed = {
            h["url"]: (h["hid"], h["state"])
            for h in self.router.healthz()["hosts"]
        }
        for url in sorted(target_urls):
            hid_state = routed.get(url)
            if hid_state is None or hid_state[1] == "removed":
                self.router.join(url)
                self.joined += 1
        for url, (hid, state) in routed.items():
            if url in target_urls or state in ("removed", "draining"):
                continue
            self.router.drain(hid, timeout_s=self.drain_timeout_s)
            self.drained += 1
        if self.aggregator is not None:
            self.aggregator.sync_membership({
                hid: (m["metrics_url"] or m["url"])
                for hid, m in members.items()
            })
        return True

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "MembershipWatcher":
        if self._thread is None:
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._loop,
                name="cluster-membership-watcher", daemon=True,
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while True:
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the watcher must survive
                pass
            if self._stop_evt.wait(self.interval_s):
                return

    def stop(self, timeout: float = 5.0) -> None:
        self._stop_evt.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=timeout)

    def stats(self) -> dict:
        return {
            "polls": self.polls,
            "poll_failures": self.poll_failures,
            "joined": self.joined,
            "drained": self.drained,
        }
