"""Legacy GLM training driver.

The analogue of the reference's ``com.linkedin.photon.ml.Driver`` ("GLMDriver"
— [CONFIRMED-BASELINE], SURVEY.md §2, §3.1): the end-to-end single-GLM
pipeline

    read → index → summarize → normalize → train over a regularization-weight
    grid (warm-started) → validate → select best → write model(s)

run as stages with artifacts written to the output directory.  Where the
reference launches a Spark job per stage, here ingest happens on the host and
every training stage is one jitted TPU program; with >1 device the grid runs
data-parallel over the mesh (parallel/distributed.py).

Usage:
    python -m photon_ml_tpu.drivers.glm_driver \
        --train-data a1a --task logistic --reg-type l2 \
        --reg-weights 0.1,1,10 --output-dir /tmp/out
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.data import libsvm
from photon_ml_tpu.data.dataset import make_glm_data
from photon_ml_tpu.data.index_map import INTERCEPT_KEY, IndexMap
from photon_ml_tpu.data.normalization import (
    NormalizationContext,
    NormalizationType,
    build_normalization,
)
from photon_ml_tpu.data.stats import summarize
from photon_ml_tpu.evaluation.evaluators import (
    default_evaluator_for_task,
    get_evaluator,
)
from photon_ml_tpu.io.model_store import save_glm_model
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.optim.problem import (
    GlmOptimizationConfig,
    GlmOptimizationProblem,
    OptimizerConfig,
    OptimizerType,
)
from photon_ml_tpu.optim.regularization import RegularizationContext, RegularizationType
from photon_ml_tpu.utils.compile_cache import (
    add_compile_cache_arg,
    enable_from_args,
    publish_cache_metrics,
)
from photon_ml_tpu.utils.logging import PhotonLogger
from photon_ml_tpu.utils.timer import Timer
from photon_ml_tpu.utils.tracker import OptimizationStatesTracker


def build_arg_parser() -> argparse.ArgumentParser:
    """CLI surface mirroring the reference Driver's ``Params``."""
    p = argparse.ArgumentParser(
        prog="glm_driver", description="TPU-native GLM training driver"
    )
    p.add_argument("--train-data", required=True, help="LIBSVM training file")
    p.add_argument("--validate-data", help="LIBSVM validation file (optional)")
    p.add_argument("--output-dir", required=True)
    p.add_argument(
        "--task",
        default="logistic",
        help="logistic | linear | poisson | smoothed_hinge (or reference "
        "TaskType names like LOGISTIC_REGRESSION)",
    )
    p.add_argument(
        "--optimizer", default="lbfgs", choices=[t.value for t in OptimizerType]
    )
    p.add_argument(
        "--solver",
        help="registered solver name (photon_ml_tpu/solvers): lbfgs | "
        "owlqn | tron | spg | admm | block_cd.  Unset keeps the historical "
        "routing (bounds → spg, any L1 → owlqn, else --optimizer) bitwise. "
        "Host-kind solvers (admm, block_cd) run sharded: over the "
        "--data-parallel mesh when available, else over --solver-shards "
        "logical shards on one device",
    )
    p.add_argument(
        "--solver-shards",
        type=int,
        default=0,
        help="logical shard count for host-kind solvers without a mesh "
        "(0 = auto: 2, or the solver_options 'shards' knob)",
    )
    p.add_argument(
        "--solver-option",
        action="append",
        default=[],
        metavar="KEY=VALUE",
        help="solver-specific knob (repeatable), e.g. --solver-option "
        "rho=1.0 --solver-option n_blocks=8 (see docs/solvers.md)",
    )
    p.add_argument(
        "--reg-type",
        default="none",
        choices=[t.value for t in RegularizationType],
    )
    p.add_argument("--reg-weights", default="0.0", help="comma-separated λ grid")
    p.add_argument("--elastic-net-alpha", type=float, default=0.5)
    p.add_argument(
        "--normalization",
        default="none",
        choices=[t.value for t in NormalizationType],
    )
    p.add_argument("--max-iters", type=int, default=100)
    p.add_argument("--tolerance", type=float, default=1e-7)
    p.add_argument(
        "--coefficient-bounds",
        help="JSON file mapping feature key -> [lower, upper] box "
        "constraints (the reference's constraint map); unlisted features "
        "are unconstrained",
    )
    p.add_argument("--intercept", action="store_true", default=True)
    p.add_argument("--no-intercept", dest="intercept", action="store_false")
    p.add_argument("--compute-variances", action="store_true")
    p.add_argument("--evaluator", help="AUC | RMSE | ... (default: per task)")
    p.add_argument(
        "--output-mode",
        default="best",
        choices=["best", "all"],
        help="write only the selected model or every grid point "
        "(the reference's ModelOutputMode)",
    )
    p.add_argument("--n-features", type=int, help="fixed feature-space width")
    p.add_argument(
        "--resume",
        action="store_true",
        help="continue from the λ-grid checkpoint in the output dir "
        "(skips already-solved weights, keeps the warm-start chain)",
    )
    p.add_argument(
        "--initial-model",
        help="saved model Avro to warm-start the grid from (the reference's "
        "incremental training)",
    )
    p.add_argument(
        "--data-parallel",
        choices=["off", "auto"],
        default="off",
        help="auto: with >1 device, shard rows over a mesh and run the "
        "whole λ grid with one fused psum per objective evaluation (the "
        "reference's treeAggregate loop on ICI)",
    )
    p.add_argument(
        "--training-report",
        action="store_true",
        help="write report.json + report.html to the output dir: "
        "per-lambda convergence traces, bootstrap CIs on the validation "
        "metric, Hosmer-Lemeshow calibration (logistic), and "
        "|coef|*std feature importance (the reference's old diagnostics "
        "package, SURVEY.md 5.1)",
    )
    p.add_argument(
        "--max-retries",
        type=int,
        default=0,
        help="automatic recovery from TRANSIENT failures (lost device, "
        "transport drop, preemption): re-enter training up to this many "
        "times, resuming from the λ-grid checkpoint so finished work is "
        "never repeated (the Spark cluster manager's task-retry analogue). "
        "0 disables",
    )
    p.add_argument(
        "--retry-backoff",
        type=float,
        default=5.0,
        help="initial seconds between retries (exponential, x2 per "
        "attempt, capped at 300s)",
    )
    p.add_argument(
        "--precise-accumulation",
        action="store_true",
        help="accumulate the objective VALUE in float64 (the reference's "
        "Breeze f64 end-to-end; here f64 on the value reduction only — "
        "gradient sums stay f32 tree reductions). At 1e9 rows the f32 "
        "value rounds at ~1e-7 relative, competing with tight convergence "
        "tolerances. Costs one emulated-f64 pass per evaluation on TPU",
    )
    p.add_argument(
        "--stream-chunk-rows",
        type=int,
        default=0,
        help="out-of-core training: keep the dataset in host RAM as chunks "
        "of this many rows and stream them through HBM per objective "
        "evaluation (double-buffered device_put). 0 = device-resident. "
        "Datasets larger than HBM train this way; L-BFGS, OWL-QN "
        "(L1/elastic-net) and smooth TRON all stream",
    )
    p.add_argument(
        "--stream-storage-dir",
        help="with --stream-chunk-rows: spill the chunk store to .npy "
        "files in this directory and train from disk-backed (memmap) "
        "leaves — host RAM stops bounding the trainable size, disk does "
        "(the reference's MEMORY_AND_DISK RDD persistence)",
    )
    p.add_argument(
        "--stream-prefetch-depth",
        type=int,
        default=2,
        help="with --stream-chunk-rows: how many chunks the background "
        "ingest pipeline keeps in flight, and how many dispatched chunk "
        "programs the consumer runs ahead of its carry sync (HBM holds "
        "at most 2x this many chunks). 2 = the classic double buffer; 1 "
        "serializes transfer and compute (measurement baseline)",
    )
    p.add_argument(
        "--stream-chunk-fuse",
        type=int,
        default=1,
        help="with --stream-chunk-rows: fold this many chunks into one "
        "device dispatch (an in-program lax.scan over a stacked "
        "super-chunk) — amortizes per-dispatch overhead when chunks are "
        "small. Single-device only; 1 disables fusion",
    )
    p.add_argument(
        "--stream-batch-linesearch",
        choices=["on", "off"],
        default="on",
        help="with --stream-chunk-rows: evaluate a bracket of line-search "
        "candidate steps in ONE streamed pass (identical trial sequence, "
        "roughly half the passes per solve). 'off' streams one trial per "
        "pass",
    )
    p.add_argument(
        "--stream-compress",
        choices=["off", "lossless", "fp16", "int8"],
        default="off",
        help="with --stream-chunk-rows: compressed chunk wire formats — "
        "chunks cross the host->device link encoded (delta/downcast "
        "index blocks, {0,1} bitmaps, fp16/int8 feature quantization) "
        "and are dequantized ON DEVICE inside the per-chunk program. "
        "'lossless' keeps every solve bitwise identical to the raw "
        "stream; fp16/int8 add bounded quantization error for a bigger "
        "wire win. Single-host only",
    )
    p.add_argument(
        "--stream-hot-budget-mb",
        type=float,
        default=0.0,
        help="with --stream-chunk-rows: keep up to this many MB of "
        "(wire) chunk buffers RESIDENT in HBM across passes — the "
        "importance-aware working-set cache: admission/eviction is "
        "re-scored each pass from per-chunk gradient contributions, hot "
        "chunks skip pack+transfer entirely. Bitwise neutral; "
        "single-device only. 0 disables",
    )
    p.add_argument(
        "--telemetry",
        choices=["on", "off"],
        default="on",
        help="unified telemetry (events.jsonl + trace.json + metrics.json "
        "in the output dir, summary in the log). 'off' reduces every "
        "instrumented site to one branch",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        help="expose the live ops plane on this port while the run is "
        "in flight (/metrics Prometheus exposition, /snapshot JSON, "
        "/healthz); 0 binds an ephemeral port; omit to disable",
    )
    p.add_argument(
        "--metrics-interval-s",
        type=float,
        default=1.0,
        help="interval of the metrics_ts.jsonl time-series sampler "
        "(live registry snapshots in the output dir; 0 disables)",
    )
    add_compile_cache_arg(p)
    return p


def make_fit_once(
    X_train,
    y_train,
    X_val,
    y_val,
    *,
    task: str = "logistic",
    reg_type: str = "l2",
    elastic_net_alpha: float = 0.5,
    optimizer: str = "lbfgs",
    max_iters: int = 100,
    tolerance: float = 1e-8,
    suite=None,
    val_weights=None,
    solver: Optional[str] = None,
    solver_options: tuple = (),
):
    """Reusable single-fit entry for the tuning orchestrator
    (photon_ml_tpu/tuning/): ``fit_once(params, resource, warm_start) ->
    (metric, metrics, coefficients)``.

    ``params[0]`` is the regularization weight λ.  ``resource`` > 0 caps
    the optimizer's iteration budget (an ASHA rung's resource; 0 uses
    ``max_iters``), and ``warm_start`` seeds the solve — the executor
    chains a promoted trial from its own previous rung and a fresh trial
    from the nearest completed λ's coefficients, the λ-path warm-start
    pattern this driver's own grid loop uses.  Data uploads once; every
    trial at one rung level shares one compiled solver (λ, w0 are traced
    arguments), so a parallel sweep adds no recompiles.

    Exposes ``fit_once.suite`` and ``fit_once.larger_is_better`` so
    callers wire the orchestrator's direction without re-deriving it.
    """
    import threading

    from photon_ml_tpu.evaluation.suite import EvaluationSuite

    if suite is None:
        from photon_ml_tpu.ops import losses as losses_lib

        suite = EvaluationSuite.for_task(losses_lib.get(task).name)
    from photon_ml_tpu.solvers import registry as solver_registry

    host_kind = (
        solver is not None
        and solver_registry.get(solver).kind == "host"
    )
    if host_kind and hasattr(X_train, "todense"):
        # Host-kind solvers shard dense row blocks; tuning-scale designs
        # densify cheaply (the distributed grid path takes sparse).
        X_train = np.asarray(X_train.todense(), np.float32)
    data = make_glm_data(X_train, y_train)
    y_val = np.asarray(y_val)
    problems: dict[int, GlmOptimizationProblem] = {}
    sharded_solves: dict[int, object] = {}
    lock = sanitizers.tracked(threading.Lock(), "glm.problem_cache")

    def _problem(iters: int) -> GlmOptimizationProblem:
        # One problem (= one jitted solver) per distinct iteration
        # budget, shared across trials and threads.
        with lock:
            p = problems.get(iters)
            if p is None:
                p = problems[iters] = GlmOptimizationProblem(
                    task,
                    GlmOptimizationConfig(
                        optimizer=OptimizerConfig(
                            optimizer=OptimizerType(optimizer),
                            max_iters=iters,
                            tolerance=tolerance,
                            solver=solver,
                            solver_options=tuple(solver_options),
                        ),
                        regularization=RegularizationContext(
                            RegularizationType(reg_type), elastic_net_alpha
                        ),
                    ),
                )
            return p

    def _sharded_solve(iters: int):
        # Host-kind counterpart of the per-iters problem cache: one
        # bound solver (logical shards, one compiled step program) per
        # iteration budget.
        from photon_ml_tpu.solvers import sharded as solvers_sharded

        problem = _problem(iters)
        with lock:
            s = sharded_solves.get(iters)
            if s is None:
                n_shards = solvers_sharded.resolve_shard_count(
                    problem.config.optimizer
                )
                dist = solvers_sharded.stack_resident(data, n_shards)
                defn = solver_registry.get(solver)
                s = sharded_solves[iters] = defn.sharded(
                    problem, dist, None, None
                )
            return s

    def fit_once(params, resource=0, warm_start=None):
        iters = int(resource) if resource else max_iters
        w0 = (
            None
            if warm_start is None
            else jnp.asarray(np.asarray(warm_start, np.float32))
        )
        lam = float(np.asarray(params).ravel()[0])
        if host_kind:
            res = _sharded_solve(iters)(lam, w0)
        else:
            res = _problem(iters).solve_single_device(
                data, reg_weight=lam, w0=w0
            )
        w = np.asarray(res.w, np.float32)
        scores = np.asarray(X_val @ w).ravel()
        metric, all_metrics = suite.evaluate_primary(
            scores, y_val, val_weights
        )
        return metric, all_metrics, w

    fit_once.suite = suite
    fit_once.larger_is_better = suite.primary_evaluator.larger_is_better
    return fit_once


def run(argv: Optional[Sequence[str]] = None) -> dict:
    args = build_arg_parser().parse_args(argv)
    # x64 is process-global jax state; restore it afterwards so one
    # --precise-accumulation run can't leak f64 defaults into later
    # in-process runs (bench, tests, library users).
    prev_x64 = None
    if args.precise_accumulation:
        prev_x64 = bool(jax.config.jax_enable_x64)
        jax.config.update("jax_enable_x64", True)
    try:
        return _run(args)
    finally:
        if prev_x64 is not None:
            jax.config.update("jax_enable_x64", prev_x64)


def _run(args) -> dict:
    os.makedirs(args.output_dir, exist_ok=True)
    # The logger and telemetry hub own process-level resources (file
    # handles, the process-current hub slot); context managers release
    # them on ANY exit — repeated in-process driver runs (tests, bench,
    # hyperparameter search) must not leak either.
    with PhotonLogger(args.output_dir) as logger:
        tel = telemetry_mod.Telemetry(
            output_dir=args.output_dir,
            logger=logger,
            enabled=args.telemetry != "off",
        )
        with tel, tel.span(
            "run", driver="glm_driver", task=args.task
        ), telemetry_mod.mount_ops_plane(
            tel, port=args.metrics_port,
            interval_s=args.metrics_interval_s, logger=logger,
        ):
            return _run_impl(args, logger, tel)


def _run_impl(args, logger, tel) -> dict:
    timer = Timer().start()
    cache_dir = enable_from_args(args, logger)
    from photon_ml_tpu.parallel.multihost import initialize_logged

    initialize_logged(logger)

    # Stage 1: read ---------------------------------------------------------
    with tel.span("read", path=args.train_data):
        X_train, y_train = libsvm.read_libsvm(
            args.train_data, n_features=args.n_features,
            add_intercept=args.intercept,
        )
    d = X_train.shape[1]
    logger.info(
        "read %d rows x %d features from %s", X_train.shape[0], d, args.train_data
    )
    # The LIBSVM path has positional features; the index map gives them names
    # (feature "j" + intercept last), as FeatureIndexingDriver would.
    names = [f"f{j}" for j in range(d - 1)] if args.intercept else [
        f"f{j}" for j in range(d)
    ]
    index_map = IndexMap.build(names, add_intercept=args.intercept)

    # Stage 2: summarize + normalization ------------------------------------
    data_parallel = args.data_parallel == "auto" and len(jax.devices()) > 1
    if args.stream_storage_dir and args.stream_chunk_rows <= 0:
        # Silently ignoring the flag would hand the user a fully
        # RAM-resident run on exactly the oversized dataset the flag
        # exists for.
        raise ValueError(
            "--stream-storage-dir requires --stream-chunk-rows > 0"
        )
    if args.stream_chunk_fuse > 1 and data_parallel:
        # StreamingObjective would refuse this at construction anyway,
        # but only after the (possibly long) chunk-store ingest.
        raise ValueError(
            "--stream-chunk-fuse > 1 is single-device only (the scan-"
            "fused program does not compose with the mesh reduction)"
        )
    if args.stream_hot_budget_mb > 0 and data_parallel:
        raise ValueError(
            "--stream-hot-budget-mb > 0 is single-device only (a cached "
            "chunk would pin sharded buffers across the mesh)"
        )
    streaming = args.stream_chunk_rows > 0
    with tel.span("summarize", rows=int(X_train.shape[0]), features=int(d)):
        if data_parallel or streaming:
            # The sharded path uploads the matrix across the mesh (and the
            # streamed path never uploads it whole); a second full
            # single-device copy just for summarization would defeat both.
            from photon_ml_tpu.data.stats import summarize_host

            train_data = None
            summary = summarize_host(X_train)
        else:
            train_data = make_glm_data(X_train, y_train)
            summary = summarize(train_data)
    norm_type = NormalizationType(args.normalization)
    normalization = (
        None
        if norm_type is NormalizationType.NONE
        else build_normalization(norm_type, summary, index_map.intercept_index)
    )
    summary_out = {
        "mean": np.asarray(summary.mean).tolist(),
        "variance": np.asarray(summary.variance).tolist(),
        "min": np.asarray(summary.min).tolist(),
        "max": np.asarray(summary.max).tolist(),
        "nnz": np.asarray(summary.nnz).tolist(),
        "count": float(summary.count),
    }
    with open(os.path.join(args.output_dir, "feature_summary.json"), "w") as f:
        json.dump(summary_out, f)
    # Avro artifact too, as the reference writes (SURVEY.md §5.5).
    from photon_ml_tpu.io.summary_store import save_feature_summary

    save_feature_summary(
        summary, index_map,
        os.path.join(args.output_dir, "feature_summary.avro"),
    )

    # Stage 3: train over the λ grid ----------------------------------------
    solver_options = []
    for kv in args.solver_option:
        if "=" not in kv:
            raise SystemExit(
                f"--solver-option must be KEY=VALUE, got {kv!r}"
            )
        k, _, v = kv.partition("=")
        solver_options.append((k.strip(), v.strip()))
    if args.solver_shards:
        solver_options.append(("shards", args.solver_shards))
    host_solver = False
    if args.solver is not None:
        from photon_ml_tpu.solvers import registry as solver_registry

        try:
            host_solver = solver_registry.get(args.solver).kind == "host"
        except KeyError as e:
            raise SystemExit(str(e))
        if host_solver:
            if streaming:
                raise SystemExit(
                    f"--solver {args.solver} runs over sharded resident "
                    "data; it does not compose with --streaming (the "
                    "streamed pass loop IS the jit-kind solvers' "
                    "distribution story)"
                )
            if args.compute_variances:
                raise SystemExit(
                    f"--solver {args.solver} does not support "
                    "--compute-variances"
                )
            if args.coefficient_bounds:
                raise SystemExit(
                    f"--solver {args.solver} does not support "
                    "--coefficient-bounds (only spg does)"
                )
    problem = GlmOptimizationProblem(
        args.task,
        GlmOptimizationConfig(
            optimizer=OptimizerConfig(
                optimizer=OptimizerType(args.optimizer),
                max_iters=args.max_iters,
                tolerance=args.tolerance,
                solver=args.solver,
                solver_options=tuple(solver_options),
            ),
            regularization=RegularizationContext(
                RegularizationType(args.reg_type), args.elastic_net_alpha
            ),
            compute_variances=args.compute_variances,
        ),
        normalization=normalization,
        accumulate="f64" if args.precise_accumulation else "f32",
    )
    reg_weights = [float(s) for s in args.reg_weights.split(",")]
    l1_mask = None
    if args.intercept and index_map.intercept_index is not None:
        l1_mask = jnp.ones((d,), jnp.float32).at[index_map.intercept_index].set(0.0)

    bounds = None
    if args.coefficient_bounds:
        # Box constraints apply to the coefficients the solver actually
        # optimizes; under normalization those live in scaled space where
        # a per-feature box does not map back to the user's box — reject
        # rather than silently constrain the wrong quantity.  Streamed /
        # data-parallel composition is not wired up.
        if normalization is not None:
            raise SystemExit(
                "--coefficient-bounds requires --normalization none"
            )
        if streaming or data_parallel:
            raise SystemExit(
                "--coefficient-bounds is single-device resident-data only"
            )
        if args.compute_variances:
            # The diag-inverse-Hessian variance assumes an interior
            # optimum; it is wrong for coefficients pinned at an active
            # bound (nonzero gradient there).
            raise SystemExit(
                "--coefficient-bounds is incompatible with "
                "--compute-variances"
            )
        with open(args.coefficient_bounds) as f:
            bounds_map = json.load(f)
        lower = np.full((d,), -np.inf, np.float32)
        upper = np.full((d,), np.inf, np.float32)
        unknown = [k for k in bounds_map if index_map.get_index(k) < 0]
        if unknown:
            raise SystemExit(
                f"--coefficient-bounds names unknown features: {unknown[:5]}"
            )
        for key, (lo, hi) in bounds_map.items():
            lo, hi = float(lo), float(hi)
            if np.isnan(lo) or np.isnan(hi) or lo > hi:
                # json.load accepts NaN literals, and jnp.clip with
                # lower > upper silently returns upper — both would
                # train a wrong model without a word.
                raise SystemExit(
                    f"--coefficient-bounds: invalid bounds for {key!r}: "
                    f"[{lo}, {hi}]"
                )
            idx = index_map.get_index(key)
            lower[idx], upper[idx] = lo, hi
        bounds = (jnp.asarray(lower), jnp.asarray(upper))
        logger.info(
            "box constraints on %d of %d coefficients", len(bounds_map), d
        )

    # Checkpoint/resume + incremental training (SURVEY.md §5.3/§5.4): each
    # solved λ is persisted; --resume skips finished λs bit-exactly;
    # --initial-model seeds the warm-start chain from a saved model.
    from photon_ml_tpu.io.checkpoint import GridCheckpointer
    from photon_ml_tpu.io.model_store import load_glm_model

    # Fingerprint the RESOLVED box constraints (the arrays the solver
    # actually sees): a --resume against a checkpoint written under
    # different bounds would warm-start the remaining λs from
    # incompatibly-constrained coefficients and silently blend two
    # models (the CD locked-set guard's failure mode, ADVICE r5).
    bounds_fp = None
    if bounds is not None:
        import hashlib

        h = hashlib.sha256()
        h.update(np.ascontiguousarray(np.asarray(bounds[0])).tobytes())
        h.update(np.ascontiguousarray(np.asarray(bounds[1])).tobytes())
        bounds_fp = h.hexdigest()

    ckpt = GridCheckpointer(os.path.join(args.output_dir, "checkpoints"))
    if args.resume:
        saved_fp = ckpt.load_meta().get("bounds_fingerprint")
        if ckpt.exists() and saved_fp != bounds_fp:
            raise SystemExit(
                "--resume: the grid checkpoint was written under "
                f"different --coefficient-bounds (saved fingerprint "
                f"{saved_fp}, this run {bounds_fp}); clear "
                f"{ckpt.path} or rerun with the matching bounds"
            )
        solved = ckpt.load()
    else:
        # A stale checkpoint (possibly from a run on different data or
        # normalization) must not survive into a later --resume.
        ckpt.clear()
        solved = {}
    if solved:
        logger.info(
            "resuming: %d of %d grid points already solved",
            len(solved), len(reg_weights),
        )

    w0 = None
    if args.initial_model:
        glm0, _ = load_glm_model(args.initial_model, index_map)
        w0 = jnp.asarray(np.asarray(glm0.coefficients.means, np.float32))
        if normalization is not None:
            # Saved models live in the original feature space; the solver
            # works in scaled-coefficient space.
            w0 = normalization.original_to_model(w0)
        logger.info("warm-starting from %s", args.initial_model)

    mesh = None
    stream = None
    if streaming:
        from photon_ml_tpu.data.streaming import make_streaming_glm_data
        from photon_ml_tpu.optim.streaming import ensure_streamable

        # Reject unstreamable configs BEFORE the (possibly large) ingest.
        ensure_streamable(problem.config)
        n_shards = 1
        if data_parallel:
            from photon_ml_tpu.parallel.distributed import data_mesh

            mesh = data_mesh()
            n_shards = mesh.devices.size
        stream = make_streaming_glm_data(
            X_train, y_train, chunk_rows=args.stream_chunk_rows,
            use_pallas=False if n_shards > 1 else "auto",
            n_shards=n_shards,
            storage_dir=args.stream_storage_dir,
        )
        logger.info(
            "streaming: %d chunks x %d rows (%.1f MB host), %d shard(s)",
            stream.n_chunks, stream.chunk_rows,
            stream.nbytes() / 1e6, n_shards,
        )
    elif data_parallel:
        from photon_ml_tpu.parallel.distributed import data_mesh

        mesh = data_mesh()
        logger.info("data-parallel: %d-device mesh", len(jax.devices()))

    def train(attempt: int):
        """One training attempt over the λ grid.  Re-entered by the
        watchdog after a transient failure (SURVEY.md §5.3): checkpointed
        λs are reloaded so finished work is never repeated, and device-
        resident data is re-placed (a lost device invalidates buffers)."""
        solved_now = dict(solved)
        if attempt:
            solved_now.update(ckpt.load())
            logger.info(
                "retry %d: %d grid points restored from checkpoints",
                attempt, len(solved_now),
            )
        solved_acc = dict(solved_now)

        def on_solved(lam, w):
            solved_acc[lam] = np.asarray(w)
            ckpt.save(
                solved_acc, extra_meta={"bounds_fingerprint": bounds_fp}
            )

        if streaming:
            from photon_ml_tpu.optim.streaming import streaming_run_grid

            # Chunks are host-resident numpy; nothing to re-place.
            return streaming_run_grid(
                problem, stream, reg_weights, w0=w0, mesh=mesh,
                solved=solved_now, on_solved=on_solved, l1_mask=l1_mask,
                prefetch_depth=args.stream_prefetch_depth,
                chunk_fuse=args.stream_chunk_fuse,
                batch_linesearch=args.stream_batch_linesearch == "on",
                compress=args.stream_compress,
                hot_budget_bytes=int(args.stream_hot_budget_mb * 1e6),
            )
        if data_parallel:
            from photon_ml_tpu.parallel.distributed import (
                run_grid_distributed,
                shard_glm_data,
            )

            dist = shard_glm_data(X_train, y_train, mesh)
            return run_grid_distributed(
                problem, dist, mesh, reg_weights, w0=w0, l1_mask=l1_mask,
                solved=solved_now, on_solved=on_solved,
            )
        if host_solver:
            # No mesh: a host-kind solver still runs sharded, over
            # logical row blocks on one device (same step program as the
            # mesh path, vmap + axis-0 sum standing in for the psum).
            from photon_ml_tpu.parallel.distributed import shard_glm_data
            from photon_ml_tpu.solvers import sharded as solvers_sharded

            n_shards = solvers_sharded.resolve_shard_count(
                problem.config.optimizer
            )
            X_sh = X_train
            if args.solver == "block_cd" and hasattr(X_sh, "todense"):
                # block CD reads per-shard columns; densify (LIBSVM
                # inputs at driver scale fit — the mesh path keeps
                # sparse for admm).
                X_sh = np.asarray(X_sh.todense(), np.float32)
                logger.info(
                    "block_cd: densified %d x %d design for column "
                    "access", X_sh.shape[0], X_sh.shape[1],
                )
            dist = shard_glm_data(X_sh, y_train, None, n_shards=n_shards)
            logger.info(
                "solver %s: %d logical shard(s)", args.solver, n_shards
            )
            return solvers_sharded.run_grid_sharded(
                problem, dist, None, reg_weights, w0=w0, l1_mask=l1_mask,
                solved=solved_now, on_solved=on_solved,
            )
        data = train_data if attempt == 0 else make_glm_data(
            X_train, y_train
        )
        return problem.run_grid(
            data, reg_weights, w0=w0, l1_mask=l1_mask,
            solved=solved_now, on_solved=on_solved, bounds=bounds,
        )

    from photon_ml_tpu.utils.watchdog import (
        RetryPolicy,
        RetryStats,
        run_with_retries,
    )

    retry_stats = RetryStats()
    with tel.span(
        "train", grid_points=len(reg_weights),
        streaming=streaming, data_parallel=data_parallel,
    ):
        grid = run_with_retries(
            train,
            RetryPolicy(
                max_retries=args.max_retries,
                backoff_seconds=args.retry_backoff,
            ),
            logger,
            stats=retry_stats,
        )
    grid_walls = getattr(problem, "grid_wall_seconds", {})
    for lam, _, res in grid:
        if res is None:
            logger.info("lambda=%g: restored from checkpoint", lam)
            continue
        tracker = OptimizationStatesTracker.from_solve_result(
            res, wall_seconds=grid_walls.get(lam, float("nan"))
        )
        logger.info(
            "lambda=%g: value=%.8g iters=%d converged=%s wall=%.3fs",
            lam, float(res.value), tracker.iterations, tracker.converged,
            tracker.wall_seconds,
        )

    # Stage 4: validate + select --------------------------------------------
    evaluator = (
        get_evaluator(args.evaluator)
        if args.evaluator
        else default_evaluator_for_task(problem.task)
    )
    if args.validate_data:
        X_val, y_val = libsvm.read_libsvm(
            args.validate_data, n_features=d - (1 if args.intercept else 0),
            add_intercept=args.intercept,
            # Features unseen at training time contribute nothing, they must
            # not abort the job after all training compute is spent.
            drop_out_of_range=True,
        )
    else:
        X_val, y_val = X_train, y_train
    host_scoring = data_parallel or streaming
    val_data = None if host_scoring else (
        make_glm_data(X_val, y_val) if args.validate_data else train_data
    )

    report = None
    if args.training_report:
        from photon_ml_tpu.diagnostics import (
            TrainingReport,
            bootstrap_metric_ci,
            feature_importance,
            hosmer_lemeshow,
        )

        report = TrainingReport(task=problem.task)
        # Loop-invariant report inputs (d can be millions; the λ loop
        # must not rebuild them per grid point, and names resolve lazily
        # for just the top-k rendered rows).
        report_std = np.sqrt(
            np.maximum(np.asarray(summary.variance), 0.0)
        )

    metrics = {}
    best: tuple[float, GeneralizedLinearModel] | None = None
    best_metric = None
    with tel.span(
        "validate", rows=int(len(y_val)),
        evaluator=type(evaluator).__name__,
    ):
        for lam, model, res in grid:
            if host_scoring:
                # Host scipy matvec: validation never needs a device round
                # trip of a full unsharded copy.
                scores = np.asarray(
                    X_val @ np.asarray(model.coefficients.means, np.float32)
                ).ravel()
                val_weights = None
            else:
                scores = np.asarray(model.compute_score(val_data))
                val_weights = np.asarray(val_data.weights)
            m = evaluator.evaluate(scores, y_val, val_weights)
            metrics[lam] = m
            logger.info(
                "lambda=%g: %s=%.6f", lam, type(evaluator).__name__, m
            )
            if best_metric is None or evaluator.better_than(m, best_metric):
                best_metric, best = m, (lam, model)
            if report is not None:
                if res is not None:
                    report.add_convergence(lam, res.values, res.grad_norms)
                report.add_metric(
                    type(evaluator).__name__, lam,
                    bootstrap_metric_ci(
                        lambda s, l: evaluator.evaluate(s, l, None),
                        scores, np.asarray(y_val),
                    ),
                )
                if problem.task == "logistic":
                    report.add_calibration(
                        lam, hosmer_lemeshow(scores, np.asarray(y_val))
                    )
                report.add_importance(lam, feature_importance(
                    np.asarray(model.coefficients.means),
                    feature_std=report_std,
                    name_fn=index_map.index_to_name,
                ))

    # Stage 5: write --------------------------------------------------------
    assert best is not None
    best_lam, best_model = best
    with tel.span("write", output_mode=args.output_mode):
        to_write = grid if args.output_mode == "all" else [
            (lam, mdl, res) for lam, mdl, res in grid if lam == best_lam
        ]
        for lam, model, _ in to_write:
            out = os.path.join(args.output_dir, f"model_lambda_{lam:g}.avro")
            save_glm_model(model, index_map, out, model_id=f"lambda={lam:g}")
        index_map.save(args.output_dir)
    result = {
        "best_lambda": best_lam,
        "metrics": {str(k): v for k, v in metrics.items()},
        "evaluator": type(evaluator).__name__,
        "n_rows": int(X_train.shape[0]),
        "n_features": int(d),
        "wall_seconds": timer.stop(),
        "solver_wall_seconds": {
            str(lam): w for lam, w in sorted(grid_walls.items())
        },
    }
    if retry_stats.retries or retry_stats.failures:
        result["retry"] = retry_stats.snapshot()
    if report is not None:
        jpath, hpath = report.save(args.output_dir)
        result["report"] = {"json": jpath, "html": hpath}
        logger.info("training report: %s", hpath)
    with open(os.path.join(args.output_dir, "training_result.json"), "w") as f:
        json.dump(result, f, indent=2)
    publish_cache_metrics(cache_dir)
    tel.gauge("run_wall_seconds").set(result["wall_seconds"])
    logger.info(
        "selected lambda=%g (%s=%.6f) in %.2fs",
        best_lam, type(evaluator).__name__, best_metric, result["wall_seconds"],
    )
    return result


def main() -> None:
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
