"""Feature indexing driver.

The analogue of the reference's ``FeatureIndexingDriver`` (SURVEY.md §2,
"Feature index maps"): scan training data once and persist per-shard
feature-name → column-index maps, so training/scoring jobs can share a
stable feature space without re-deriving it.  ``--binary`` additionally
writes the hash-sorted mmap layout (the PalDB analogue) for very wide
spaces.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional, Sequence

from photon_ml_tpu.data.game_reader import read_game_avro
from photon_ml_tpu.utils.logging import PhotonLogger


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="feature_indexing_driver",
        description="Build feature index maps from GAME Avro data",
    )
    p.add_argument("--data", required=True, help="GAME Avro file")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--add-intercept", action="store_true")
    p.add_argument("--binary", action="store_true",
                   help="also write the mmap binary layout")
    return p


def run(argv: Optional[Sequence[str]] = None) -> dict:
    args = build_arg_parser().parse_args(argv)
    os.makedirs(args.output_dir, exist_ok=True)
    with PhotonLogger(args.output_dir) as logger:
        shards, _, response, _, _, _, index_maps = read_game_avro(args.data)
        if args.add_intercept:
            # Shard names are only known after a first read; re-read with
            # an intercept column appended to every shard.
            shards, _, response, _, _, _, index_maps = read_game_avro(
                args.data, add_intercept_shards=tuple(shards)
            )
        sizes = {}
        for shard, imap in index_maps.items():
            target = os.path.join(args.output_dir, shard)
            imap.save(target)
            if args.binary:
                imap.save_binary(target)
            sizes[shard] = len(imap)
            logger.info(
                "shard %s: %d features -> %s", shard, len(imap), target
            )
        return {"shards": sizes, "n_rows": int(len(response))}


def main() -> None:
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
