"""GAME scoring driver.

The analogue of the reference's ``GameScoringDriver`` (SURVEY.md §2, §3.3):
load a saved GameModel, read GAME Avro data through the SAVED index maps
(unseen features drop, as the reference's scoring path does), score (fixed
effect matvec + per-entity random-effect gathers, summed with offsets), and
write ``ScoringResultAvro`` records.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.data.game_reader import read_game_avro
from photon_ml_tpu.evaluation.evaluators import get_evaluator
from photon_ml_tpu.game.estimator import GameTransformer
from photon_ml_tpu.io import avro
from photon_ml_tpu.io.game_store import load_game_model
from photon_ml_tpu.io.schemas import SCORING_RESULT
from photon_ml_tpu.utils.compile_cache import (
    add_compile_cache_arg,
    enable_from_args,
)
from photon_ml_tpu.utils.logging import PhotonLogger
from photon_ml_tpu.utils.timer import Timer


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="game_scoring_driver", description="TPU-native GAME batch scoring"
    )
    p.add_argument("--data", required=True, help="GAME Avro file to score")
    p.add_argument("--model-dir", required=True, help="saved GameModel directory")
    p.add_argument("--output-dir", required=True)
    p.add_argument(
        "--mean", action="store_true",
        help="emit mean responses (inverse link) instead of raw margins",
    )
    p.add_argument("--evaluator", help="also compute a metric if labels present")
    add_compile_cache_arg(p)
    return p


def run(argv: Optional[Sequence[str]] = None) -> dict:
    args = build_arg_parser().parse_args(argv)
    os.makedirs(args.output_dir, exist_ok=True)
    logger = PhotonLogger(args.output_dir)
    timer = Timer().start()
    enable_from_args(args, logger)
    from photon_ml_tpu.parallel.multihost import initialize_logged

    initialize_logged(logger)

    model, index_maps = load_game_model(os.path.join(args.model_dir, "models"))
    shards, ids, response, weight, offset, uids, _ = read_game_avro(
        args.data, index_maps=index_maps, logger=logger
    )
    transformer = GameTransformer(model, logger=logger)
    scores = (
        transformer.transform_with_mean(shards, ids, offset)
        if args.mean
        else transformer.transform(shards, ids, offset)
    )

    records = [
        {
            "uid": uids[i],
            "predictionScore": float(scores[i]),
            "label": float(response[i]),
            "ids": {k: str(v[i]) for k, v in ids.items()},
        }
        for i in range(len(scores))
    ]
    avro.write_container(
        os.path.join(args.output_dir, "scores.avro"), SCORING_RESULT, records
    )

    result = {"n_rows": int(len(scores)), "wall_seconds": timer.stop()}
    if args.evaluator:
        ev = get_evaluator(args.evaluator)
        result["metric"] = ev.evaluate(scores, response, weight)
        result["evaluator"] = type(ev).__name__
        logger.info("%s = %.6f", type(ev).__name__, result["metric"])
    with open(os.path.join(args.output_dir, "scoring_result.json"), "w") as f:
        json.dump(result, f, indent=2)
    logger.info("scored %d rows in %.2fs", result["n_rows"], result["wall_seconds"])
    logger.close()
    return result


def main() -> None:
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
