"""GAME scoring driver.

The analogue of the reference's ``GameScoringDriver`` (SURVEY.md §2, §3.3):
load a saved GameModel, read GAME Avro data through the SAVED index maps
(unseen features drop, as the reference's scoring path does), score (fixed
effect matvec + per-entity random-effect gathers, summed with offsets), and
write ``ScoringResultAvro`` records.

The scoring math is the serving subsystem's (``serving/kernels.py``, via
``GameTransformer``): batch jobs here and the online request path
(``python -m photon_ml_tpu.serving``) share ONE implementation of the
fixed-effect matvec + random-effect gather + offset sum, so a model
validated offline scores identically when deployed behind the
micro-batched HTTP endpoint (docs/serving.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.game_reader import read_game_avro
from photon_ml_tpu.evaluation.evaluators import get_evaluator
from photon_ml_tpu.game.estimator import GameTransformer
from photon_ml_tpu.io import avro
from photon_ml_tpu.io.game_store import load_game_model

from photon_ml_tpu.utils.compile_cache import (
    add_compile_cache_arg,
    enable_from_args,
)
from photon_ml_tpu.utils.logging import PhotonLogger
from photon_ml_tpu.utils.timer import Timer


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="game_scoring_driver", description="TPU-native GAME batch scoring"
    )
    p.add_argument("--data", required=True, help="GAME Avro file to score")
    p.add_argument("--model-dir", required=True, help="saved GameModel directory")
    p.add_argument("--output-dir", required=True)
    p.add_argument(
        "--mean", action="store_true",
        help="emit mean responses (inverse link) instead of raw margins",
    )
    p.add_argument("--evaluator", help="also compute a metric if labels present")
    p.add_argument(
        "--device-metrics",
        action="store_true",
        help="compute the metric ON DEVICE; with --stream-block-rows and "
        "a pointwise evaluator (rmse/logistic_loss/poisson_loss/"
        "squared_loss) the metric accumulates as two scalars per block — "
        "NO per-row columns are retained, so memory stays one block even "
        "with a metric (AUC still needs the full column: global sort)",
    )
    p.add_argument(
        "--stream-block-rows",
        type=int,
        default=0,
        help="out-of-core scoring: read, score, and write the data in "
        "bounded blocks of about this many rows — memory is one block, "
        "never the dataset (plus 12 B/row of score/label/weight columns "
        "kept ONLY when --evaluator needs a global metric; the reference "
        "scores arbitrary-size data via Spark partitions, SURVEY.md 3.3). "
        "0 = materialize the whole file",
    )
    p.add_argument(
        "--telemetry",
        choices=["on", "off"],
        default="on",
        help="unified telemetry (events.jsonl + trace.json + metrics.json "
        "in the output dir, summary in the log)",
    )
    add_compile_cache_arg(p)
    return p


def run(argv: Optional[Sequence[str]] = None) -> dict:
    args = build_arg_parser().parse_args(argv)
    os.makedirs(args.output_dir, exist_ok=True)
    from photon_ml_tpu import telemetry as telemetry_mod

    with PhotonLogger(args.output_dir) as logger:
        tel = telemetry_mod.Telemetry(
            output_dir=args.output_dir,
            logger=logger,
            enabled=args.telemetry != "off",
        )
        with tel, tel.span("run", driver="game_scoring_driver"):
            return _run_impl(args, logger, tel)


def _run_impl(args, logger, tel) -> dict:
    timer = Timer().start()
    enable_from_args(args, logger)
    from photon_ml_tpu.parallel.multihost import initialize_logged

    initialize_logged(logger)

    model, index_maps = load_game_model(os.path.join(args.model_dir, "models"))
    transformer = GameTransformer(model, logger=logger)
    out_path = os.path.join(args.output_dir, "scores.avro")

    def score_block(uids, scores, labels, ids):
        # ONE columnar block shape for both paths — the streamed/resident
        # parity tests assert bit-for-bit identical output files.  Sorted
        # keys: the upstream ids dict order is insertion order
        # (whole-file for the resident reader, block-local for the
        # streamed one), so a canonical order here is what actually
        # makes the two output files byte-identical.  The writer
        # serializes natively (native/score_encoder.cpp) when available.
        return (
            uids,
            np.asarray(scores, np.float32),
            np.asarray(labels, np.float32),
            {k: ids[k] for k in sorted(ids)},
        )

    if args.stream_block_rows > 0:
        # Out-of-core: decode → score → write per bounded block.  The
        # score/label/weight columns (12 B/row) accumulate across blocks
        # ONLY when a global metric needs them; without --evaluator the
        # footprint stays one block.
        from photon_ml_tpu.data.game_reader import iter_game_avro
        from photon_ml_tpu.game.model import RandomEffectModel

        stream_kind = None
        if args.evaluator and args.device_metrics:
            from photon_ml_tpu.evaluation.device import pointwise_kind_for

            stream_kind = pointwise_kind_for(get_evaluator(args.evaluator))
        # Pointwise device metrics accumulate as (num, den) scalars per
        # block — no O(n_rows) column retention for the metric at all.
        keep_columns = bool(args.evaluator) and stream_kind is None
        partial_num = [0.0]
        partial_den = [0.0]
        all_scores: list[np.ndarray] = []
        all_labels: list[np.ndarray] = []
        all_weights: list[np.ndarray] = []
        n_streamed = [0]
        # Every block must expose the model's entity-id columns even if
        # none of its rows carry them (a block of id-less rows would
        # otherwise KeyError inside the random-effect scorer).
        entity_keys = [
            sub.entity_key
            for sub in model.models.values()
            if isinstance(sub, RandomEffectModel)
        ]

        def block_records():
            for shards, ids, response, weight, offset, uids in iter_game_avro(
                args.data, index_maps, block_rows=args.stream_block_rows,
                logger=logger, id_keys=entity_keys,
            ):
                blk = (
                    transformer.transform_with_mean(shards, ids, offset)
                    if args.mean
                    else transformer.transform(shards, ids, offset)
                )
                n_streamed[0] += len(blk)
                if keep_columns:
                    all_scores.append(np.asarray(blk, np.float32))
                    all_labels.append(response)
                    all_weights.append(weight)
                elif stream_kind is not None and len(blk):
                    from photon_ml_tpu.evaluation.device import (
                        device_pointwise_partial,
                    )

                    num, den = device_pointwise_partial(
                        jnp.asarray(np.asarray(blk, np.float32)),
                        jnp.asarray(response),
                        jnp.asarray(weight),
                        kind=stream_kind,
                    )
                    partial_num[0] += float(num)
                    partial_den[0] += float(den)
                logger.info("scored block of %d rows", len(blk))
                yield score_block(uids, blk, response, ids)

        # The columnar writer consumes the generator block-by-block:
        # rows stream to disk as they are produced, never as one list.
        avro.write_scoring_container(out_path, block_records())
        n_rows = n_streamed[0]
        if keep_columns:
            scores = np.concatenate(all_scores) if all_scores else (
                np.zeros(0, np.float32)
            )
            response = np.concatenate(all_labels) if all_labels else (
                np.zeros(0, np.float32)
            )
            weight = np.concatenate(all_weights) if all_weights else (
                np.zeros(0, np.float32)
            )
        else:
            scores = response = weight = None  # never needed without a metric
    else:
        shards, ids, response, weight, offset, uids, _ = read_game_avro(
            args.data, index_maps=index_maps, logger=logger
        )
        scores = (
            transformer.transform_with_mean(shards, ids, offset)
            if args.mean
            else transformer.transform(shards, ids, offset)
        )
        avro.write_scoring_container(
            out_path, [score_block(uids, scores, response, ids)]
        )
        n_rows = len(scores)

    result = {"n_rows": int(n_rows), "wall_seconds": timer.stop()}
    tel.gauge("scored_rows").set(int(n_rows))
    tel.gauge("run_wall_seconds").set(result["wall_seconds"])
    if args.evaluator:
        ev = get_evaluator(args.evaluator)
        if scores is None and args.stream_block_rows > 0:
            # Streamed + pointwise device metric: the per-block scalar
            # accumulation already holds the whole answer.
            from photon_ml_tpu.evaluation.device import (
                finish_pointwise_partial, pointwise_kind_for,
            )

            result["metric"] = finish_pointwise_partial(
                partial_num[0], partial_den[0], pointwise_kind_for(ev)
            )
        elif args.device_metrics:
            from photon_ml_tpu.evaluation.device import device_evaluator_fn

            fn = device_evaluator_fn(ev)
            result["metric"] = (
                float(fn(
                    jnp.asarray(scores), jnp.asarray(response),
                    None if weight is None else jnp.asarray(weight),
                ))
                if fn is not None
                else ev.evaluate(scores, response, weight)
            )
        else:
            result["metric"] = ev.evaluate(scores, response, weight)
        result["evaluator"] = type(ev).__name__
        logger.info("%s = %.6f", type(ev).__name__, result["metric"])
    with open(os.path.join(args.output_dir, "scoring_result.json"), "w") as f:
        json.dump(result, f, indent=2)
    logger.info("scored %d rows in %.2fs", result["n_rows"], result["wall_seconds"])
    return result


def main() -> None:
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
