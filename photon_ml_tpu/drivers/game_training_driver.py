"""GAME training driver.

The analogue of the reference's ``GameTrainingDriver``
([CONFIRMED-BASELINE], SURVEY.md §2, §3.2): validate params → read GAME Avro
data → build feature index maps → ``GameEstimator.fit`` over the coordinate
configuration → evaluate → save the GameModel (fixed-effect + per-entity
coefficient Avro files).

The coordinate configuration comes from a JSON file (the reference's
spark.ml ``Param`` surface), e.g.::

    {
      "task": "logistic",
      "iterations": 3,
      "evaluator": "auc",
      "coordinates": [
        {"name": "fixed", "type": "fixed", "feature_shard": "global",
         "optimizer": "lbfgs", "max_iters": 50, "tolerance": 1e-7,
         "reg_type": "l2", "reg_weight": 1.0},
        {"name": "per_user", "type": "random", "feature_shard": "userFeatures",
         "entity_key": "userId", "optimizer": "lbfgs", "max_iters": 30,
         "reg_type": "l2", "reg_weight": 1.0, "max_rows_per_entity": 4096}
      ]
    }
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.data.game_reader import read_game_avro
from photon_ml_tpu.evaluation.suite import EvaluationSuite
from photon_ml_tpu.game.estimator import (
    FactoredRandomEffectCoordinateConfig,
    FixedEffectCoordinateConfig,
    GameEstimator,
    GameTransformer,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.io.game_store import save_game_model
from photon_ml_tpu.optim.problem import (
    GlmOptimizationConfig,
    OptimizerConfig,
    OptimizerType,
)
from photon_ml_tpu.optim.regularization import RegularizationContext, RegularizationType
from photon_ml_tpu.ops import losses as losses_lib
from photon_ml_tpu.utils.compile_cache import (
    add_compile_cache_arg,
    enable_from_args,
    publish_cache_metrics,
)
from photon_ml_tpu.utils.logging import PhotonLogger
from photon_ml_tpu.utils.timer import Timer


def expand_config_grid(coordinate_specs: Sequence[dict]) -> list[dict]:
    """Expand the JSON coordinate list into the coordinate-config GRID the
    reference's GameEstimator fits (SURVEY.md §3.2 "for each
    coordinate-config combination"): a spec may give ``reg_weights`` (a list)
    instead of scalar ``reg_weight``; the grid is the cross product of every
    coordinate's variants.  Returns a list of name→config mappings."""
    import dataclasses as _dc
    import itertools

    per_coord = []
    for spec in coordinate_specs:
        name, base = parse_coordinate_config(spec)
        weights = spec.get("reg_weights")
        variants = (
            [_dc.replace(base, reg_weight=float(w)) for w in weights]
            if weights
            else [base]
        )
        per_coord.append((name, variants))
    return [
        {name: cfg for (name, _), cfg in zip(per_coord, combo)}
        for combo in itertools.product(*[v for _, v in per_coord])
    ]


def parse_coordinate_config(spec: dict):
    """One JSON coordinate spec → (name, CoordinateConfig)."""
    solver = spec.get("solver")
    solver_options = tuple(
        sorted((str(k), str(v)) for k, v in
               dict(spec.get("solver_options", {})).items())
    )
    opt = GlmOptimizationConfig(
        optimizer=OptimizerConfig(
            optimizer=OptimizerType(spec.get("optimizer", "lbfgs")),
            max_iters=int(spec.get("max_iters", 100)),
            tolerance=float(spec.get("tolerance", 1e-7)),
            # "solver" names a registered solver (docs/solvers.md);
            # unset keeps the historical OWL-QN/TRON/L-BFGS routing
            # bitwise.  "solver_options" is a JSON object of knobs.
            solver=solver if solver is None else str(solver),
            solver_options=solver_options,
        ),
        regularization=RegularizationContext(
            RegularizationType(spec.get("reg_type", "none")),
            float(spec.get("elastic_net_alpha", 0.5)),
        ),
        compute_variances=bool(spec.get("compute_variances", False)),
    )
    name = spec["name"]
    if spec["type"] == "fixed":
        return name, FixedEffectCoordinateConfig(
            feature_shard=spec["feature_shard"],
            optimization=opt,
            reg_weight=float(spec.get("reg_weight", 0.0)),
            down_sampling_rate=float(spec.get("down_sampling_rate", 1.0)),
            # >0: train this coordinate out-of-core (host-RAM chunks of
            # this many rows streamed through HBM — game/streaming.py).
            streaming_chunk_rows=int(spec.get("streaming_chunk_rows", 0)),
            # chunks the ingest pipeline keeps in flight when streaming.
            prefetch_depth=int(spec.get("prefetch_depth", 2)),
            # chunks folded per device dispatch (lax.scan) when streaming;
            # amortizes per-dispatch overhead for small chunks.
            chunk_fuse=int(spec.get("chunk_fuse", 1)),
            # batch line-search trials into one streamed pass per bracket.
            batch_linesearch=bool(spec.get("batch_linesearch", True)),
            # compressed chunk wire format when streaming
            # (off|lossless|fp16|int8) — on-device dequant, lossless is
            # bitwise neutral.
            stream_compress=str(spec.get("stream_compress", "off")),
            # MB of wire chunk buffers kept HBM-resident across passes
            # (importance-aware working-set cache; single-device only).
            stream_hot_budget_mb=float(
                spec.get("stream_hot_budget_mb", 0.0)
            ),
        )
    if spec["type"] == "random":
        return name, RandomEffectCoordinateConfig(
            feature_shard=spec["feature_shard"],
            entity_key=spec["entity_key"],
            optimization=opt,
            reg_weight=float(spec.get("reg_weight", 0.0)),
            max_rows_per_entity=spec.get("max_rows_per_entity"),
            bucket_growth=float(spec.get("bucket_growth", 2.0)),
            # bucket-boundary policy: "geometric" | "cost_model" (the
            # repacker, game/data.py) + its program budget and seed.
            repack=str(spec.get("repack", "geometric")),
            program_budget=int(spec.get("program_budget", 16)),
            repack_seed=int(spec.get("repack_seed", 0)),
            # mesh bucket-ladder placement threshold (game/hierarchical.py).
            split_factor=float(spec.get("split_factor", 0.5)),
            # >0: train this coordinate out-of-core (entity blocks stay in
            # host RAM, streamed through HBM in pass groups bounded by this
            # many megabytes — game/ooc_random.py).
            device_budget_bytes=int(
                float(spec.get("device_budget_mb", 0)) * 2**20
            ),
            prefetch_depth=int(spec.get("prefetch_depth", 2)),
            # MB of out-of-core static slice payloads kept HBM-resident
            # across passes (hot working-set cache; bitwise neutral).
            hot_budget_mb=float(spec.get("hot_budget_mb", 0.0)),
        )
    if spec["type"] in ("factored_random", "factored"):
        proj_rw = spec.get("projection_reg_weight")
        return name, FactoredRandomEffectCoordinateConfig(
            feature_shard=spec["feature_shard"],
            entity_key=spec["entity_key"],
            rank=int(spec["rank"]),
            optimization=opt,
            reg_weight=float(spec.get("reg_weight", 0.0)),
            projection_reg_weight=(
                None if proj_rw is None else float(proj_rw)
            ),
            alternations=int(spec.get("alternations", 2)),
            max_rows_per_entity=spec.get("max_rows_per_entity"),
            bucket_growth=float(spec.get("bucket_growth", 2.0)),
            repack=str(spec.get("repack", "geometric")),
            program_budget=int(spec.get("program_budget", 16)),
            repack_seed=int(spec.get("repack_seed", 0)),
            device_budget_bytes=int(
                float(spec.get("device_budget_mb", 0)) * 2**20
            ),
            prefetch_depth=int(spec.get("prefetch_depth", 2)),
        )
    raise ValueError(f"unknown coordinate type {spec['type']!r}")


def make_fit_once(
    task: str,
    coordinate_configs: dict,
    shards: dict,
    ids: dict,
    response,
    validation,
    *,
    weight=None,
    offset=None,
    suite=None,
    mesh=None,
    device_metrics: bool = False,
):
    """Reusable single-fit entry for the tuning orchestrator
    (photon_ml_tpu/tuning/): ``fit_once(params, resource, warm_start) ->
    (metric, metrics, None)``.

    ``params`` carries one regularization weight per coordinate (in
    ``coordinate_configs`` order) and ``resource`` the number of CD
    iterations (an ASHA rung's budget; 0 uses the config count of 1).
    ``warm_start`` is accepted but unused — GAME coordinate state does
    not warm-start across trials; ASHA's cross-rung refits are whole
    fits at a larger iteration budget.

    Trials mutate per-coordinate ``reg_weight`` (a traced argument), so
    one coordinate build serves MANY trials — but never two in-flight
    trials at once: coordinates carry mutable per-fit state.  Builds
    live in a checkout pool per iteration budget, so the number of
    builds is bounded by the executor's peak concurrency (not
    trials × rungs) and builds are reused across searches sharing this
    ``fit_once``.
    """
    import threading

    import dataclasses as _dc

    from photon_ml_tpu.evaluation.suite import EvaluationSuite

    if suite is None:
        suite = EvaluationSuite.for_task(losses_lib.get(task).name)
    evaluator = suite.primary_evaluator
    names = list(coordinate_configs)
    # Never pay the coefficient-variance finalize cost per tuning point
    # (same policy as this driver's built-in tuning mode).
    base_configs = {
        nm: _dc.replace(
            cfg,
            optimization=_dc.replace(
                cfg.optimization, compute_variances=False
            ),
        )
        for nm, cfg in coordinate_configs.items()
    }
    v_shards, v_ids, v_resp, v_weight, v_offset = validation[:5]
    v_groups = (
        np.asarray(v_ids[suite.group_column])
        if suite.group_column is not None
        else None
    )
    pools: dict[int, list] = {}
    pool_lock = sanitizers.tracked(threading.Lock(), "game.checkout_pool")

    def _checkout(resource: int):
        n_iter = int(resource) if resource else 1
        with pool_lock:
            free = pools.setdefault(n_iter, [])
            if free:
                return n_iter, free.pop()
        est = GameEstimator(
            task, base_configs, n_iterations=n_iter, mesh=mesh,
            device_metrics=device_metrics,
        )
        coords = est.build_coordinates(shards, ids, response, weight, offset)
        return n_iter, (est, coords)

    def fit_once(params, resource=0, warm_start=None):
        n_iter, inst = _checkout(resource)
        try:
            est, coords = inst
            for coord, xi in zip(coords, np.asarray(params, float).ravel()):
                coord.reg_weight = float(xi)
            model, _ = est.fit_coordinates(
                coords, response, weight, offset, evaluator
            )
        finally:
            with pool_lock:
                pools[n_iter].append(inst)
        scores = GameTransformer(model).transform(v_shards, v_ids, v_offset)
        metric, all_metrics = suite.evaluate_primary(
            scores, v_resp, v_weight, group_ids=v_groups
        )
        return metric, all_metrics, None

    fit_once.suite = suite
    fit_once.larger_is_better = evaluator.larger_is_better
    fit_once.names = names
    return fit_once


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="game_training_driver", description="TPU-native GAME training"
    )
    p.add_argument("--train-data", required=True, help="GAME Avro file")
    p.add_argument("--validate-data", help="GAME Avro validation file")
    p.add_argument("--config", required=True, help="coordinate config JSON")
    p.add_argument("--output-dir", required=True)
    p.add_argument(
        "--resume",
        action="store_true",
        help="continue coordinate descent from the checkpoint in the "
        "output dir (bit-exact with the uninterrupted run)",
    )
    p.add_argument(
        "--initial-model",
        help="saved GameModel directory to warm-start from (the reference's "
        "incremental training); its index maps are used to read the data",
    )
    p.add_argument(
        "--locked-coordinates",
        help="comma-separated coordinate names held at --initial-model "
        "instead of retrained (the reference's partial retraining)",
    )
    p.add_argument(
        "--data-parallel",
        choices=["off", "auto"],
        default="off",
        help="auto: with >1 device, shard rows (fixed effects) and the "
        "entity axis (random effects) over a mesh of all devices — the "
        "reference's Spark-cluster layout on ICI",
    )
    p.add_argument(
        "--pipeline-coordinates",
        action="store_true",
        help="overlap coordinate updates' offset-independent host work "
        "(the next coordinate prestages its first pass groups while the "
        "current one solves — game/descent.py); bitwise identical to "
        "the serial schedule",
    )
    p.add_argument(
        "--device-metrics",
        action="store_true",
        help="compute per-update train/validation metrics ON DEVICE "
        "(only metric scalars cross to host — the at-scale validation "
        "path). Requires an ungrouped evaluation suite",
    )
    p.add_argument(
        "--max-retries",
        type=int,
        default=0,
        help="automatic recovery from TRANSIENT failures (lost device, "
        "transport drop): re-enter training up to this many times. The "
        "single-config path resumes from the per-iteration CD checkpoint; "
        "a config GRID resumes at the completed-grid-point boundary "
        "(each finished point's model is checkpointed). 0 disables",
    )
    p.add_argument(
        "--retry-backoff",
        type=float,
        default=5.0,
        help="initial seconds between retries (exponential, x2 per "
        "attempt, capped at 300s)",
    )
    p.add_argument(
        "--telemetry",
        choices=["on", "off"],
        default="on",
        help="unified telemetry (events.jsonl + trace.json + metrics.json "
        "in the output dir, summary in the log). 'off' reduces every "
        "instrumented site to one branch",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        help="expose the live ops plane on this port while the run is "
        "in flight (/metrics Prometheus exposition, /snapshot JSON, "
        "/healthz); 0 binds an ephemeral port; omit to disable",
    )
    p.add_argument(
        "--metrics-interval-s",
        type=float,
        default=1.0,
        help="interval of the metrics_ts.jsonl time-series sampler "
        "(live registry snapshots in the output dir; 0 disables)",
    )
    add_compile_cache_arg(p)
    return p


def run(argv: Optional[Sequence[str]] = None) -> dict:
    args = build_arg_parser().parse_args(argv)
    os.makedirs(args.output_dir, exist_ok=True)
    # Context-managed logger + telemetry: both own process-level
    # resources that must release on ANY exit (see glm_driver).
    with PhotonLogger(args.output_dir) as logger:
        tel = telemetry_mod.Telemetry(
            output_dir=args.output_dir,
            logger=logger,
            enabled=args.telemetry != "off",
        )
        with tel, tel.span(
            "run", driver="game_training_driver"
        ), telemetry_mod.mount_ops_plane(
            tel, port=args.metrics_port,
            interval_s=args.metrics_interval_s, logger=logger,
        ):
            return _run_impl(args, logger, tel)


def _run_impl(args, logger, tel) -> dict:
    timer = Timer().start()
    cache_dir = enable_from_args(args, logger)
    from photon_ml_tpu.parallel.multihost import initialize_logged

    initialize_logged(logger)

    with open(args.config) as f:
        config = json.load(f)
    task = config.get("task", "logistic")
    config_grid = expand_config_grid(config["coordinates"])
    coordinate_configs = config_grid[0]
    # Evaluation suite (reference: EvaluationSuite / MultiEvaluator — a LIST
    # of evaluators per run, the first driving model selection).
    group_column = config.get("evaluator_group_column")
    if "evaluators" in config:
        suite = EvaluationSuite.from_specs(
            config["evaluators"], group_column=group_column
        )
    elif "evaluator" in config:
        suite = EvaluationSuite.from_specs(
            [config["evaluator"]], group_column=group_column
        )
    else:
        suite = EvaluationSuite.for_task(losses_lib.get(task).name)
        if group_column is not None:
            import dataclasses as _dc

            suite = _dc.replace(suite, group_column=group_column)
    evaluator = suite.primary_evaluator

    # Incremental training (SURVEY.md §5.4): a prior model fixes the feature
    # index maps — the data is read through them so coefficient vectors line
    # up column-for-column with the saved model.
    initial_model = None
    with tel.span("read", path=args.train_data):
        if args.initial_model:
            from photon_ml_tpu.io.game_store import load_game_model

            initial_model, initial_imaps = load_game_model(
                args.initial_model
            )
            shards, ids, response, weight, offset, _, index_maps = (
                read_game_avro(
                    args.train_data, index_maps=initial_imaps, logger=logger
                )
            )
            index_maps = initial_imaps
            logger.info("incremental training from %s", args.initial_model)
        else:
            shards, ids, response, weight, offset, _, index_maps = (
                read_game_avro(args.train_data)
            )
    logger.info(
        "read %d rows; shards: %s; id columns: %s",
        len(response),
        {k: v.shape for k, v in shards.items()},
        list(ids),
    )

    # Optional per-shard feature summaries (the reference writes feature
    # summary Avro artifacts — SURVEY.md §5.5).
    if config.get("feature_summaries", False):
        from photon_ml_tpu.data.stats import summarize_host
        from photon_ml_tpu.io.summary_store import save_feature_summary

        summary_dir = os.path.join(args.output_dir, "feature-summaries")
        os.makedirs(summary_dir, exist_ok=True)
        for shard_name, shard_matrix in shards.items():
            save_feature_summary(
                summarize_host(shard_matrix, weight),
                index_maps[shard_name],
                os.path.join(summary_dir, f"{shard_name}.avro"),
            )
        logger.info(
            "wrote feature summaries for %s", sorted(shards)
        )

    n_cd_iterations = int(config.get("iterations", 1))
    validation = None
    if args.validate_data:
        with tel.span("read", path=args.validate_data, validation=True):
            validation = read_game_avro(
                args.validate_data, index_maps=index_maps, logger=logger
            )

    result = {"task": task, "n_rows": int(len(response))}

    # Optional hyperparameter tuning over per-coordinate regularization
    # weights (the reference's BAYESIAN|RANDOM tuning mode inside
    # GameTrainingDriver — SURVEY.md §3.5).
    mesh = None
    if args.data_parallel == "auto":
        import jax

        if len(jax.devices()) > 1:
            from photon_ml_tpu.parallel.distributed import data_mesh

            mesh = data_mesh()
            logger.info(
                "data-parallel: %d-device mesh (rows + entity axis sharded)",
                len(jax.devices()),
            )

    locked = tuple(
        s.strip() for s in (args.locked_coordinates or "").split(",")
        if s.strip()
    )
    if locked and not args.initial_model:
        raise SystemExit("--locked-coordinates requires --initial-model")

    tuning = config.get("tuning")
    if tuning:
        if locked:
            # Tuning sweeps every coordinate's reg weight and refits all
            # of them per evaluation — a locked coordinate would be
            # silently retrained during the search, then locked only in
            # the final fit (inconsistent selection).
            raise SystemExit(
                "--locked-coordinates is incompatible with tuning mode"
            )
        if validation is None:
            raise ValueError("hyperparameter tuning requires --validate-data")
        import dataclasses as _dc

        from photon_ml_tpu.hyperparameter.search import (
            GaussianProcessSearch,
            RandomSearch,
        )

        names = list(coordinate_configs)
        lo, hi = tuning.get("range", [1e-3, 1e3])
        v_shards, v_ids, v_resp, v_weight, v_offset, _, _ = validation

        # Datasets and jitted solvers are built ONCE; each tuning point only
        # mutates reg_weight (a traced argument) — no recompiles, no
        # re-grouping/upload of random-effect shards.
        # Tuning evaluates by score metric only — never pay the
        # coefficient-variance finalize cost per tuning point.
        tuning_configs = {
            nm: _dc.replace(
                cfg,
                optimization=_dc.replace(
                    cfg.optimization, compute_variances=False
                ),
            )
            for nm, cfg in coordinate_configs.items()
        }
        tuning_est = GameEstimator(
            task, tuning_configs, n_cd_iterations, mesh=mesh,
            device_metrics=args.device_metrics,
        )
        tuning_coords = tuning_est.build_coordinates(
            shards, ids, response, weight, offset
        )

        v_groups = (
            np.asarray(v_ids[suite.group_column])
            if suite.group_column is not None
            else None
        )

        def evaluate(x):
            for coord, xi in zip(tuning_coords, x):
                coord.reg_weight = float(xi)
            mdl, _ = tuning_est.fit_coordinates(
                tuning_coords, response, weight, offset, evaluator
            )
            scores = GameTransformer(mdl).transform(v_shards, v_ids, v_offset)
            metric = evaluator.evaluate(
                scores, v_resp, v_weight, group_ids=v_groups
            )
            logger.info("tuning: reg=%s -> %.6f", list(map(float, x)), metric)
            return metric

        search_cls = (
            GaussianProcessSearch
            if tuning.get("mode", "bayesian") == "bayesian"
            else RandomSearch
        )
        search = search_cls([(lo, hi)] * len(names), log_scale=True, seed=0)
        with tel.span(
            "tuning", mode=tuning.get("mode", "bayesian"),
            iterations=int(tuning.get("iterations", 10)),
        ):
            found = search.find(
                evaluate,
                int(tuning.get("iterations", 10)),
                maximize=evaluator.larger_is_better,
            )
        coordinate_configs = {
            nm: _dc.replace(coordinate_configs[nm], reg_weight=float(xi))
            for nm, xi in zip(names, found.best_params)
        }
        config_grid = [coordinate_configs]  # tuning supersedes any grid
        result["tuning"] = {
            "best_reg_weights": dict(zip(names, map(float, found.best_params))),
            "best_metric": found.best_value,
            "n_evaluations": len(found.history),
        }
        logger.info("tuning selected %s", result["tuning"]["best_reg_weights"])

    val_tuple = None
    if validation is not None:
        v_shards, v_ids, v_resp, v_weight, v_offset, _, _ = validation
        val_tuple = (v_shards, v_ids, v_resp, v_weight, v_offset)

    # Checkpointing: per-CD-iteration for a single config, per-grid-point
    # for a config grid (a finished point's model persists; an interrupted
    # point re-fits, earlier points are skipped).
    checkpointer = None
    grid_checkpointer = None
    checkpoint_enabled = bool(config.get("checkpoint", True))
    if checkpoint_enabled:
        ckpt_dir = os.path.join(args.output_dir, "checkpoints")
        if len(config_grid) == 1:
            from photon_ml_tpu.io.checkpoint import (
                CoordinateDescentCheckpointer,
            )

            checkpointer = CoordinateDescentCheckpointer(ckpt_dir)
            if not args.resume:
                # A stale checkpoint from a previous job must not silently
                # hijack a fresh run.
                checkpointer.clear()
        else:
            from photon_ml_tpu.io.checkpoint import GameGridCheckpointer

            grid_checkpointer = GameGridCheckpointer(ckpt_dir, index_maps)
            if not args.resume:
                grid_checkpointer.clear()
    elif args.resume:
        raise ValueError(
            '--resume requires checkpointing ("checkpoint": false is set '
            "in the config JSON)"
        )

    estimator = GameEstimator(
        task, coordinate_configs, n_iterations=n_cd_iterations, logger=logger,
        mesh=mesh, device_metrics=args.device_metrics,
        pipeline=args.pipeline_coordinates,
    )
    from photon_ml_tpu.utils.watchdog import (
        RetryPolicy,
        RetryStats,
        run_with_retries,
    )

    retry_policy = RetryPolicy(
        max_retries=args.max_retries, backoff_seconds=args.retry_backoff
    )
    retry_stats = RetryStats()
    if len(config_grid) > 1:
        if locked:
            raise SystemExit(
                "--locked-coordinates is single-config only (a locked "
                "coordinate has nothing to sweep)"
            )
        # Config-grid fit with validation-driven selection (SURVEY.md §3.2).
        with tel.span(
            "train", grid_points=len(config_grid),
            cd_iterations=n_cd_iterations,
        ):
            model, grid_results = run_with_retries(
                lambda attempt: estimator.fit_grid(
                    config_grid, shards, ids, response, weight=weight,
                    offset=offset, validation=val_tuple, suite=suite,
                    initial_model=initial_model,
                    grid_checkpointer=grid_checkpointer,
                ),
                retry_policy, logger, stats=retry_stats,
            )
        best = next(r for r in grid_results if r["best"])
        history = best["history"]
        result["grid"] = [
            {
                "grid_index": r["grid_index"],
                "reg_weights": {
                    nm: cfg.reg_weight for nm, cfg in r["configs"].items()
                },
                "metric": r["metric"],
                "selected_by": r["selected_by"],
                "best": r["best"],
            }
            for r in grid_results
        ]
        logger.info(
            "config grid: %d points, best index %d (%s = %s)",
            len(grid_results), best["grid_index"], best["selected_by"],
            best["metric"],
        )
    else:
        # A retry resumes from the per-iteration CD checkpoint (the
        # CoordinateDescent loop reloads it on entry — SURVEY.md §5.3).
        with tel.span("train", cd_iterations=n_cd_iterations):
            model, history = run_with_retries(
                lambda attempt: estimator.fit(
                    shards, ids, response, weight=weight, offset=offset,
                    validation=val_tuple, suite=suite,
                    initial_model=initial_model, checkpointer=checkpointer,
                    locked_coordinates=locked,
                ),
                retry_policy, logger, stats=retry_stats,
            )
    result["history"] = history
    result["train_metric"] = history[-1].get("train_metric") if history else None
    if history and "validation" in history[-1]:
        result["per_iteration_validation"] = True
        result["validation_suite"] = history[-1]["validation"]

    if validation is not None:
        v_shards, v_ids, v_resp, v_weight, v_offset, _, _ = validation
        with tel.span("validate", rows=int(len(v_resp))):
            v_scores = GameTransformer(model).transform(
                v_shards, v_ids, v_offset
            )
            v_groups = (
                np.asarray(v_ids[suite.group_column])
                if suite.group_column is not None
                else None
            )
            result["validation_metric"] = evaluator.evaluate(
                v_scores, v_resp, v_weight, group_ids=v_groups
            )
        logger.info(
            "validation %s = %.6f",
            type(evaluator).__name__, result["validation_metric"],
        )

    with tel.span("write"):
        save_game_model(
            model, index_maps, os.path.join(args.output_dir, "models")
        )
    if retry_stats.retries or retry_stats.failures:
        result["retry"] = retry_stats.snapshot()
    result["wall_seconds"] = timer.stop()
    with open(os.path.join(args.output_dir, "training_result.json"), "w") as f:
        json.dump(result, f, indent=2)
    publish_cache_metrics(cache_dir)
    tel.gauge("run_wall_seconds").set(result["wall_seconds"])
    logger.info("GAME training done in %.2fs", result["wall_seconds"])
    return result


def main() -> None:
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
