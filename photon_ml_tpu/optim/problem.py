"""Optimization problems: objective + optimizer + regularization + normalization.

The analogue of the reference's ``GeneralizedLinearOptimizationProblem`` /
``DistributedOptimizationProblem`` / ``SingleNodeOptimizationProblem`` and
their ``OptimizationProblemConfig`` (SURVEY.md §2): bind everything needed to
produce a trained ``GeneralizedLinearModel``, optionally with coefficient
variances, and sweep a regularization-weight grid with warm starts (the
reference's ``ModelTraining`` trains the λ grid chained — SURVEY.md §3.1).

The distributed/single-node split is ONE class here: ``axis_name=None`` is
single-device; an axis name + ``shard_map`` (parallel/distributed.py) is the
distributed problem.  λ is a runtime argument, so one compiled solver serves
the whole grid without recompilation.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.data.dataset import GlmData
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.ops import losses as losses_lib
from photon_ml_tpu.optim.lbfgs import SolveResult
from photon_ml_tpu.optim.objective import GlmObjective
from photon_ml_tpu.optim.regularization import RegularizationContext

Array = jax.Array


class OptimizerType(enum.Enum):
    LBFGS = "lbfgs"
    OWLQN = "owlqn"
    TRON = "tron"


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Mirrors the reference's ``OptimizerConfig`` (optimizerType,
    maximumIterations, tolerance).

    ``solver`` names a registered solver (photon_ml_tpu/solvers/registry.py)
    explicitly; None keeps the historical routing (bounds → SPG, any L1
    component → OWL-QN, else ``optimizer``) bitwise.  ``solver_options`` is
    a tuple of (key, value) pairs — a TUPLE, not a dict, because this
    config lives in lru_cache keys (GAME block solvers, fixed-effect jit
    caches) and must stay hashable."""

    optimizer: OptimizerType = OptimizerType.LBFGS
    max_iters: int = 100
    tolerance: float = 1e-7
    history: int = 10  # L-BFGS/OWL-QN corrections
    solver: Optional[str] = None
    solver_options: tuple = ()


@dataclasses.dataclass(frozen=True)
class GlmOptimizationConfig:
    """Mirrors the reference's per-coordinate ``GLMOptimizationConfiguration``:
    optimizer config + regularization context + weight(s) + variance flag."""

    optimizer: OptimizerConfig = OptimizerConfig()
    regularization: RegularizationContext = RegularizationContext.none()
    compute_variances: bool = False


class GlmOptimizationProblem:
    """Trains GLMs for a task under a config.

    All solve paths are pure jittable functions; this class only does static
    dispatch (optimizer type, loss) and host-side bookkeeping, so it can be
    used identically on one device or inside ``shard_map``.
    """

    def __init__(
        self,
        task: str,
        config: GlmOptimizationConfig = GlmOptimizationConfig(),
        normalization: Optional[NormalizationContext] = None,
        accumulate: str = "f32",
    ):
        self.task = losses_lib.get(task).name  # canonicalize aliases
        self.config = config
        #: per-λ blocking wall seconds of the LAST grid_loop run (drivers
        #: read it to put real wall-clock on convergence trackers).
        self.grid_wall_seconds: dict[float, float] = {}
        self.objective = GlmObjective(
            losses_lib.get(task), normalization, accumulate=accumulate
        )
        self.normalization = normalization
        # One compiled program serves every single-device solve: data,
        # reg_weight, w0, and l1_mask are traced arguments, so a λ grid or
        # repeated fits never re-trace (the GAME coordinates already did
        # this; the legacy-driver path goes through here).
        self._solve_jit = jax.jit(
            lambda data, reg_weight, w0, l1_mask, bounds: self.solve(
                data, reg_weight, w0, None, l1_mask, bounds
            )
        )

    def solve_single_device(
        self,
        data: GlmData,
        reg_weight: Array | float = 0.0,
        w0: Optional[Array] = None,
        l1_mask: Optional[Array] = None,
        bounds: Optional[tuple[Array, Array]] = None,
    ) -> SolveResult:
        """Jit-cached single-device :meth:`solve` (axis_name=None)."""
        if w0 is None:
            w0 = jnp.zeros((data.n_features,), jnp.float32)
        return self._solve_jit(
            data, jnp.asarray(reg_weight, jnp.float32), w0, l1_mask, bounds
        )

    # -- core solve (jit/shard_map-safe) -----------------------------------
    def solve(
        self,
        data: GlmData,
        reg_weight: Array | float = 0.0,
        w0: Optional[Array] = None,
        axis_name: Optional[str] = None,
        l1_mask: Optional[Array] = None,
        bounds: Optional[tuple[Array, Array]] = None,
    ) -> SolveResult:
        """One optimization run at one regularization weight.

        ``reg_weight`` may be a traced scalar: the split into L1/L2 uses only
        the (static) regularization type.

        ``bounds`` = (lower, upper) per-coefficient arrays (±inf entries
        unconstrained) routes the solve to the box-constrained SPG path —
        the reference's constraint-map support on its optimizer layer.
        """
        obj = self.objective
        cfg = self.config
        d = data.n_features
        if bounds is not None and cfg.compute_variances:
            # The diag-inverse-Hessian variance (coefficient_variances)
            # assumes an interior optimum; a coefficient pinned at an
            # active bound has a nonzero gradient there and its reported
            # variance would be meaningless.  Static config check, so it
            # raises at trace time, before any compute is spent.
            raise ValueError(
                "bounds are incompatible with compute_variances=True: "
                "diag-inverse-Hessian variances assume an interior "
                "optimum and are wrong for coefficients at an active "
                "bound — drop the bounds or the variance request"
            )
        if w0 is None:
            w0 = jnp.zeros((d,), jnp.float32)
        reg_weight = jnp.asarray(reg_weight, w0.dtype)
        # Static split coefficients (floats), dynamic weight (traced scalar).
        l1_frac = cfg.regularization.l1_weight(1.0)
        l1 = l1_frac * reg_weight
        l2 = cfg.regularization.l2_weight(1.0) * reg_weight
        opt = cfg.optimizer

        if bounds is not None and l1_frac > 0.0:
            # Box constraints conflict with the orthant-wise machinery
            # for any solver choice.
            raise NotImplementedError(
                "box constraints combined with L1 regularization are "
                "not supported: the orthant-wise and projection "
                "machineries conflict (drop the L1 component or the "
                "bounds)"
            )
        # Dispatch through the solver registry (photon_ml_tpu/solvers/):
        # cfg.optimizer.solver unset reproduces the pre-registry static
        # routing bitwise — bounds → SPG for any smooth config, any L1
        # component → OWL-QN (the only orthant-capable machinery, as in
        # the reference), else the configured optimizer.  All checks are
        # static: l1_frac is a float, the solver name a config string.
        from photon_ml_tpu.solvers import registry as solver_registry

        defn = solver_registry.resolve(
            opt, l1_frac=l1_frac, has_bounds=bounds is not None
        )
        if defn.kind != "jit":
            raise ValueError(
                f"solver {defn.name!r} runs a host-side outer loop and "
                "cannot execute inside a traced solve; route through "
                "solvers.sharded.run_grid_sharded (glm_driver --solver "
                "and run_grid_distributed do this automatically)"
            )
        return defn.resident(solver_registry.ResidentSolve(
            objective=obj, data=data, w0=w0, l1=l1, l2=l2, opt=opt,
            axis_name=axis_name, l1_mask=l1_mask, bounds=bounds,
        ))

    # -- variances (reference: optional coefficient variance computation) ---
    def coefficient_variances(
        self,
        w: Array,
        data: GlmData,
        reg_weight: Array | float = 0.0,
        axis_name: Optional[str] = None,
    ) -> Array:
        """Diagonal-inverse-Hessian approximation ``1 / H_jj`` — the
        reference's ``VarianceComputationType.SIMPLE``.  ``H_jj = Σ_i wᵢ·d2ᵢ·
        X²ᵢⱼ + λ₂``, one squared-column reduction."""
        l2 = self.config.regularization.l2_weight(1.0) * jnp.asarray(
            reg_weight, w.dtype
        )
        d2w = self.objective.d2_weights(w, data)
        diag = data.features.sq_rmatvec(d2w)
        if axis_name is not None:
            from jax import lax

            diag = lax.psum(diag, axis_name)
        return 1.0 / jnp.maximum(diag + l2, 1e-12)

    # -- model construction (host side) ------------------------------------
    def make_model(
        self, w: Array, variances: Optional[Array] = None
    ) -> GeneralizedLinearModel:
        """Map scaled-space coefficients back to the original feature space
        (normalization) and wrap them as a model."""
        if self.normalization is not None:
            w = self.normalization.model_to_original(w)
            # Variances are not transformed through normalization shifts;
            # scale-only transforms square the factors (as the reference's
            # coefficient summaries do).
            if variances is not None:
                variances = variances * self.normalization.factors**2
        return GeneralizedLinearModel(Coefficients(w, variances), self.task)

    # -- grid sweep with warm start (the reference's ModelTraining loop) ----
    def grid_loop(
        self,
        solve_fn,
        reg_weights: Sequence[float],
        w0: Optional[Array] = None,
        warm_start: bool = True,
        solved: Optional[dict] = None,
        on_solved=None,
        variance_fn=None,
    ) -> list[tuple[float, GeneralizedLinearModel, Optional[SolveResult]]]:
        """The warm-started λ chain shared by the single-device and
        distributed grids; ``solve_fn(lam, w_prev) → SolveResult`` is the
        only thing that differs between them.

        Checkpoint/resume: ``solved`` (λ → coefficient vector, from
        io/checkpoint.GridCheckpointer) skips already-solved λs — their
        entries come back with ``res=None`` and the warm-start chain
        continues from the restored coefficients, so a resumed grid matches
        the uninterrupted one bit-for-bit.  ``on_solved(lam, w)`` fires
        after each fresh solve (the driver persists the checkpoint there).
        ``variance_fn(w, lam)`` runs for EVERY grid point (including
        restored ones) when coefficient variances are requested.

        Each fresh solve runs under a ``solver`` telemetry span and is
        wall-clocked to COMPLETION (``Timer.stop_blocking`` on the
        solution vector — the grid is a warm-start chain, so solves were
        already serialized; the block only moves the sync to where it can
        be attributed).  Per-λ walls land in ``self.grid_wall_seconds``
        so drivers can put real wall-clock on their convergence
        trackers."""
        from photon_ml_tpu.utils.timer import Timer

        tel = telemetry_mod.current()
        self.grid_wall_seconds: dict[float, float] = {}
        results = []
        w_prev = w0
        solved = solved or {}
        for lam in sorted(reg_weights, reverse=True):
            if lam in solved:
                w = jnp.asarray(solved[lam])
                res = None
                tel.event("grid.restored", reg_weight=float(lam))
            else:
                with tel.span(
                    "solver",
                    reg_weight=float(lam),
                    optimizer=self.config.optimizer.optimizer.value,
                ) as sp:
                    timer = Timer().start()
                    res = solve_fn(lam, w_prev)
                    wall = timer.stop_blocking(res.w)
                    if tel.enabled:
                        # res.w is ready (blocked above), so these scalar
                        # readbacks cost a copy, not a device sync.
                        iters = int(res.iterations)
                        sp.set(
                            iterations=iters,
                            converged=bool(res.converged),
                            wall_seconds=wall,
                        )
                        tel.counter("solver_iterations").inc(iters)
                        tel.histogram("solver_wall_seconds").observe(wall)
                self.grid_wall_seconds[lam] = wall
                w = res.w
                if on_solved is not None:
                    on_solved(lam, w)
                # The natural crash/resume boundary of the warm-start
                # chain: the point is solved AND persisted, nothing of
                # the next λ has started (docs/robustness.md).
                chaos_mod.maybe_fail("grid.point", reg_weight=float(lam))
            variances = variance_fn(w, lam) if variance_fn is not None else None
            results.append((lam, self.make_model(w, variances), res))
            if warm_start:
                w_prev = w
        return results

    def run_grid(
        self,
        data: GlmData,
        reg_weights: Sequence[float],
        w0: Optional[Array] = None,
        axis_name: Optional[str] = None,
        l1_mask: Optional[Array] = None,
        warm_start: bool = True,
        solved: Optional[dict] = None,
        on_solved=None,
        bounds: Optional[tuple[Array, Array]] = None,
    ) -> list[tuple[float, GeneralizedLinearModel, Optional[SolveResult]]]:
        """Train one model per regularization weight (see :meth:`grid_loop`
        for the warm-start/checkpoint semantics)."""
        if bounds is not None and self.config.compute_variances:
            # Mirrors solve()'s guard, but raised eagerly here — before
            # the grid loop touches the device at all.
            raise ValueError(
                "run_grid with bounds is incompatible with "
                "compute_variances=True: diag-inverse-Hessian variances "
                "assume an interior optimum (see solve())"
            )

        def solve_fn(lam, w_prev):
            return (
                self.solve_single_device(data, lam, w_prev, l1_mask, bounds)
                if axis_name is None
                else self.solve(data, lam, w_prev, axis_name, l1_mask, bounds)
            )

        variance_fn = None
        if self.config.compute_variances:
            variance_fn = lambda w, lam: self.coefficient_variances(
                w, data, lam, axis_name
            )
        return self.grid_loop(
            solve_fn, reg_weights, w0, warm_start, solved, on_solved,
            variance_fn,
        )
