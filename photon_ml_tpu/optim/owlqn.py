"""OWL-QN: Orthant-Wise Limited-memory Quasi-Newton, fully on-device.

The analogue of the reference's ``OWLQN`` optimizer (photon-lib wraps
Breeze's ``OWLQN`` for L1 / elastic-net — SURVEY.md §2; BASELINE.json:
"L1 / elastic-net (OWL-QN)").  Minimizes ``f(w) + λ·‖w∘mask‖₁`` where f is
the smooth (optionally L2-regularized) part, per Andrew & Gao (2007):

- the *pseudo-gradient* replaces the gradient where ``w_i = 0`` (picks the
  steepest one-sided derivative, or 0 inside the subdifferential interval);
- the quasi-Newton direction (two-loop over smooth-gradient pairs) is
  projected onto the pseudo-gradient's descent orthant;
- each trial point is projected back onto the chosen orthant (coordinates
  that would cross zero are clamped to zero), with Armijo backtracking.

Everything is one jitted ``lax.while_loop`` — same zero-host-round-trip
property as lbfgs.py, and ``vmap``-able for batched per-entity L1 solves.
``l1_mask`` lets callers exempt the intercept column from the penalty
(the reference never regularizes the intercept).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.lbfgs import SolveResult, _two_loop, update_history
from photon_ml_tpu.optim.linesearch import ValueAndGrad, pnorm, pvdot

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class OWLQNConfig:
    max_iters: int = 100
    tolerance: float = 1e-7
    history: int = 10
    max_line_search_evals: int = 30
    armijo_c1: float = 1e-4
    backtrack: float = 0.5


class _OWLQNState(NamedTuple):
    w: Array
    value: Array  # full value incl. L1 term
    grad: Array  # smooth-part gradient
    S: Array
    Y: Array
    rho: Array
    gamma: Array
    k: Array
    n_pairs: Array
    done: Array
    converged: Array
    values: Array
    grad_norms: Array  # pseudo-gradient norms


def _pseudo_gradient(w: Array, grad: Array, l1: Array, mask: Array) -> Array:
    """Steepest-descent direction of f + λ‖w‖₁ (Andrew & Gao eq. 4)."""
    lam = l1 * mask
    at_zero_pos = grad + lam  # right derivative at w_i = 0
    at_zero_neg = grad - lam  # left derivative at w_i = 0
    pg_zero = jnp.where(
        at_zero_neg > 0, at_zero_neg, jnp.where(at_zero_pos < 0, at_zero_pos, 0.0)
    )
    return jnp.where(w != 0, grad + lam * jnp.sign(w), pg_zero)


def owlqn_solve(
    value_and_grad: ValueAndGrad,
    w0: Array,
    l1_weight: Array | float,
    config: OWLQNConfig = OWLQNConfig(),
    l1_mask: Optional[Array] = None,
    w_axis: Optional[str] = None,
) -> SolveResult:
    """Minimize ``f(w) + l1_weight·Σ_i mask_i·|w_i|``.

    ``value_and_grad`` evaluates only the smooth part f.  Returned
    ``SolveResult.grad`` is the final *pseudo-gradient* (its norm is the
    convergence quantity, matching Breeze's OWLQN ``adjustedGradient``).

    ``w_axis``: mesh axis name when ``w0`` (and f's gradient) are
    feature-dim SHARDS of a wide coefficient vector (tensor parallelism);
    every w-space reduction — the L1 term, pseudo-gradient norms, the
    two-loop recursion, history update, Armijo products — then reduces over
    that axis, so the sharded iteration replicates the single-device one
    (the orthant machinery itself is elementwise).
    """
    m = config.history
    d = w0.shape[0]
    dtype = w0.dtype
    l1 = jnp.asarray(l1_weight, dtype)
    mask = (
        jnp.ones((d,), dtype) if l1_mask is None else jnp.asarray(l1_mask, dtype)
    )

    def full_value(w, smooth_value):
        return smooth_value + l1 * pvdot(mask, jnp.abs(w), w_axis)

    f0_smooth, g0 = value_and_grad(w0)
    f0 = full_value(w0, f0_smooth)
    pg0 = _pseudo_gradient(w0, g0, l1, mask)
    pg0_norm = pnorm(pg0, w_axis)
    tol_scale = jnp.maximum(1.0, pg0_norm)

    n_track = config.max_iters + 1
    values0 = jnp.full((n_track,), jnp.nan, dtype).at[0].set(f0.astype(dtype))
    gnorms0 = jnp.full((n_track,), jnp.nan, dtype).at[0].set(pg0_norm)

    init = _OWLQNState(
        w=w0, value=f0, grad=g0,
        S=jnp.zeros((m, d), dtype),
        Y=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        gamma=jnp.asarray(1.0, dtype),
        k=jnp.asarray(0, jnp.int32),
        n_pairs=jnp.asarray(0, jnp.int32),
        done=pg0_norm <= config.tolerance * tol_scale,
        converged=pg0_norm <= config.tolerance * tol_scale,
        values=values0,
        grad_norms=gnorms0,
    )

    def cond(s: _OWLQNState):
        return jnp.logical_and(~s.done, s.k < config.max_iters)

    def body(s: _OWLQNState):
        pg = _pseudo_gradient(s.w, s.grad, l1, mask)

        direction = -_two_loop(
            pg, s.S, s.Y, s.rho, s.gamma, s.n_pairs, w_axis
        )
        # Project the direction onto the descent orthant of -pg: zero any
        # coordinate whose sign disagrees (Andrew & Gao §3.2 "alignment").
        direction = jnp.where(direction * (-pg) > 0, direction, 0.0)
        # Degenerate (all-zero) direction → steepest descent on pg.
        deg = pvdot(direction, direction, w_axis) == 0.0
        direction = jnp.where(deg, -pg, direction)

        # Orthant choice: sign(w) where nonzero, else sign of the step.
        xi = jnp.where(s.w != 0, jnp.sign(s.w), jnp.sign(-pg))

        first = s.n_pairs == 0
        t = jnp.where(
            first, jnp.minimum(1.0, 1.0 / pnorm(pg, w_axis)), 1.0
        )

        def project(w):
            # Clamp coordinates that crossed out of the chosen orthant.
            return jnp.where(w * xi >= 0, w, 0.0)

        def trial(t):
            w = project(s.w + t * direction)
            smooth, grad = value_and_grad(w)
            return w, full_value(w, smooth), grad

        def ls_cond(ls):
            t, w, value, _, n = ls
            # Armijo on the PROJECTED step (Andrew & Gao / Breeze OWLQN):
            # the trial point is orthant-projected, so the realized step is
            # w - s.w, not t*direction; using <pg, w - s.w> keeps the
            # sufficient-decrease threshold correctly scaled when the
            # projection clamps coordinates.  The inequality is non-strict:
            # a fully-clamped trial (w == s.w, dg_proj == 0) must keep
            # backtracking — a smaller t clamps fewer coordinates — rather
            # than be accepted as a zero step.
            dg_proj = pvdot(pg, w - s.w, w_axis)
            return jnp.logical_and(
                value >= s.value + config.armijo_c1 * dg_proj,
                n < config.max_line_search_evals,
            )

        def ls_body(ls):
            t, _, _, _, n = ls
            t_next = t * config.backtrack
            w, value, grad = trial(t_next)
            return (t_next, w, value, grad, n + 1)

        w1, f1, g1 = trial(t)
        t, w_new, f_new, g_new, _ = lax.while_loop(
            ls_cond, ls_body, (t, w1, f1, g1, jnp.asarray(1, jnp.int32))
        )

        # History pairs use the SMOOTH gradient (standard OWL-QN).
        S, Y, rho, gamma, n_pairs = update_history(
            s.S, s.Y, s.rho, s.gamma, s.n_pairs, w_new - s.w, g_new - s.grad,
            w_axis,
        )

        k = s.k + 1
        pg_new = _pseudo_gradient(w_new, g_new, l1, mask)
        pg_norm = pnorm(pg_new, w_axis)
        rel_impr = jnp.abs(s.value - f_new) / jnp.maximum(jnp.abs(s.value), 1e-12)
        # Line search made no progress: end the run and keep the incumbent
        # iterate (never adopt a trial point with a higher objective).
        # Convergence is measured at the iterate actually returned: the
        # pseudo-gradient test at the kept point on a stalled step, the usual
        # tests otherwise.
        stalled = f_new >= s.value
        converged = jnp.where(
            stalled,
            pnorm(pg, w_axis) <= config.tolerance * tol_scale,
            jnp.logical_or(
                pg_norm <= config.tolerance * tol_scale,
                rel_impr <= config.tolerance * 1e-2,
            ),
        )
        w_keep = jnp.where(stalled, s.w, w_new)
        f_keep = jnp.where(stalled, s.value, f_new)
        g_keep = jnp.where(stalled, s.grad, g_new)
        pg_norm = jnp.where(
            stalled, pnorm(pg, w_axis), pnorm(pg_new, w_axis)
        )

        return _OWLQNState(
            w=w_keep, value=f_keep, grad=g_keep,
            S=S, Y=Y, rho=rho, gamma=gamma,
            k=k, n_pairs=n_pairs,
            done=jnp.logical_or(converged, stalled),
            converged=converged,
            values=s.values.at[k].set(f_keep.astype(s.values.dtype)),
            grad_norms=s.grad_norms.at[k].set(pg_norm),
        )

    final = lax.while_loop(cond, body, init)
    pg_final = _pseudo_gradient(final.w, final.grad, l1, mask)
    return SolveResult(
        w=final.w,
        value=final.value,
        grad=pg_final,
        iterations=final.k,
        converged=final.converged,
        values=final.values,
        grad_norms=final.grad_norms,
    )
