from photon_ml_tpu.optim.objective import GlmObjective  # noqa: F401
from photon_ml_tpu.optim.regularization import RegularizationContext  # noqa: F401
