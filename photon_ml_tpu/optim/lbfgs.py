"""L-BFGS, fully on-device.

The analogue of the reference's ``LBFGS`` optimizer (photon-lib
``com.linkedin.photon.ml.optimization.LBFGS``, which wraps Breeze's L-BFGS —
SURVEY.md §2).  Where the reference runs the two-loop recursion on the driver
JVM and ships coefficients to executors once per objective evaluation, here
the *entire* optimize loop — two-loop recursion, line search, convergence
check — is one jitted ``lax.while_loop``: zero host round-trips per
iteration.  For a distributed objective, the only cross-device traffic is the
``psum`` inside each value+gradient evaluation (the ``treeAggregate``
analogue).

Fixed-size circular history (default m=10, matching Breeze/reference
defaults): ``S``/``Y`` are ``(m, d)`` buffers indexed modulo m, and the
two-loop recursion is a pair of ``lax.scan``s over the history axis with
masking for not-yet-filled slots — static shapes, MXU-friendly, no Python
control flow.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.linesearch import (
    LineSearchConfig,
    ValueAndGrad,
    pnorm,
    pvdot,
    wolfe_line_search,
)

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class LBFGSConfig:
    """Mirrors the reference's optimizer config surface
    (maxNumIterations, tolerance, numCorrections)."""

    max_iters: int = 100
    # Relative convergence tolerance on both objective decrease and gradient
    # norm (Breeze-style: ||g|| / max(1, ||g0||) <= tol).
    tolerance: float = 1e-7
    history: int = 10
    line_search: LineSearchConfig = LineSearchConfig()


class SolveResult(NamedTuple):
    """What every solver returns (the reference returns a model + an
    ``OptimizationStatesTracker``; values/grad_norms are that tracker)."""

    w: Array
    value: Array
    grad: Array
    iterations: Array  # int32
    converged: Array  # bool
    values: Array  # (max_iters+1,) objective per iteration (nan-padded)
    grad_norms: Array  # (max_iters+1,)
    # True when the solve EXITED without meeting the gradient-norm
    # tolerance (objective-plateau or failed-line-search exit) —
    # distinct from ``converged`` so callers can tell a constrained
    # stationary point from a stall.  None for solvers that fold the
    # plateau exit into ``converged`` (the historical contract); SPG
    # reports it.
    stalled: Array | None = None


class _LBFGSState(NamedTuple):
    w: Array
    value: Array
    grad: Array
    S: Array  # (m, d) coefficient deltas
    Y: Array  # (m, d) gradient deltas
    rho: Array  # (m,) 1 / <s, y>;  0 marks an empty/skipped slot
    gamma: Array  # initial-Hessian scale <s,y>/<y,y>
    k: Array  # iteration counter
    n_pairs: Array  # total pairs ever stored (for masking)
    done: Array
    converged: Array
    values: Array
    grad_norms: Array


def _two_loop(grad: Array, S: Array, Y: Array, rho: Array, gamma: Array,
              k_pairs: Array, w_axis: str | None = None) -> Array:
    """Two-loop recursion over the circular (S, Y) history.

    Slots with index >= k_pairs (never written) or rho == 0 (curvature-skipped)
    are masked out.  Newest pair is at (k_pairs - 1) mod m.
    """
    m = S.shape[0]
    # Order indices newest → oldest for the first loop.
    offsets = jnp.arange(m)
    newest = (k_pairs - 1) % jnp.maximum(m, 1)
    idx_new_to_old = (newest - offsets) % m
    valid = offsets < jnp.minimum(k_pairs, m)

    def first_loop(q, i_and_valid):
        i, is_valid = i_and_valid
        alpha = rho[i] * pvdot(S[i], q, w_axis)
        alpha = jnp.where(jnp.logical_and(is_valid, rho[i] > 0), alpha, 0.0)
        return q - alpha * Y[i], alpha

    q, alphas = lax.scan(first_loop, grad, (idx_new_to_old, valid))

    r = gamma * q

    def second_loop(r, scan_in):
        i, is_valid, alpha = scan_in
        beta = rho[i] * pvdot(Y[i], r, w_axis)
        corr = jnp.where(jnp.logical_and(is_valid, rho[i] > 0),
                         alpha - beta, 0.0)
        return r + corr * S[i], None

    # Oldest → newest: reverse the scan inputs.
    r, _ = lax.scan(
        second_loop, r, (idx_new_to_old[::-1], valid[::-1], alphas[::-1])
    )
    return r


def update_history(
    S: Array, Y: Array, rho: Array, gamma: Array, n_pairs: Array,
    s_vec: Array, y_vec: Array, w_axis: str | None = None,
) -> tuple[Array, Array, Array, Array, Array]:
    """Insert a curvature pair into the circular history, skipping it when
    <s, y> is not safely positive (standard safeguard).  Shared by L-BFGS
    and OWL-QN so the history rules cannot drift apart."""
    m = S.shape[0]
    sy = pvdot(s_vec, y_vec, w_axis)
    good = sy > 1e-10 * pnorm(s_vec, w_axis) * pnorm(y_vec, w_axis)
    slot = n_pairs % m
    S = jnp.where(good, S.at[slot].set(s_vec), S)
    Y = jnp.where(good, Y.at[slot].set(y_vec), Y)
    rho = jnp.where(good, rho.at[slot].set(1.0 / sy), rho)
    gamma = jnp.where(good, sy / pvdot(y_vec, y_vec, w_axis), gamma)
    n_pairs = jnp.where(good, n_pairs + 1, n_pairs)
    return S, Y, rho, gamma, n_pairs


def lbfgs_solve(
    value_and_grad: ValueAndGrad,
    w0: Array,
    config: LBFGSConfig = LBFGSConfig(),
    w_axis: str | None = None,
) -> SolveResult:
    """Minimize via L-BFGS.  Pure function of (w0, closure data); safe to wrap
    in ``jit`` / ``vmap`` (the vmap'd form is what batched per-entity
    random-effect solves use) / ``shard_map`` (distributed objectives).

    ``w_axis``: mesh axis name when ``w0`` (and the objective's gradient) are
    feature-dim SHARDS of a wide coefficient vector (tensor parallelism —
    SURVEY.md §5.7 scale axis (b)).  Every w-space inner product and norm in
    the two-loop recursion, history update, and line search then reduces
    over that axis, so the solver runs an exact replica of the single-device
    iteration on sharded state."""
    m = config.history
    d = w0.shape[0]
    dtype = w0.dtype

    f0, g0 = value_and_grad(w0)
    g0_norm = pnorm(g0, w_axis)
    tol_scale = jnp.maximum(1.0, g0_norm)

    n_track = config.max_iters + 1
    values0 = jnp.full((n_track,), jnp.nan, dtype).at[0].set(f0.astype(dtype))
    gnorms0 = jnp.full((n_track,), jnp.nan, dtype).at[0].set(g0_norm)

    init = _LBFGSState(
        w=w0,
        value=f0,
        grad=g0,
        S=jnp.zeros((m, d), dtype),
        Y=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        gamma=jnp.asarray(1.0, dtype),
        k=jnp.asarray(0, jnp.int32),
        n_pairs=jnp.asarray(0, jnp.int32),
        done=g0_norm <= config.tolerance * tol_scale,
        converged=g0_norm <= config.tolerance * tol_scale,
        values=values0,
        grad_norms=gnorms0,
    )

    def cond(s: _LBFGSState):
        return jnp.logical_and(~s.done, s.k < config.max_iters)

    def body(s: _LBFGSState):
        direction = -_two_loop(
            s.grad, s.S, s.Y, s.rho, s.gamma, s.n_pairs, w_axis
        )
        dg = pvdot(direction, s.grad, w_axis)
        # Fall back to steepest descent if the history produced a
        # non-descent direction (can happen after skipped updates).
        bad = dg >= 0.0
        direction = jnp.where(bad, -s.grad, direction)

        # First iteration: scale the initial step like Breeze
        # (1 / ||g||, capped at 1) so the unit quasi-Newton step is sane later.
        first = s.n_pairs == 0
        init_step = jnp.where(
            first, jnp.minimum(1.0, 1.0 / pnorm(s.grad, w_axis)), 1.0
        )

        ls = wolfe_line_search(
            value_and_grad, s.w, s.value, s.grad, direction,
            initial_step=init_step, config=config.line_search, w_axis=w_axis,
        )

        S, Y, rho, gamma, n_pairs = update_history(
            s.S, s.Y, s.rho, s.gamma, s.n_pairs, ls.w - s.w, ls.grad - s.grad,
            w_axis,
        )

        k = s.k + 1
        g_norm = pnorm(ls.grad, w_axis)
        # Converged when the gradient is small (relative, Breeze-style) or the
        # objective stops moving (relative function decrease).
        rel_impr = jnp.abs(s.value - ls.value) / jnp.maximum(
            jnp.abs(s.value), 1e-12
        )
        # A failed line search that also made no progress ends the run; the
        # incumbent iterate is kept (never adopt a trial point with a higher
        # objective than the current one).  Convergence is measured at the
        # iterate actually returned: the gradient test at the kept point on a
        # stalled step, the usual gradient/function-decrease tests otherwise.
        stalled = jnp.logical_and(~ls.success, ls.value >= s.value)
        converged = jnp.where(
            stalled,
            pnorm(s.grad, w_axis) <= config.tolerance * tol_scale,
            jnp.logical_or(
                g_norm <= config.tolerance * tol_scale,
                rel_impr <= config.tolerance * 1e-2,
            ),
        )
        w_next = jnp.where(stalled, s.w, ls.w)
        value_next = jnp.where(stalled, s.value, ls.value)
        grad_next = jnp.where(stalled, s.grad, ls.grad)

        return _LBFGSState(
            w=w_next,
            value=value_next,
            grad=grad_next,
            S=S, Y=Y, rho=rho, gamma=gamma,
            k=k,
            n_pairs=n_pairs,
            done=jnp.logical_or(converged, stalled),
            converged=converged,
            values=s.values.at[k].set(value_next.astype(s.values.dtype)),
            grad_norms=s.grad_norms.at[k].set(
                jnp.where(stalled, pnorm(s.grad, w_axis), g_norm)
            ),
        )

    final = lax.while_loop(cond, body, init)
    return SolveResult(
        w=final.w,
        value=final.value,
        grad=final.grad,
        iterations=final.k,
        converged=final.converged,
        values=final.values,
        grad_norms=final.grad_norms,
    )
