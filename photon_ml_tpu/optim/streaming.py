"""Out-of-core GLM training: stream host chunks through the chip per pass.

The resident solvers (optim/lbfgs.py) run the ENTIRE optimize loop inside
one jitted ``lax.while_loop`` — possible only because the dataset lives in
HBM.  When it does not (BASELINE.json's north-star configs are 1B rows ≈
hundreds of GB of slot data), the structure inverts to the reference's own
shape: the OUTER loop runs on the host (the reference's driver-side Breeze
L-BFGS — SURVEY.md §2 Optimizers), and each objective evaluation is one
full pass over the data (the ``treeAggregate`` analogue, SURVEY.md §3.1) —
here a three-stage software pipeline of host chunks, value/grad
accumulated on device:

    pack thread:     stack/slice chunk k+2's host buffers ──►
    transfer thread: chunk k+1 ──one coalesced transfer──► HBM
    caller thread:   HBM chunk k ──unpack+Pallas/XLA──► (value, grad) +=

Each chunk crosses as a few large dtype-segregated staging buffers
(data/staging.py), the pack and transfer stages run on their own threads
(data/prefetch.py) with ``prefetch_depth`` (default 2) chunks in flight,
and the consumer syncs on a bounded WINDOW of carries (it dispatches
chunk k's program, then waits only for chunk k-depth's carry), so the
device never idles during a chunk's Python dispatch.  Accumulator
buffers are donated back to XLA each step (in-place updates), HBM holds
O(``prefetch_depth``) chunks regardless of dataset size, and the f32
accumulation order stays strictly per-chunk-sequential — the async
pipeline is bit-identical to the ``prefetch_depth=1`` serial baseline
(pinned by tests/test_streaming.py).  ``chunk_fuse > 1`` additionally
stacks that many chunks per dispatch and folds them with an in-program
``lax.scan`` (same order, one dispatch), amortizing per-dispatch
overhead when chunks are small.

The inner per-chunk program is ONE jitted function for all chunks
(uniform shapes — see data/streaming.py) with the staging unpack traced
in, so there is one compile per solve (two with a ragged fused tail);
per-chunk transfer timing, per-stage wall attribution, and stall
counters accumulate on ``StreamingObjective.transfer_stats``.

Host-loop math mirrors lbfgs_solve step-for-step (same two-loop recursion
and history via the SAME jitted helpers, same weak-Wolfe bracketing, same
stall/convergence rules), so a single-chunk streamed solve lands on the
resident solution to float tolerance; tests/test_streaming.py pins that.
Line searches batch their trials: one streamed pass evaluates the current
candidate step PLUS its possible successors (vector-free-L-BFGS-style
pass fusion), so a bracketing search costs about half the passes of the
one-trial-per-pass loop while examining the identical candidate sequence.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import threading
import time
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.data.prefetch import TransferStats, run_prefetched
from photon_ml_tpu.data.staging import COMPRESSION_MODES, plan_compression
from photon_ml_tpu.data.streaming import StreamingGlmData
from photon_ml_tpu.parallel.compat import shard_map
from photon_ml_tpu.optim.lbfgs import (
    LBFGSConfig,
    SolveResult,
    _two_loop,
    update_history,
)
from photon_ml_tpu.optim.linesearch import LineSearchConfig
from photon_ml_tpu.optim.objective import GlmObjective
from photon_ml_tpu.optim.owlqn import OWLQNConfig, _pseudo_gradient

Array = jax.Array

#: candidate steps per batched weak-Wolfe pass: the current trial plus its
#: two possible bisection successors (see ``_host_wolfe``).
_WOLFE_TRIAL_BATCH = 3
#: candidate steps per batched OWL-QN Armijo pass (the geometric
#: backtracking ladder is fully deterministic, so any prefix batches).
_OWLQN_TRIAL_BATCH = 4


# ---------------------------------------------------------------------------
# Importance-aware HBM working set: hot chunks skip pack + transfer
# ---------------------------------------------------------------------------


class HotChunkCache:
    """Byte-budgeted resident working set of streamed chunk items.

    The DuHL idea (arXiv:1708.05357, PAPERS.md) applied to the chunk
    stream: keep the most-influential chunks RESIDENT in HBM and stream
    only the cold tail.  Importance is re-derived every accumulation
    pass, for free, from the per-chunk deltas of the value accumulator
    the streamed carry already computes — no extra device work.  A hot
    hit returns the (wire) device buffers directly, skipping pack,
    ``device_put`` and the transfer wait entirely; the SAME compiled
    per-chunk program serves hot and cold items, so results stay
    bitwise identical to the uncached path (accumulation order remains
    strictly chunk-sequential — the consumer interleaves hot hits into
    their global positions).

    Admission is one pass deferred by construction: pass N's scores
    pick the wanted set (:meth:`replan`), pass N+1 admits those items'
    device buffers as they stream by, pass N+2 onward hits.  Ties in
    the importance score break by item index, so admission is
    deterministic under equal scores (pinned by tests).

    The lock guards pure bookkeeping only (dict/set/counter updates);
    evicted device references are collected under the lock but DROPPED
    outside it, so buffer deallocation never runs in a critical section
    (the lock-blocking-call rule in analysis/ checks this discipline).
    Entries are never donated to XLA — chunk arguments are not in any
    program's ``donate_argnums`` — so a resident buffer stays valid
    across passes.

    With ``n_devices > 1`` the cached buffers are mesh-sharded, so a
    resident item pins only ``ceil(nbytes / n_devices)`` bytes on EACH
    device; ``budget_bytes`` then bounds the PER-DEVICE resident bytes
    (the quantity that actually competes with program HBM), not the
    logical total.  Admission/replan arithmetic uses that per-device
    cost throughout — the same budget number means the same per-device
    pressure whether the stream is sharded or not.
    """

    def __init__(self, budget_bytes: int, n_devices: int = 1):
        self.budget_bytes = int(budget_bytes)
        self.n_devices = max(1, int(n_devices))
        self._lock = sanitizers.tracked(
            threading.Lock(), "streaming.hot_cache"
        )
        self._entries: dict = {}  # item index -> (device bufs, nbytes)
        self._want: set = set()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.admissions = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def get(self, i: int):
        """Resident device buffers for item ``i``, or None (counted)."""
        with self._lock:
            e = self._entries.get(i)
            if e is None:
                self.misses += 1
                return None
            self.hits += 1
            return e[0]

    def maybe_admit(self, i: int, dev, nbytes: int) -> bool:
        """Admit item ``i``'s just-transferred device buffers iff the
        last replan wants it and it fits the remaining budget."""
        cost = -(-int(nbytes) // self.n_devices)  # per-device ceil
        with self._lock:
            if i in self._entries or i not in self._want:
                return False
            if self._bytes + cost > self.budget_bytes:
                return False
            self._entries[i] = (dev, cost)
            self._bytes += cost
            self.admissions += 1
            return True

    def replan(self, scores: dict, item_nbytes: Callable[[int], int]):
        """Recompute the wanted set from this pass's importance scores
        and evict residents that fell out of it.

        Greedy by descending score (ties broken by ascending item index
        — deterministic), packing until the byte budget is exhausted.
        On an injected eviction fault the cache is CLEARED before the
        fault propagates: a half-applied plan may never survive into
        the next pass (which then simply streams everything — results
        are unaffected either way, only transfer counts).
        """
        try:
            chaos_mod.maybe_fail("streaming.cache_evict")
        except BaseException:
            self.clear()
            raise
        dropped = []
        with self._lock:
            want: set = set()
            budget = self.budget_bytes
            for i in sorted(scores, key=lambda j: (-scores[j], j)):
                nb = -(-int(item_nbytes(i)) // self.n_devices)
                if nb <= budget:
                    want.add(i)
                    budget -= nb
            self._want = want
            for i in [j for j in self._entries if j not in want]:
                dev, nb = self._entries.pop(i)
                self._bytes -= nb
                self.evictions += 1
                dropped.append(dev)
        del dropped  # device refs released outside the lock

    def clear(self) -> None:
        with self._lock:
            dropped = list(self._entries.values())
            self._entries.clear()
            self._want = set()
            self._bytes = 0
        del dropped


# ---------------------------------------------------------------------------
# Streamed objective: value+grad as one pass over host chunks
# ---------------------------------------------------------------------------


class StreamingObjective:
    """A GlmObjective evaluated by streaming host chunks through the device.

    ``accumulate``: "f32" adds chunk contributions directly; "kahan"
    carries a compensation term per accumulator (value and gradient), so
    the cross-chunk summation error stays O(ε) instead of O(n_chunks·ε) —
    the scale-robust option for very long streams (the reference
    accumulates in f64 via Breeze; TPUs have no fast f64, compensation is
    the idiomatic equivalent).

    With ``mesh`` (and chunks built with ``n_shards == mesh size``) each
    chunk is placed sharded over the mesh's first axis and the per-chunk
    reduction runs under ``shard_map`` with one fused psum — streamed data
    parallelism.

    Transfers ride the coalesced ingest pipeline: each chunk moves as a
    few large dtype-segregated staging buffers (data/staging.py) whose
    compiled unpack is traced into the per-chunk program, and two
    background threads (pack + transfer, data/prefetch.py) keep
    ``prefetch_depth`` chunks in flight while the consumer syncs on a
    bounded window of carries — pack, transfer and compute overlap, and
    results stay bit-identical to ``prefetch_depth=1`` because the f32
    accumulation order is per-chunk-sequential either way.  HBM holds at
    most ``2·prefetch_depth`` chunks (``prefetch_depth`` transferred-not-
    consumed + a ``prefetch_depth``-deep window of dispatched-not-synced
    programs), times ``chunk_fuse`` when fusing.

    ``chunk_fuse > 1`` stacks that many chunks per transfer and folds
    them on device with ``lax.scan`` (one dispatch per group, same
    accumulation order) — for stores whose chunks are small enough that
    per-dispatch overhead dominates.  Single-device only (no mesh), and
    requires the staged (coalesced-buffer) representation.

    ``transfer_stats`` accumulates per-chunk h2d timing, achieved GB/s,
    per-stage wall attribution (pack/dispatch/h2d/consume) and
    queue-stall counters across passes — reset it around a measurement
    window (bench_streaming does).

    ``compress`` (off|lossless|fp16|int8) turns on the compressed chunk
    wire formats (data/staging.py): chunks cross the link as encoded
    wire buffers 2–4× smaller and are decoded ON DEVICE by the dequant
    step traced into each per-chunk program.  "lossless" keeps every
    streamed result BITWISE identical to the raw path; fp16/int8
    additionally quantize float feature values (bounded error, pinned
    by tests).  Requires the staged representation and a single-host
    run (per-process compression plans would compile divergent SPMD
    executables on a pod).  ``transfer_stats.bytes`` stays WIRE bytes;
    ``logical_bytes`` carries the decoded total.

    ``hot_budget_bytes`` > 0 enables the importance-aware HBM working
    set (:class:`HotChunkCache`): up to that many bytes of (wire)
    chunk buffers stay RESIDENT across passes, re-chosen each
    accumulation pass from per-chunk gradient-contribution importance,
    and hot chunks skip pack + transfer entirely.  Single-device only.
    Results are bitwise identical to the uncached path — the cache
    only changes which chunks cross the link, never the accumulation
    order.  (``scores()`` always streams: its readback pipeline does
    not consult the cache.)
    """

    def __init__(
        self,
        task_or_objective,
        stream: StreamingGlmData,
        normalization=None,
        mesh=None,
        accumulate: str = "f32",
        prefetch_depth: int = 2,
        chunk_fuse: int = 1,
        compress: str = "off",
        hot_budget_bytes: int = 0,
    ):
        from photon_ml_tpu.ops import losses as losses_lib

        if isinstance(task_or_objective, GlmObjective):
            self.objective = task_or_objective
        else:
            self.objective = GlmObjective(
                losses_lib.get(task_or_objective), normalization
            )
        if accumulate not in ("f32", "kahan"):
            raise ValueError(f"accumulate must be f32|kahan, got {accumulate}")
        if prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {prefetch_depth}"
            )
        if chunk_fuse < 1:
            raise ValueError(f"chunk_fuse must be >= 1, got {chunk_fuse}")
        if chunk_fuse > 1 and mesh is not None:
            raise ValueError(
                "chunk_fuse > 1 is single-device only: the scan-fused "
                "program is not composed with the shard_map reduction — "
                "pass chunk_fuse=1 with a mesh"
            )
        if compress not in COMPRESSION_MODES:
            raise ValueError(
                f"compress must be one of {COMPRESSION_MODES}, got "
                f"{compress!r}"
            )
        if hot_budget_bytes < 0:
            raise ValueError(
                f"hot_budget_bytes must be >= 0, got {hot_budget_bytes}"
            )
        if hot_budget_bytes and mesh is not None and jax.process_count() > 1:
            raise ValueError(
                "the hot working-set cache is single-host only: on a "
                "pod each process would pin a divergent resident set "
                "and the SPMD dispatch order would skew across hosts — "
                "pass hot_budget_bytes=0 in multi-host mode"
            )
        self.stream = stream
        self.mesh = mesh
        self.accumulate = accumulate
        self.prefetch_depth = int(prefetch_depth)
        self.chunk_fuse = int(chunk_fuse)
        self.transfer_stats = TransferStats()
        # Coalesce to staging buffers (no-op when the builder already
        # did); falls back to per-leaf pytree transfers only for
        # hand-built disk-backed stores, which cannot pack in RAM.
        stream.ensure_staged()
        self._staging = stream.staging
        if self.chunk_fuse > 1 and stream.staged is None:
            raise ValueError(
                "chunk_fuse > 1 needs the staged (coalesced-buffer) "
                "representation — this store could not be staged "
                "(hand-built disk-backed per-leaf store?)"
            )
        # Fused transfer groups: consecutive chunk ranges of chunk_fuse
        # (the last one ragged).  With chunk_fuse == 1 the pipeline runs
        # per chunk and this grouping is the identity.
        n_ch = stream.n_chunks
        fuse = min(self.chunk_fuse, max(n_ch, 1))
        self._groups = [
            range(lo, min(lo + fuse, n_ch)) for lo in range(0, n_ch, fuse)
        ]
        self._sharding = None
        # Multi-host (pod) mode: every process holds a chunk store over
        # ITS host-local rows only (n_shards = local device count) and
        # feeds just its own shards of each globally-sharded chunk — the
        # streamed analogue of multihost.assemble_global, so no host ever
        # materializes a global chunk.  Row order across hosts differs
        # from the single-host layout, which is immaterial: every
        # streamed reduction is a permutation-invariant sum over rows.
        self._multihost = jax.process_count() > 1
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            expect = (
                jax.local_device_count() if self._multihost
                else mesh.devices.size
            )
            if stream.n_shards != expect:
                raise ValueError(
                    f"stream has n_shards={stream.n_shards}; this "
                    f"{'process' if self._multihost else 'mesh'} needs "
                    f"{expect}"
                )
            if stream.n_shards == 1 and not self._multihost:
                # Single-shard chunks carry NO shard axis (data/streaming
                # builds the stacked layout only for n_shards > 1).  The
                # mesh path's x[0] unstack would then strip a DATA axis
                # and silently compute the objective over wrong slices —
                # no error, wrong numbers (verified).  Refuse loudly.
                raise ValueError(
                    "single-shard chunks carry no shard axis; the mesh "
                    "path would silently compute over wrong data — pass "
                    "mesh=None for single-device streams"
                )
            if stream.n_shards == 1 and self._multihost:
                raise ValueError(
                    "multi-host streams need n_shards == "
                    "jax.local_device_count() > 1 per process; a "
                    "1-local-device pod member is unsupported"
                )
            if self._multihost:
                self._align_multihost_chunks()
            self._axis = mesh.axis_names[0]
            self._sharding = NamedSharding(mesh, P(self._axis))
        elif stream.n_shards != 1:
            raise ValueError("sharded chunks need a mesh")

        # Compressed chunk formats: plan one codec over the whole store
        # (AFTER any multihost equalization so padding chunks are
        # scanned too), encode every chunk's wire buffers eagerly (host
        # RAM cost ≈ staged bytes / ratio — the raw staged store stays
        # the source of truth for host-side views), and route the
        # per-chunk unpack through the codec's on-device decode.
        self.compress = compress
        self._codec = None
        self._wire = None
        if compress != "off":
            if stream.staged is None:
                raise ValueError(
                    "compress != 'off' needs the staged (coalesced-"
                    "buffer) representation — this store could not be "
                    "staged (hand-built disk-backed per-leaf store?)"
                )
            if self._multihost:
                raise ValueError(
                    "compress != 'off' is single-host only: each "
                    "process would plan its own encodings from its own "
                    "rows and compile divergent SPMD executables — "
                    "pass compress='off' on a pod"
                )
            self._codec = plan_compression(
                self._staging, stream.staged, compress
            )
            self._wire = [
                self._codec.encode(bufs) for bufs in stream.staged
            ]
        # Importance-aware HBM working set (see class docstring for the
        # admit-next-pass lifecycle).  Under a mesh the cached buffers
        # are the sharded wire trees, so the budget counts per-device
        # bytes — n_devices divides each entry's cost.
        self.hot_budget_bytes = int(hot_budget_bytes)
        if hot_budget_bytes and stream.staged is None:
            raise ValueError(
                "hot_budget_bytes > 0 needs the staged representation "
                "(byte-budgeted admission requires the fixed per-chunk "
                "staged size)"
            )
        self._hot_cache = (
            HotChunkCache(
                hot_budget_bytes,
                n_devices=(1 if mesh is None else int(mesh.devices.size)),
            )
            if hot_budget_bytes
            else None
        )

        obj = self.objective
        staging = self._staging
        codec = self._codec

        def unpack(chunk_in):
            # The compiled on-device unpack (slice + reshape) restoring
            # the GlmData view from the coalesced staging buffers —
            # traced INTO each per-chunk program, so coalescing costs no
            # extra dispatch.  Identity for unstaged (fallback) streams.
            # Under shard_map the buffers arrive as per-device blocks;
            # unpack_device reads the local leading dim off the trace.
            # With a codec the arriving buffers are the COMPRESSED wire
            # buffers and this is the in-program dequant step (slice +
            # cast + cumsum/shift), same relative-slicing contract.
            if codec is not None:
                return codec.unpack_device(chunk_in)
            if staging is None:
                return chunk_in
            return staging.unpack_device(chunk_in)

        def chunk_vg(w, off, chunk):
            # ``off``: extra per-row margin offsets (coordinate descent —
            # the other coordinates' scores); a traced scalar 0 when
            # absent, so the plain-GLM trace carries no extra transfer.
            # Under a mesh, a non-scalar ``off`` arrives SHARDED like the
            # chunk (leading shard axis) — the streamed-GAME × DP
            # composition.
            chunk = unpack(chunk)
            if mesh is not None:
                local = jax.tree.map(lambda x: x[0], chunk)
                off_local = off if off.ndim == 0 else off[0]
                local = dataclasses.replace(
                    local, offsets=local.offsets + off_local
                )
                v, g = obj.raw_value_and_grad(w, local)
                return lax.psum(v, self._axis), lax.psum(g, self._axis)
            chunk = dataclasses.replace(chunk, offsets=chunk.offsets + off)
            return obj.raw_value_and_grad(w, chunk)

        def chunk_hvp(w, v, off, chunk):
            # Recomputes the d2 weights inside the chunk program (one extra
            # margins matvec) — the streamed analogue of the reference's
            # HessianVectorAggregator, which recomputes per-row d2 on every
            # treeAggregate round (SURVEY.md §3.1).  The resident TRON's
            # per-iterate d2 cache (optim/tron.py) is an HBM-resident
            # luxury the chunk store deliberately forgoes: caching would
            # mean either holding n_rows of d2 weights in HBM (not
            # out-of-core) or round-tripping them host↔device per CG step.
            chunk = unpack(chunk)
            if mesh is not None:
                local = jax.tree.map(lambda x: x[0], chunk)
                off_local = off if off.ndim == 0 else off[0]
                local = dataclasses.replace(
                    local, offsets=local.offsets + off_local
                )
                return lax.psum(obj.raw_hvp(w, v, local), self._axis)
            chunk = dataclasses.replace(chunk, offsets=chunk.offsets + off)
            return obj.raw_hvp(w, v, chunk)

        def chunk_diag(w, off, chunk):
            chunk = unpack(chunk)
            if mesh is not None:
                local = jax.tree.map(lambda x: x[0], chunk)
                off_local = off if off.ndim == 0 else off[0]
                local = dataclasses.replace(
                    local, offsets=local.offsets + off_local
                )
                d2w = obj.d2_weights(w, local)
                return lax.psum(
                    local.features.sq_rmatvec(d2w), self._axis
                )
            chunk = dataclasses.replace(chunk, offsets=chunk.offsets + off)
            d2w = obj.d2_weights(w, chunk)
            return chunk.features.sq_rmatvec(d2w)

        def score_step(w, chunk):
            chunk = unpack(chunk)
            if mesh is not None:
                local = jax.tree.map(lambda x: x[0], chunk)
                return obj.margins(w, local)
            return obj.margins(w, chunk)

        def acc_update(carry, v, g):
            # Shared f32/kahan accumulator fold; elementwise, so the SAME
            # formulas serve the plain and the batched ((K,)/(K,d)) carry.
            if accumulate == "f32":
                vacc, gacc = carry
                return (vacc + v, gacc + g)
            vacc, vc, gacc, gc = carry
            yv = v - vc
            tv = vacc + yv
            vc = (tv - vacc) - yv
            yg = g - gc
            tg = gacc + yg
            gc = (tg - gacc) - yg
            return (tv, vc, tg, gc)

        def hvp_update(carry, h):
            if accumulate == "f32":
                return (carry[0] + h,)
            hacc, hc = carry
            yh = h - hc
            th = hacc + yh
            return (th, (th - hacc) - yh)

        # Flattened step functions: ``step(*carry, *args, off, chunk) ->
        # carry tuple``.  The carry is flattened into SEPARATE positional
        # args so donation can target just the gradient accumulators
        # (donate_argnums is per-argument) while the value scalar stays
        # un-donated — it is the windowed-sync handle _stream_accumulate
        # blocks on (a donated buffer cannot be synced: it is deleted the
        # moment the next step consumes it).
        self._n_carry = {
            "acc": 2 if accumulate == "f32" else 4,
            "hvp": 1 if accumulate == "f32" else 2,
            "diag": 1,
        }
        self._n_args = {"acc": 1, "hvp": 2, "diag": 1}
        # Gradient/HVP accumulators update IN PLACE via buffer donation.
        # The value scalar (leaf 0 of "acc") is deliberately NOT donated:
        # it is the sync handle.  "hvp"/"diag" carries are their own sync
        # handles, so they are not donated either.
        self._donate = {
            "acc": (1,) if accumulate == "f32" else (2, 3),
            "hvp": (),
            "diag": (),
        }

        def make_step(kind: str, batch: int | None):
            nc = self._n_carry[kind]

            def step(*fl):
                carry = fl[:nc]
                off, chunk = fl[-2], fl[-1]
                if kind == "acc":
                    w = fl[nc]
                    if batch is None:
                        v, g = chunk_vg(w, off, chunk)
                    else:
                        # UNROLLED over the K candidates, not vmapped:
                        # each candidate's arithmetic is the exact graph
                        # the single-w program runs, so a batched trial
                        # matches a sequential trial bitwise (vmap would
                        # re-block the matvecs by batch shape — the same
                        # parity hazard serving/kernels.py documents).
                        outs = [
                            chunk_vg(w[i], off, chunk) for i in range(batch)
                        ]
                        v = jnp.stack([o[0] for o in outs])
                        g = jnp.stack([o[1] for o in outs])
                    return acc_update(carry, v, g)
                if kind == "hvp":
                    w, vec = fl[nc], fl[nc + 1]
                    return hvp_update(carry, chunk_hvp(w, vec, off, chunk))
                diag = carry[0]
                w = fl[nc]
                return (diag + chunk_diag(w, off, chunk),)

            return step

        def fuse_step(step, kind: str, n_fused: int):
            nc = self._n_carry[kind]
            na = self._n_args[kind]

            def fused(*fl):
                carry = tuple(fl[:nc])
                rest = fl[nc:nc + na]
                off, chunk = fl[-2], fl[-1]

                def body(c, xs):
                    o, b = xs
                    return tuple(step(*c, *rest, o, b)), None

                out, _ = lax.scan(body, carry, (off, chunk), length=n_fused)
                return out

            return fused

        self._make_step = make_step
        self._fuse_step = fuse_step
        self._score_step = score_step
        self._progs: dict = {}

        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            self._chunk_spec = P(self._axis)
            self._score = jax.jit(shard_map(
                score_step, mesh=mesh,
                in_specs=(P(), self._chunk_spec), out_specs=self._chunk_spec,
                check_vma=False,
            ))
        else:
            self._score = jax.jit(score_step)
        self._finish = jax.jit(
            lambda v, g, w, l2: (
                v + 0.5 * l2 * jnp.dot(w, w), g + l2 * w
            )
        )
        self._finish_batch = jax.jit(
            lambda v, g, w, l2: (
                v + 0.5 * l2 * jnp.einsum("kd,kd->k", w, w), g + l2 * w
            )
        )
        self._hvp_finish = jax.jit(lambda h, v, l2: h + l2 * v)

    @property
    def n_features(self) -> int:
        return self.stream.n_features

    def _program(self, kind: str, n_fused: int = 1, batch: int | None = None,
                 row_off: bool = False) -> Callable:
        """The compiled per-item program for pass ``kind`` — built lazily
        and cached per (fused length, trial-batch width, offset kind).
        One compile per solve in the common case; a ragged fused tail
        adds one more."""
        key = (kind, n_fused, batch, row_off)
        prog = self._progs.get(key)
        if prog is not None:
            return prog
        step = self._make_step(kind, batch)
        if self.mesh is not None:
            from jax.sharding import PartitionSpec as P

            nc = self._n_carry[kind]
            na = self._n_args[kind]
            carry_specs = (P(),) * nc
            off_spec = self._chunk_spec if row_off else P()
            step = shard_map(
                step, mesh=self.mesh,
                in_specs=carry_specs + (P(),) * na
                + (off_spec, self._chunk_spec),
                out_specs=carry_specs, check_vma=False,
            )
        elif n_fused > 1:
            step = self._fuse_step(step, kind, n_fused)
        prog = jax.jit(step, donate_argnums=self._donate[kind])
        self._progs[key] = prog
        return prog

    def _align_multihost_chunks(self) -> None:
        """Pod-wide agreement checks the streamed loop's collectives need.

        Every process runs one psum per chunk, so (a) chunk COUNTS must
        match — an uneven ``host_local_rows`` split is equalized by
        appending all-padding (zero-weight) chunks locally, which add
        exactly zero to every reduction; (b) chunk leaf SHAPES must match
        — each process's store pads to its OWN nnz budget / layout, and a
        mismatch would compile different SPMD executables per process
        (hang or crash deep in XLA), so it is refused loudly here with
        the fix spelled out."""
        import zlib

        from jax.experimental import multihost_utils

        chunks = self.stream.chunks
        leaves = jax.tree.leaves(chunks[0])
        # The structure signature is hashed to a SCALAR before the
        # allgather: a raw per-leaf shape vector would have a
        # process-dependent LENGTH exactly when structures mismatch, and
        # process_allgather on ragged inputs dies (or hangs) deep in the
        # collective instead of reaching the explanatory error below.
        shape_sig = ",".join(
            f"{len(leaf.shape)}:{leaf.shape}" for leaf in leaves
        )
        crc = zlib.crc32(f"{len(leaves)}|{shape_sig}".encode())
        sig = np.asarray([len(chunks), crc], np.int64)
        all_sigs = np.asarray(multihost_utils.process_allgather(sig))
        if not (all_sigs[1:, 1] == all_sigs[0, 1]).all():
            raise ValueError(
                "multi-host chunk stores have mismatched leaf shapes "
                "across processes (per-process nnz budgets / layouts "
                "differ) — build every process's store with the same "
                "chunk_rows and a COMMON coo_budget "
                "(make_streaming_glm_data(..., coo_budget=N)), and "
                "use_pallas=False"
            )
        max_chunks = int(all_sigs[:, 0].max())
        if len(chunks) < max_chunks:
            pad = max_chunks - len(chunks)
            if self.stream.staged is not None:
                # Equalization chunks ride the staged representation
                # too: one shared all-zero buffer set (read-only) and a
                # view over it, so every transfer path stays coalesced.
                blank_bufs = tuple(
                    np.zeros_like(np.asarray(b))
                    for b in self.stream.staged[0]
                )
                blank = self.stream.staging.view(blank_bufs)
                self.stream.staged = (
                    list(self.stream.staged) + [blank_bufs] * pad
                )
            else:
                blank = jax.tree.map(np.zeros_like, chunks[0])
            self.stream.chunks = chunks + [blank] * pad
        # The fused grouping is sized off n_chunks; re-derive after any
        # equalization padding (fusion is single-device-only today, but
        # keep the invariant locally true).
        n_ch = self.stream.n_chunks
        fuse = min(self.chunk_fuse, max(n_ch, 1))
        self._groups = [
            range(lo, min(lo + fuse, n_ch)) for lo in range(0, n_ch, fuse)
        ]

    def _put_local_block(self, x) -> Array:
        """Assemble one globally-sharded array from THIS process's local
        shard block (multihost.assemble_global's contract): global shard
        axis = processes x local shards, this process's block slotting in
        at its process index."""
        total = self.mesh.devices.size
        gshape = (total,) + tuple(x.shape[1:])
        return jax.make_array_from_process_local_data(
            self._sharding, np.asarray(x), gshape
        )

    def _put(self, chunk):
        chaos_mod.maybe_fail("staging.put")
        if self._sharding is not None:
            if self._multihost:
                # Each process contributes ONLY its local shard block of
                # the global chunk, per leaf.
                return jax.tree.map(self._put_local_block, chunk)
            return jax.device_put(chunk, self._sharding)
        return jax.device_put(chunk)

    def offset_slices(self, offsets) -> list:
        """Per-chunk slices of coordinate-descent offsets (the other
        coordinates' scores), zero-padded to the chunk grid; a traced
        scalar 0 per chunk when absent (no extra transfer, own trace).
        Callers evaluating many passes against FIXED offsets (a whole
        L-BFGS solve) should call this once and pass the list to
        ``value_and_grad`` — it is accepted in place of the raw array."""
        if isinstance(offsets, list):  # already sliced
            return offsets
        cr = self.stream.chunk_rows
        n_chunks = self.stream.n_chunks
        if offsets is None:
            zero = jnp.zeros((), jnp.float32)
            return [zero] * n_chunks
        if offsets.shape[0] != self.stream.n_rows:
            # A silently zero-padded short array would train the tail rows
            # against offset 0 and converge to a wrong model.
            raise ValueError(
                f"offsets has {offsets.shape[0]} rows; the stream has "
                f"{self.stream.n_rows}"
            )
        if self.mesh is not None:
            # Streamed GAME × DP: each chunk's offset slice is reshaped to
            # the chunk's (shard, row) grid and placed SHARDED over the
            # mesh, so the per-chunk program adds it to the local rows with
            # no gather (row k of shard s is chunk row s·per_shard + k,
            # matching data/streaming's reshape layout).
            #
            # On a POD, per-row CD state is PROCESS-LOCAL (the reference's
            # layout: score RDDs live partitioned next to the data): the
            # offsets are THIS PROCESS's rows — exactly the rows its chunk
            # store holds — and each reshaped slice feeds only the local
            # shard block of the global chunk, the same assemble_global
            # contract the data chunks use.  Blank equalization chunks
            # (appended past the local rows) get zero offsets from the
            # padding below, matching their zero weights.
            n_sh = self.stream.n_shards
            off = np.asarray(offsets, np.float32)
            pad = n_chunks * cr - off.shape[0]
            if pad:
                off = np.pad(off, (0, pad))
            blocks = [
                off[k * cr:(k + 1) * cr].reshape(n_sh, cr // n_sh)
                for k in range(n_chunks)
            ]
            if self._multihost:
                return [self._put_local_block(b) for b in blocks]
            return [
                jax.device_put(b, self._sharding) for b in blocks
            ]
        off = jnp.asarray(offsets, jnp.float32)
        pad = n_chunks * cr - off.shape[0]
        if pad:
            off = jnp.pad(off, (0, pad))
        return [off[k * cr:(k + 1) * cr] for k in range(n_chunks)]

    def _host_item(self, k: int):
        """What crosses the wire for chunk ``k``: the encoded wire
        buffers when compressing, else the coalesced staging buffers
        when the store is staged, else the leaf pytree."""
        if self._wire is not None:
            return self._wire[k]
        if self.stream.staged is not None:
            return self.stream.staged[k]
        return self.stream.chunks[k]

    def _fused_host_item(self, g: int):
        """Fused group ``g``'s transfer item: the group's staging buffers
        stacked on a new leading chunk axis (the scan axis of the fused
        program).  The stack is a transient host copy that runs on the
        PACK thread, where it overlaps both the link and device compute;
        memmapped (disk-backed) buffers page in here too.  A singleton
        group (the ragged tail) stays a plain un-stacked chunk item and
        runs the ordinary per-chunk program."""
        ks = self._groups[g]
        staged = (
            self._wire if self._wire is not None else self.stream.staged
        )
        if len(ks) == 1:
            return staged[ks[0]]
        n_buf = len(staged[ks[0]])
        return tuple(
            np.stack([np.asarray(staged[k][b]) for k in ks])
            for b in range(n_buf)
        )

    def _group_offsets(self, slices: list) -> list:
        """Per-ITEM offsets under fusion: each group's per-chunk slices
        stacked on the scan axis (identity when chunk_fuse == 1;
        singleton groups keep their plain per-chunk slice)."""
        if self.chunk_fuse == 1:
            return slices
        return [
            slices[grp[0]] if len(grp) == 1
            else jnp.stack([slices[k] for k in grp])
            for grp in self._groups
        ]

    def _stream_accumulate(self, kind: str, init: tuple, args=(),
                           per_chunk=None, batch: int | None = None):
        """Run ``carry = prog(*carry, *args, off_i, item_i)`` over all
        chunks (or fused chunk groups) through the prefetch pipeline,
        syncing on a bounded WINDOW of carries.

        The pack and transfer threads keep ``prefetch_depth`` items in
        flight (data/prefetch.py); the consumer dispatches item k's
        program and then blocks only on item ``k - prefetch_depth``'s
        sync handle, so the device always has up to ``prefetch_depth``
        programs queued behind the executing one and never idles during
        a chunk's Python dispatch.  The window is the backpressure that
        bounds HBM residency: a dispatched-but-unexecuted program pins
        its chunk's buffers, so ≤ ``2·prefetch_depth`` chunk groups are
        ever live (``prefetch_depth`` un-consumed transfers + the
        window).  ``prefetch_depth=1`` degrades to the fully-serial
        sync-every-chunk baseline.  The sync handle is carry leaf 0,
        which is never donated (see ``__init__``); gradient accumulators
        ARE donated, updating in place.  Accumulation order is strictly
        chunk-sequential regardless of depth/window/fusion — results are
        bit-identical across all of them on f32.

        With the hot working-set cache enabled, resident items bypass
        the pipeline entirely: only the cold tail rides
        ``run_prefetched``, and the consumer interleaves each hot
        item's dispatch at its exact global position before the next
        cold item — the accumulation order (and therefore every f32
        bit) is unchanged.  On "acc" passes the synced carry handles
        double as the importance source: |Δvalue| per item scores the
        pass for free, and the cache replans (admit set + evictions)
        ONCE at pass end.
        """
        if self.chunk_fuse == 1:
            n_items = self.stream.n_chunks
            get_host = self._host_item
            items_off = per_chunk
            lens = None  # all programs identical
        else:
            n_items = len(self._groups)
            get_host = self._fused_host_item
            items_off = self._group_offsets(per_chunk)
            lens = [len(g) for g in self._groups]
        row_off = (
            self.mesh is not None
            and getattr(per_chunk[0], "ndim", 0) != 0
        )
        if lens is None:
            prog = self._program(kind, 1, batch, row_off)
            progs = [prog] * n_items
        else:
            progs = [
                self._program(kind, L, batch, row_off) for L in lens
            ]
        window = 0 if self.prefetch_depth == 1 else self.prefetch_depth
        carry_box = [tuple(init)]
        ring: collections.deque = collections.deque()
        ring_peak = 0
        stats = self.transfer_stats
        bytes0, chunks0 = stats.bytes, stats.chunks
        codec = self._codec
        cache = self._hot_cache
        hot0 = (
            (cache.hits, cache.misses, cache.admissions, cache.evictions)
            if cache is not None else None
        )
        st_nbytes = self._staging.nbytes if self._staging else 0

        def item_logical(i: int) -> int:
            # Decoded (staged) bytes item i stands for; × group length
            # under fusion.
            return st_nbytes * (lens[i] if lens else 1)

        def item_wire(i: int) -> int:
            wb = codec.wire_nbytes if codec is not None else st_nbytes
            return wb * (lens[i] if lens else 1)

        t_pass0 = time.perf_counter()
        # Importance scoring: only accumulation passes carry a scalar
        # value whose per-item delta is the chunk's contribution (hvp/
        # diag carries are vectors) — other kinds still SERVE hits, they
        # just don't replan.
        scoring = {} if (cache is not None and kind == "acc") else None
        vprev = [0.0]

        def sync_handle(entry):
            i, h = entry
            jax.block_until_ready(h)
            if scoring is not None:
                # |Δvalue| this item added to the running accumulator —
                # free importance (the handle is already synced; the
                # readback is one scalar, K for batched trials where
                # candidate 0 — the current iterate — scores).
                v = float(np.asarray(h).reshape(-1)[0])
                scoring[i] = abs(v - vprev[0])
                vprev[0] = v

        def dispatch(i, dev):
            # One item's program dispatch + windowed sync, identical
            # for hot (cache-resident) and cold (just-transferred)
            # items — the shared path is what keeps hot/cold bitwise
            # interchangeable.
            nonlocal ring_peak
            if codec is not None:
                chaos_mod.maybe_fail("staging.decode", item=i)
            chaos_mod.maybe_fail("streaming.carry_sync", item=i)
            carry_box[0] = progs[i](
                *carry_box[0], *args, items_off[i], dev
            )
            ring.append((i, carry_box[0][0]))
            if len(ring) > window:
                sync_handle(ring.popleft())
            # Post-sync occupancy: dispatched-but-unexecuted programs
            # still pinning their chunk buffers (the popped handle just
            # proved its chunk executed).
            ring_peak = max(ring_peak, len(ring))

        # Hot/cold split for this pass: resident items skip pack +
        # transfer; the cold tail streams.  The gather is one locked
        # dict probe per item, before any thread starts.
        hot: dict = {}
        if cache is not None:
            for i in range(n_items):
                d = cache.get(i)
                if d is not None:
                    hot[i] = d
        cold = [i for i in range(n_items) if i not in hot]
        next_i = [0]  # next global item index still to dispatch

        def advance_hot(upto: int) -> None:
            # Dispatch every not-yet-dispatched HOT item below ``upto``
            # — called before each cold item (and once at the end) so
            # the global dispatch order is exactly 0..n_items-1.
            while next_i[0] < upto:
                j = next_i[0]
                if j in hot:
                    dispatch(j, hot[j])
                next_i[0] = j + 1

        def consume(ci, dev):
            i = cold[ci]
            advance_hot(i)
            dispatch(i, dev)
            next_i[0] = i + 1
            if cache is not None:
                cache.maybe_admit(i, dev, item_wire(i))

        run_max = run_prefetched(
            len(cold), lambda ci: get_host(cold[ci]), self._put, consume,
            depth=self.prefetch_depth, stats=stats,
            logical_nbytes=(
                (lambda ci: item_logical(cold[ci]))
                if codec is not None else None
            ),
        )
        advance_hot(n_items)  # trailing hot items past the last cold one
        while ring:
            # Drain: the carry chain is sequential, so the LAST handle's
            # readiness implies every chunk executed (and every chunk
            # buffer is collectable) before the pass returns.  When
            # scoring, each handle is read back in order instead.
            entry = ring.popleft()
            if scoring is not None or not ring:
                sync_handle(entry)
        if scoring:
            # Admission is one pass deferred: this replan's wanted set
            # admits during the NEXT pass's stream.  A chaos eviction
            # fault propagates from here (cache already cleared).
            cache.replan(scoring, item_wire)
        # HBM accounting for the carry window (docs/telemetry.md "HBM
        # accounting"): a dispatched-but-unexecuted program pins its
        # chunk's buffers beyond the prefetch permit, so the pass's true
        # staged-buffer residency peak is (live transfers + window
        # occupancy) x per-chunk staged bytes — the measured counterpart
        # of the documented <= 2·depth·chunk bound, and the number
        # ROADMAP item 1's working-set cache must beat.  One gauge write
        # per PASS, nothing per chunk.
        tel = telemetry_mod.current()
        if tel.enabled:
            # Every streamed pass is one logical all-reduce round: the
            # chunk-sequential accumulation folds a (batch × (d+1)) carry
            # exactly like a psum across shards.  Publishing it here puts
            # the jit-kind solvers on the same instrument the distributed
            # solvers (solvers/admm.py, solvers/block_cd.py) report on, so
            # BENCH_ONLY=solvers A/Bs reduces-per-solve directly.
            tel.counter("solver_allreduce_count").inc(1)
            tel.counter("solver_allreduce_bytes_total").inc(
                (batch or 1) * (self.stream.n_features + 1) * 4
            )
            d_chunks = stats.chunks - chunks0
            if d_chunks > 0:
                chunk_bytes = (stats.bytes - bytes0) / d_chunks
                tel.gauge("hbm_stream_chunk_bytes").set(int(chunk_bytes))
                tel.gauge("hbm_stream_window_peak_bytes").set(
                    int((run_max + ring_peak) * chunk_bytes)
                )
            if codec is not None or cache is not None:
                # Effective ingest rate: LOGICAL bytes of every item the
                # pass processed (hot hits move zero wire bytes but
                # stand for their full decoded size) over the pass wall
                # — the number compression + caching actually move,
                # where h2d_gbps honestly reports only the link.
                wall = time.perf_counter() - t_pass0
                if wall > 0.0:
                    tel.gauge("stream_effective_gbps").set(
                        sum(item_logical(i) for i in range(n_items))
                        / wall / 1e9
                    )
            if cache is not None:
                d_hit = cache.hits - hot0[0]
                d_miss = cache.misses - hot0[1]
                tel.counter("stream_hot_hits_total").inc(d_hit)
                tel.counter("stream_hot_misses_total").inc(d_miss)
                tel.counter("stream_hot_admissions_total").inc(
                    cache.admissions - hot0[2]
                )
                tel.counter("stream_hot_evictions_total").inc(
                    cache.evictions - hot0[3]
                )
                if d_hit + d_miss:
                    tel.gauge("stream_hot_hit_ratio").set(
                        d_hit / (d_hit + d_miss)
                    )
                tel.gauge("hbm_hot_bytes").set(cache.resident_bytes)
                tel.gauge("hbm_hot_budget_bytes").set(cache.budget_bytes)
                tel.gauge("hbm_hot_chunk_count").set(len(cache))
        return carry_box[0]

    def _acc_init(self, batch: int | None):
        d = self.stream.n_features
        shp_v = () if batch is None else (batch,)
        shp_g = (d,) if batch is None else (batch, d)
        if self.accumulate == "f32":
            return (jnp.zeros(shp_v, jnp.float32),
                    jnp.zeros(shp_g, jnp.float32))
        return (
            jnp.zeros(shp_v, jnp.float32), jnp.zeros(shp_v, jnp.float32),
            jnp.zeros(shp_g, jnp.float32), jnp.zeros(shp_g, jnp.float32),
        )

    def value_and_grad(
        self, w: Array, l2_weight=0.0, offsets=None
    ) -> tuple[Array, Array]:
        """One full streamed pass; returns device (value, grad) with the L2
        term applied.  ``offsets``: optional (n_rows,) extra margins added
        per row (coordinate descent)."""
        slices = self.offset_slices(offsets)
        out = self._stream_accumulate(
            "acc", self._acc_init(None), args=(w,), per_chunk=slices,
        )
        v, g = (out[0], out[1]) if self.accumulate == "f32" else (
            out[0], out[2]
        )
        return self._finish(v, g, w, jnp.asarray(l2_weight, jnp.float32))

    def value_and_grad_batch(
        self, ws: Array, l2_weight=0.0, offsets=None
    ) -> tuple[Array, Array]:
        """K objective evaluations in ONE streamed pass: ``ws`` is (K, d)
        candidate weight vectors (a line search's trial bracket), the
        per-chunk program evaluates all K against each chunk (unrolled,
        not vmapped — each candidate runs the exact single-w graph, so a
        batched trial is bitwise the sequential trial), and K (value,
        grad) accumulators ride one carry.  Returns ((K,), (K, d)) with
        the L2 term applied per candidate.  This is the vector-free
        L-BFGS pass-fusion trick: the line search streams the dataset
        once per BRACKET instead of once per trial."""
        ws = jnp.asarray(ws)
        if ws.ndim != 2:
            raise ValueError(
                f"value_and_grad_batch wants (K, n_features), got "
                f"{ws.shape}"
            )
        K = int(ws.shape[0])
        slices = self.offset_slices(offsets)
        out = self._stream_accumulate(
            "acc", self._acc_init(K), args=(ws,), per_chunk=slices,
            batch=K,
        )
        v, g = (out[0], out[1]) if self.accumulate == "f32" else (
            out[0], out[2]
        )
        return self._finish_batch(
            v, g, ws, jnp.asarray(l2_weight, jnp.float32)
        )

    def hessian_diagonal(self, w: Array, offsets=None) -> Array:
        """Σᵢ wᵢ·d2ᵢ·X²ᵢⱼ streamed over chunks (for coefficient variances)."""
        d = self.stream.n_features
        slices = self.offset_slices(offsets)
        return self._stream_accumulate(
            "diag", (jnp.zeros((d,), jnp.float32),),
            args=(w,), per_chunk=slices,
        )[0]

    def hvp(self, w: Array, v: Array, l2_weight=0.0, offsets=None) -> Array:
        """H(w)·v = Xᵀ(d2w ⊙ (Xv)) + λ·v as ONE streamed pass over the
        chunks — the ``HessianVectorAggregator`` ``treeAggregate`` round of
        the reference's distributed TRON (SURVEY.md §3.1), here a
        windowed-async chunk stream.  Callers issuing many HVPs against
        fixed offsets (a whole CG solve) should pre-slice via
        :meth:`offset_slices` and pass the list."""
        d = self.stream.n_features
        zero = jnp.zeros((d,), jnp.float32)
        init = (zero,) if self.accumulate == "f32" else (zero, zero)
        slices = self.offset_slices(offsets)
        h = self._stream_accumulate(
            "hvp", init, args=(w, v), per_chunk=slices,
        )[0]
        return self._hvp_finish(h, v, jnp.asarray(l2_weight, jnp.float32))

    def scores(self, w: Array) -> np.ndarray:
        """Margins for every row of THIS STORE, streamed, with the
        device→host readbacks pipelined: each chunk's margins start an
        ASYNC D2H copy at dispatch and materialize a window of
        ``prefetch_depth`` chunks behind, so readback latency overlaps
        the next chunks' transfer + compute instead of serializing the
        pass.

        On a pod the contract is PROCESS-LOCAL (the defined edge VERDICT
        r4 missing #3 asked for): each process gets the margins of its
        own rows — the rows its chunk store holds — read from its
        addressable shards of the globally-sharded per-chunk result
        (that path keeps the synchronous shard readback).  GLOBAL
        metrics over these scores reduce with one psum
        (evaluation/device.py) or an explicit allgather, never by
        materializing global rows on one host."""
        fused = self.chunk_fuse > 1
        if fused:
            n_items = len(self._groups)
            get_host = self._fused_host_item
            progs = [
                self._score if len(g) == 1 else self._score_fused(len(g))
                for g in self._groups
            ]
        else:
            n_items = self.stream.n_chunks
            get_host = self._host_item
            progs = [self._score] * n_items
        outs: list = [None] * n_items
        window = 0 if self.prefetch_depth == 1 else self.prefetch_depth
        pend: collections.deque = collections.deque()

        def materialize(j, m):
            outs[j] = np.asarray(m).reshape(-1)

        def consume(k, dev):
            m = progs[k](w, dev)
            if self._multihost:
                # Local shard blocks, in global (= process-major) order:
                # together they are exactly this process's contiguous
                # local rows of the chunk, laid out (local_shard, row).
                shards = sorted(
                    m.addressable_shards, key=lambda s: s.index[0].start
                )
                outs[k] = np.concatenate(
                    [np.asarray(s.data).reshape(-1) for s in shards]
                )
                return
            if hasattr(m, "copy_to_host_async"):
                m.copy_to_host_async()
            pend.append((k, m))
            if len(pend) > window:
                materialize(*pend.popleft())

        st_nbytes = self._staging.nbytes if self._staging else 0
        glens = [len(g) for g in self._groups] if fused else None
        run_prefetched(
            n_items, get_host, self._put, consume,
            depth=self.prefetch_depth, stats=self.transfer_stats,
            logical_nbytes=(
                (lambda k: st_nbytes * (glens[k] if glens else 1))
                if self._codec is not None else None
            ),
        )
        while pend:
            materialize(*pend.popleft())
        return np.concatenate(outs)[: self.stream.n_rows]

    def _score_fused(self, n_fused: int) -> Callable:
        key = ("score", n_fused, None, False)
        prog = self._progs.get(key)
        if prog is not None:
            return prog
        score = self._score_step

        def fused(w, chunk):
            def body(c, b):
                return c, score(w, b)

            _, ms = lax.scan(
                body, jnp.zeros((), jnp.float32), chunk, length=n_fused
            )
            return ms

        prog = jax.jit(fused)
        self._progs[key] = prog
        return prog


# ---------------------------------------------------------------------------
# Host-loop L-BFGS (the streamed outer loop)
# ---------------------------------------------------------------------------


@jax.jit
def _direction_jit(grad, S, Y, rho, gamma, n_pairs):
    return -_two_loop(grad, S, Y, rho, gamma, n_pairs)


@jax.jit
def _history_jit(S, Y, rho, gamma, n_pairs, w_new, w_old, g_new, g_old):
    return update_history(
        S, Y, rho, gamma, n_pairs, w_new - w_old, g_new - g_old
    )


@jax.jit
def _axpy_jit(w0, t, direction):
    return w0 + t * direction


@jax.jit
def _axpy_batch_jit(w0, ts, direction):
    # Row i is w0 + ts[i]·direction, elementwise — bitwise the _axpy_jit
    # result for that step (broadcasting adds no reduction or re-blocking).
    return w0[None, :] + ts[:, None] * direction[None, :]


@jax.jit
def _vdot_jit(a, b):
    return jnp.vdot(a, b)


class _HostLS:
    """Result of the host-loop weak-Wolfe search (mirrors LineSearchResult)."""

    __slots__ = ("step", "w", "value", "grad", "n_evals", "success")

    def __init__(self, step, w, value, grad, n_evals, success):
        self.step = step
        self.w = w
        self.value = value
        self.grad = grad
        self.n_evals = n_evals
        self.success = success


def _host_wolfe(vg, w0, f0, g0, direction, initial_step,
                cfg: LineSearchConfig, vg_batch=None):
    """Weak-Wolfe bisection search with host control flow — the same
    bracketing rules as optim/linesearch.wolfe_line_search, but each trial
    evaluation is a full streamed pass, so host round trips are free by
    comparison.

    With ``vg_batch`` (a (K, d) → ((K,), (K, d)) batched evaluator, e.g.
    :meth:`StreamingObjective.value_and_grad_batch`), each streamed pass
    SPECULATIVELY evaluates the current trial step plus its two possible
    bisection successors — the successor for either branch of the Armijo
    test is computable from the current bracket before the trial's result
    is known — so every pass resolves two levels of the search and the
    pass count per line search roughly halves.  The examined candidate
    sequence (and therefore the accepted step and ``n_evals``) is
    IDENTICAL to the one-trial-per-pass loop.
    """
    dg0 = float(_vdot_jit(direction, g0))
    cache: dict = {}

    def clamp(t):
        return min(max(t, cfg.min_step), cfg.max_step)

    def successors(t, lo, hi):
        # The two possible next trials after examining t with bracket
        # (lo, hi): armijo-ok moves lo up to t, armijo-fail moves hi down
        # to t — the SAME update+bisection+clamp arithmetic as the main
        # loop, so a later cache lookup hits the exact float.
        out = []
        for lo2, hi2 in ((max(lo, t), hi), (lo, min(hi, t))):
            tn = 2.0 * lo2 if math.isinf(hi2) else 0.5 * (lo2 + hi2)
            out.append(clamp(tn))
        return out

    def evaluate(t, lo, hi):
        if vg_batch is None:
            w = _axpy_jit(w0, jnp.float32(t), direction)
            f, g = vg(w)
            return w, float(f), g, float(_vdot_jit(direction, g))
        if t not in cache:
            cands = [t]
            for tn in successors(t, lo, hi):
                if tn not in cands and tn not in cache:
                    cands.append(tn)
            while len(cands) < _WOLFE_TRIAL_BATCH:
                cands.append(cands[-1])  # pad: one static batch shape
            cands = cands[:_WOLFE_TRIAL_BATCH]
            ws = _axpy_batch_jit(
                w0, jnp.asarray(cands, jnp.float32), direction
            )
            fs, gs = vg_batch(ws)
            fs_host = np.asarray(fs)
            for i, tc in enumerate(cands):
                if tc not in cache:
                    cache[tc] = (
                        ws[i], float(fs_host[i]), gs[i],
                        float(_vdot_jit(direction, gs[i])),
                    )
        return cache[t]

    t = float(initial_step)
    lo, hi = 0.0, math.inf
    w, f, g, dg = evaluate(t, lo, hi)
    n_evals = 1
    while True:
        armijo_ok = f <= f0 + cfg.c1 * t * dg0
        curvature_ok = dg >= cfg.c2 * dg0
        if armijo_ok and curvature_ok:
            break
        if n_evals >= cfg.max_evals:
            break
        if armijo_ok:
            lo = max(lo, t)
        else:
            hi = min(hi, t)
        t_next = 2.0 * lo if math.isinf(hi) else 0.5 * (lo + hi)
        t_next = clamp(t_next)
        if t_next == t or hi - lo < cfg.min_step:
            break
        t = t_next
        w, f, g, dg = evaluate(t, lo, hi)
        n_evals += 1
    success = (
        f <= f0 + cfg.c1 * t * dg0 and dg >= cfg.c2 * dg0
    )
    return _HostLS(t, w, f, g, n_evals, success)


def streaming_lbfgs_solve(
    value_and_grad: Callable[[Array], tuple[Array, Array]],
    w0: Array,
    config: LBFGSConfig = LBFGSConfig(),
    value_and_grad_batch=None,
) -> SolveResult:
    """L-BFGS with the outer loop on the host: ``value_and_grad`` may do
    arbitrary host work per call (stream chunks, launch many programs).

    Math mirrors optim/lbfgs.lbfgs_solve exactly — same two-loop recursion
    and curvature-history update (via the SAME functions, jitted), same
    weak-Wolfe bracketing constants, same stall rule (a failed,
    non-improving line search keeps the incumbent), same convergence tests.

    ``value_and_grad_batch``: optional (K, d) → ((K,), (K, d)) evaluator
    (:meth:`StreamingObjective.value_and_grad_batch`); when given, the
    line search batches each trial with its speculative successors so one
    streamed pass resolves ~2 trials (identical trajectory — see
    :func:`_host_wolfe`).
    """
    m = config.history
    d = w0.shape[0]
    dtype = w0.dtype
    w0 = jnp.asarray(w0)

    f_dev, g = value_and_grad(w0)
    f = float(f_dev)
    g_norm = float(jnp.linalg.norm(g))
    tol_scale = max(1.0, g_norm)

    values = np.full(config.max_iters + 1, np.nan, np.float64)
    gnorms = np.full(config.max_iters + 1, np.nan, np.float64)
    values[0] = f
    gnorms[0] = g_norm

    S = jnp.zeros((m, d), dtype)
    Y = jnp.zeros((m, d), dtype)
    rho = jnp.zeros((m,), dtype)
    gamma = jnp.asarray(1.0, dtype)
    n_pairs = jnp.asarray(0, jnp.int32)

    w = w0
    k = 0
    converged = g_norm <= config.tolerance * tol_scale
    while not converged and k < config.max_iters:
        direction = _direction_jit(g, S, Y, rho, gamma, n_pairs)
        dg = float(_vdot_jit(direction, g))
        if dg >= 0.0:  # non-descent from a stale history → steepest descent
            direction = -g
        first = int(n_pairs) == 0
        init_step = min(1.0, 1.0 / g_norm) if first else 1.0

        ls = _host_wolfe(
            value_and_grad, w, f, g, direction, init_step,
            config.line_search, vg_batch=value_and_grad_batch,
        )

        S, Y, rho, gamma, n_pairs = _history_jit(
            S, Y, rho, gamma, n_pairs, ls.w, w, ls.grad, g
        )

        k += 1
        rel_impr = abs(f - ls.value) / max(abs(f), 1e-12)
        stalled = (not ls.success) and ls.value >= f
        if stalled:
            # Keep the incumbent; convergence measured at the kept point
            # (mirrors the resident solver's stall rule).
            converged = g_norm <= config.tolerance * tol_scale
        else:
            w, f, g = ls.w, ls.value, ls.grad
            g_norm = float(jnp.linalg.norm(ls.grad))
            converged = (
                g_norm <= config.tolerance * tol_scale
                or rel_impr <= config.tolerance * 1e-2
            )
        values[k] = f
        gnorms[k] = g_norm
        if stalled:
            break

    return SolveResult(
        w=w,
        value=jnp.asarray(f, jnp.float32),
        grad=g,
        iterations=jnp.asarray(k, jnp.int32),
        converged=jnp.asarray(bool(converged)),
        values=jnp.asarray(values, jnp.float32),
        grad_norms=jnp.asarray(gnorms, jnp.float32),
    )


# ---------------------------------------------------------------------------
# Host-loop OWL-QN (streamed L1 / elastic-net)
# ---------------------------------------------------------------------------


@jax.jit
def _ow_pseudo_jit(w, grad, l1, mask):
    return _pseudo_gradient(w, grad, l1, mask)


@jax.jit
def _ow_dir_jit(pg, S, Y, rho, gamma, n_pairs):
    direction = -_two_loop(pg, S, Y, rho, gamma, n_pairs)
    # Orthant alignment (Andrew & Gao §3.2): zero coordinates whose sign
    # disagrees with -pg; all-zero direction degrades to steepest descent.
    direction = jnp.where(direction * (-pg) > 0, direction, 0.0)
    deg = jnp.vdot(direction, direction) == 0.0
    return jnp.where(deg, -pg, direction)


@jax.jit
def _ow_trial_jit(w, t, direction, xi):
    wt = w + t * direction
    return jnp.where(wt * xi >= 0, wt, 0.0)  # orthant projection


@jax.jit
def _ow_trials_jit(w, ts, direction, xi):
    # Row i is the _ow_trial_jit result for ts[i], elementwise (broadcast
    # only — no reductions), so the batched trials match bitwise.
    wt = w[None, :] + ts[:, None] * direction[None, :]
    return jnp.where(wt * xi[None, :] >= 0, wt, 0.0)


@jax.jit
def _ow_l1_jit(w, l1, mask):
    return l1 * jnp.vdot(mask, jnp.abs(w))


def streaming_owlqn_solve(
    value_and_grad: Callable[[Array], tuple[Array, Array]],
    w0: Array,
    l1_weight: float,
    config: OWLQNConfig = OWLQNConfig(),
    l1_mask: Optional[Array] = None,
    value_and_grad_batch=None,
) -> SolveResult:
    """OWL-QN with the outer loop on the host — the streamed counterpart
    of optim/owlqn.owlqn_solve (same pseudo-gradient, orthant alignment
    and projection, projected-step Armijo with non-strict backtracking,
    smooth-gradient history, stall rule, convergence tests).
    ``value_and_grad`` evaluates only the smooth part.

    ``value_and_grad_batch``: optional batched smooth evaluator; when
    given, each streamed pass evaluates a ladder of backtracking
    candidates ``t, tβ, tβ², …`` at once (the ladder is deterministic, so
    the examined sequence is identical to one-trial-per-pass)."""
    m = config.history
    d = w0.shape[0]
    dtype = w0.dtype
    w0 = jnp.asarray(w0)
    l1 = jnp.asarray(l1_weight, jnp.float32)
    mask = (
        jnp.ones((d,), dtype) if l1_mask is None
        else jnp.asarray(l1_mask, dtype)
    )

    def full_value(w, smooth) -> float:
        return float(smooth) + float(_ow_l1_jit(w, l1, mask))

    f_smooth, g = value_and_grad(w0)
    w = w0
    f = full_value(w, f_smooth)
    # The pseudo-gradient is maintained as an invariant (pg ≡ pseudo(w, g))
    # across the loop: computed once here, refreshed only on acceptance —
    # the old loop recomputed it at the top of every iteration even though
    # the accepted iteration had just evaluated the identical value.
    pg = _ow_pseudo_jit(w, g, l1, mask)
    pg_norm = float(jnp.linalg.norm(pg))
    tol_scale = max(1.0, pg_norm)

    values = np.full(config.max_iters + 1, np.nan, np.float64)
    gnorms = np.full(config.max_iters + 1, np.nan, np.float64)
    values[0] = f
    gnorms[0] = pg_norm

    S = jnp.zeros((m, d), dtype)
    Y = jnp.zeros((m, d), dtype)
    rho = jnp.zeros((m,), dtype)
    gamma = jnp.asarray(1.0, dtype)
    n_pairs = jnp.asarray(0, jnp.int32)

    k = 0
    converged = pg_norm <= config.tolerance * tol_scale
    while not converged and k < config.max_iters:
        direction = _ow_dir_jit(pg, S, Y, rho, gamma, n_pairs)
        # Orthant: sign(w) where nonzero, else the step's sign.
        xi = jnp.where(w != 0, jnp.sign(w), jnp.sign(-pg))
        t = (
            min(1.0, 1.0 / float(jnp.linalg.norm(pg)))
            if int(n_pairs) == 0 else 1.0
        )

        cache: dict = {}

        def trial(t):
            if value_and_grad_batch is None:
                wt = _ow_trial_jit(w, jnp.float32(t), direction, xi)
                smooth, grad = value_and_grad(wt)
                return wt, full_value(wt, smooth), grad
            if t not in cache:
                # The backtracking ladder from t, by REPEATED
                # multiplication (exactly the floats `t *= backtrack`
                # would visit — t·β**i differs bitwise).
                ts = [t]
                for _ in range(_OWLQN_TRIAL_BATCH - 1):
                    ts.append(ts[-1] * config.backtrack)
                ts = [tc for tc in ts if tc not in cache]
                while len(ts) < _OWLQN_TRIAL_BATCH:
                    ts.append(ts[-1])
                wts = _ow_trials_jit(
                    w, jnp.asarray(ts, jnp.float32), direction, xi
                )
                smooths, grads = value_and_grad_batch(wts)
                smooths_host = np.asarray(smooths)
                for i, tc in enumerate(ts):
                    if tc not in cache:
                        cache[tc] = (
                            wts[i],
                            full_value(wts[i], smooths_host[i]),
                            grads[i],
                        )
            return cache[t]

        w_new, f_new, g_new = trial(t)
        n_evals = 1
        # Armijo on the PROJECTED step, non-strict (a fully-clamped trial
        # must keep backtracking) — mirrors the resident solver.
        while (
            f_new >= f + config.armijo_c1 * float(_vdot_jit(pg, w_new - w))
            and n_evals < config.max_line_search_evals
        ):
            t *= config.backtrack
            w_new, f_new, g_new = trial(t)
            n_evals += 1

        S, Y, rho, gamma, n_pairs = _history_jit(
            S, Y, rho, gamma, n_pairs, w_new, w, g_new, g
        )

        k += 1
        rel_impr = abs(f - f_new) / max(abs(f), 1e-12)
        stalled = f_new >= f
        if stalled:
            converged = (
                float(jnp.linalg.norm(pg)) <= config.tolerance * tol_scale
            )
        else:
            w, f, g = w_new, f_new, g_new
            pg = _ow_pseudo_jit(w, g, l1, mask)
            pg_norm = float(jnp.linalg.norm(pg))
            converged = (
                pg_norm <= config.tolerance * tol_scale
                or rel_impr <= config.tolerance * 1e-2
            )
        values[k] = f
        gnorms[k] = pg_norm
        if stalled:
            break

    # pg already equals the pseudo-gradient at the returned (w, g) — the
    # invariant holds through both the acceptance and stall branches.
    return SolveResult(
        w=w,
        value=jnp.asarray(f, jnp.float32),
        grad=pg,
        iterations=jnp.asarray(k, jnp.int32),
        converged=jnp.asarray(bool(converged)),
        values=jnp.asarray(values, jnp.float32),
        grad_norms=jnp.asarray(gnorms, jnp.float32),
    )


# ---------------------------------------------------------------------------
# Host-loop TRON (streamed trust-region Newton)
# ---------------------------------------------------------------------------


def _host_steihaug_cg(hvp, g, delta, max_iters, tol):
    """Steihaug CG with host control flow — same math as optim/tron.py's
    ``_steihaug_cg`` (negative-curvature and radius-crossing exits to the
    boundary, residual kept consistent with the returned step), but each
    Hessian-vector product is a full streamed pass, so host round-trips
    are free by comparison.

    Returns ``(s, r, n_hvp)`` with ``r = -g - H·s`` for the returned ``s``
    (so sᵀHs is recoverable without another streamed pass)."""
    s = jnp.zeros_like(g)
    r = _axpy_jit(jnp.zeros_like(g), jnp.float32(-1.0), g)
    p = r
    rr = float(_vdot_jit(r, r))
    if math.sqrt(rr) <= tol:
        return s, r, 0
    n_hvp = 0
    for _ in range(max_iters):
        Hp = hvp(p)
        n_hvp += 1
        pHp = float(_vdot_jit(p, Hp))
        neg_curv = pHp <= 0.0
        alpha = rr / (pHp if pHp > 0.0 else 1.0)
        s_next = _axpy_jit(s, jnp.float32(alpha), p)
        crosses = math.sqrt(float(_vdot_jit(s_next, s_next))) >= delta
        if neg_curv or crosses:
            # Go to the trust-region boundary along p: ‖s + τp‖ = delta.
            pp = float(_vdot_jit(p, p))
            sp = float(_vdot_jit(s, p))
            ss = float(_vdot_jit(s, s))
            disc = max(sp * sp + pp * (delta * delta - ss), 0.0)
            tau = (-sp + math.sqrt(disc)) / max(pp, 1e-30)
            s = _axpy_jit(s, jnp.float32(tau), p)
            r = _axpy_jit(r, jnp.float32(-tau), Hp)
            break
        s = s_next
        r = _axpy_jit(r, jnp.float32(-alpha), Hp)
        rr_new = float(_vdot_jit(r, r))
        if math.sqrt(rr_new) <= tol:
            break
        beta = rr_new / max(rr, 1e-30)
        p = _axpy_jit(r, jnp.float32(beta), p)
        rr = rr_new
    return s, r, n_hvp


def streaming_tron_solve(
    value_and_grad: Callable[[Array], tuple[Array, Array]],
    hvp_fn: Callable[[Array, Array], Array],
    w0: Array,
    config=None,
) -> SolveResult:
    """Trust-region Newton-CG with the outer loop on the host — the
    streamed counterpart of optim/tron.tron_solve, closing the last
    optimizer×residency cell: the reference runs TRON distributed, one
    ``HessianVectorAggregator`` treeAggregate round per CG step
    (SURVEY.md §3.1 / BASELINE config 3); here each CG step is one
    streamed :meth:`StreamingObjective.hvp` pass.

    Math mirrors the resident solver step-for-step: LIBLINEAR initial
    radius ``‖g0‖``, the same forcing tolerance, acceptance threshold and
    radius-update constants (via the shared ``TRONConfig``), the same
    boundary-consistent residual trick recovering sᵀHs without an extra
    HVP, and the same convergence/stall rules — so a single-chunk streamed
    solve tracks the resident trajectory to float tolerance.

    ``hvp_fn(w, v)`` must return the REGULARIZED Hessian-vector product.
    """
    from photon_ml_tpu.optim.tron import TRONConfig

    if config is None:
        config = TRONConfig()
    w = jnp.asarray(w0)

    f_dev, g = value_and_grad(w)
    f = float(f_dev)
    g_norm = float(jnp.linalg.norm(g))
    tol_scale = max(1.0, g_norm)
    delta = g_norm  # LIBLINEAR: initial radius = ||g0||

    values = np.full(config.max_iters + 1, np.nan, np.float64)
    gnorms = np.full(config.max_iters + 1, np.nan, np.float64)
    values[0] = f
    gnorms[0] = g_norm

    k = 0
    converged = g_norm <= config.tolerance * tol_scale
    while not converged and k < config.max_iters:
        cg_tol = config.cg_tol * g_norm
        step, residual, _ = _host_steihaug_cg(
            lambda v: hvp_fn(w, v), g, delta, config.max_cg_iters, cg_tol
        )

        w_try = _axpy_jit(w, jnp.float32(1.0), step)
        f_try_dev, g_try = value_and_grad(w_try)
        f_try = float(f_try_dev)

        gs = float(_vdot_jit(g, step))
        # r = -g - H·s  ⇒  sᵀHs = -s·r - s·g (one saved streamed pass per
        # outer iteration, as in the resident solver).
        sHs = -float(_vdot_jit(step, residual)) - gs
        pred = -(gs + 0.5 * sHs)
        ared = f - f_try
        rho = ared / (pred if pred > 0.0 else 1e-30)
        accept = rho > config.eta0 and pred > 0.0

        # Radius update (LIBLINEAR-style, same constants as the resident).
        snorm = math.sqrt(max(float(_vdot_jit(step, step)), 0.0))
        if rho < config.eta1:
            delta_new = max(config.sigma1 * snorm, config.sigma2 * delta)
            if rho < config.eta0:
                delta_new *= config.sigma2
        elif rho > config.eta2:
            delta_new = max(delta, config.sigma3 * snorm)
        else:
            delta_new = delta
        delta = max(delta_new, 1e-20)

        k += 1
        if accept:
            rel_impr = abs(ared) / max(abs(f), 1e-12)
            w, f, g = w_try, f_try, g_try
            g_norm = float(jnp.linalg.norm(g))
        else:
            rel_impr = math.inf
        converged = (
            g_norm <= config.tolerance * tol_scale
            or rel_impr <= config.tolerance * 1e-2
        )
        values[k] = f
        gnorms[k] = g_norm
        if delta <= 1e-18:  # radius collapsed: no further progress possible
            break

    return SolveResult(
        w=w,
        value=jnp.asarray(f, jnp.float32),
        grad=g,
        iterations=jnp.asarray(k, jnp.int32),
        converged=jnp.asarray(bool(converged)),
        values=jnp.asarray(values, jnp.float32),
        grad_norms=jnp.asarray(gnorms, jnp.float32),
    )


# ---------------------------------------------------------------------------
# Grid sweep over a streamed dataset
# ---------------------------------------------------------------------------


def ensure_streamable(config) -> None:
    """Reject configs the streamed path cannot train — callable BEFORE the
    (possibly hours-long) chunk-store ingest, and always re-checked by
    :func:`streaming_run_grid`.

    Every optimizer now streams (L-BFGS, OWL-QN, and smooth TRON via
    :func:`streaming_tron_solve`), so this currently accepts everything;
    it remains the single gate future unstreamable features must fail
    loudly through."""


def streaming_run_grid(
    problem,
    stream: StreamingGlmData,
    reg_weights: Sequence[float],
    w0: Optional[Array] = None,
    mesh=None,
    warm_start: bool = True,
    solved: Optional[dict] = None,
    on_solved=None,
    accumulate: str = "f32",
    l1_mask: Optional[Array] = None,
    prefetch_depth: int = 2,
    chunk_fuse: int = 1,
    batch_linesearch: bool = True,
    compress: str = "off",
    hot_budget_bytes: int = 0,
):
    """The λ-grid warm-start chain (optim.problem.grid_loop) over a
    streamed dataset.  L1/elastic-net routes to the streamed OWL-QN and
    smooth TRON to the streamed trust-region solver (exactly like the
    resident problem.solve's static routing).

    ``chunk_fuse``: chunks folded per device dispatch (``lax.scan``) —
    amortizes per-dispatch overhead for small chunks; ``batch_linesearch``
    evaluates a bracket of line-search candidates per streamed pass
    (identical trial sequence, ~half the passes).  ``compress`` and
    ``hot_budget_bytes`` are the transfer-avoidance knobs (compressed
    wire formats + importance-aware HBM working set — see
    :class:`StreamingObjective`); lossless compression and the cache
    leave every solve bitwise unchanged.
    """
    from photon_ml_tpu.solvers import registry as solver_registry

    cfg = problem.config
    ensure_streamable(cfg)
    sobj = StreamingObjective(
        problem.objective, stream, mesh=mesh, accumulate=accumulate,
        prefetch_depth=prefetch_depth, chunk_fuse=chunk_fuse,
        compress=compress, hot_budget_bytes=hot_budget_bytes,
    )
    opt = cfg.optimizer
    l1_frac = cfg.regularization.l1_weight(1.0)
    defn = solver_registry.resolve(opt, l1_frac=l1_frac)
    if defn.streamed is None:
        raise ValueError(
            f"solver {defn.name!r} has no streamed implementation; the "
            "streamed grid serves jit-kind solvers with a streamed pass "
            "loop (lbfgs, owlqn, tron) — distributed solvers run over "
            "sharded resident data (solvers.sharded.run_grid_sharded)"
        )

    def solve_fn(lam, w_prev):
        l1 = l1_frac * float(lam)
        l2 = cfg.regularization.l2_weight(1.0) * float(lam)
        if w_prev is None:
            w_prev = jnp.zeros((stream.n_features,), jnp.float32)
        vgb = (
            (lambda ws: sobj.value_and_grad_batch(ws, l2))
            if batch_linesearch else None
        )
        return defn.streamed(solver_registry.StreamedSolve(
            sobj=sobj, w0=w_prev, l1=l1, l2=l2, opt=opt,
            l1_mask=l1_mask, value_and_grad_batch=vgb,
        ))

    variance_fn = None
    if cfg.compute_variances:
        def variance_fn(w, lam):
            l2 = cfg.regularization.l2_weight(1.0) * float(lam)
            diag = sobj.hessian_diagonal(w)
            return 1.0 / jnp.maximum(diag + l2, 1e-12)

    return problem.grid_loop(
        solve_fn, reg_weights, w0, warm_start, solved, on_solved, variance_fn
    )
