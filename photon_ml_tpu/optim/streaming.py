"""Out-of-core GLM training: stream host chunks through the chip per pass.

The resident solvers (optim/lbfgs.py) run the ENTIRE optimize loop inside
one jitted ``lax.while_loop`` — possible only because the dataset lives in
HBM.  When it does not (BASELINE.json's north-star configs are 1B rows ≈
hundreds of GB of slot data), the structure inverts to the reference's own
shape: the OUTER loop runs on the host (the reference's driver-side Breeze
L-BFGS — SURVEY.md §2 Optimizers), and each objective evaluation is one
full pass over the data (the ``treeAggregate`` analogue, SURVEY.md §3.1) —
here a pipelined stream of host chunks, value/grad accumulated on device:

    producer thread: pack/fetch chunk k+1 ──one coalesced transfer──► HBM
    caller thread:   HBM chunk k ──unpack+Pallas/XLA──► (value, grad) +=

Each chunk crosses as a few large dtype-segregated staging buffers
(data/staging.py) rather than a pytree of small per-leaf transfers, a
producer thread keeps ``prefetch_depth`` (default 2) chunks in flight
(data/prefetch.py), and HBM holds ≤ ``prefetch_depth`` chunks regardless
of dataset size.  The inner per-chunk program is ONE jitted function for
all chunks (uniform shapes — see data/streaming.py) with the staging
unpack traced in, so there is exactly one compile per solve; per-chunk
transfer timing and stall counters accumulate on
``StreamingObjective.transfer_stats``.

Host-loop math mirrors lbfgs_solve step-for-step (same two-loop recursion
and history via the SAME jitted helpers, same weak-Wolfe bracketing, same
stall/convergence rules), so a single-chunk streamed solve lands on the
resident solution to float tolerance; tests/test_streaming.py pins that.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from photon_ml_tpu.data.prefetch import TransferStats, run_prefetched
from photon_ml_tpu.data.streaming import StreamingGlmData
from photon_ml_tpu.parallel.compat import shard_map
from photon_ml_tpu.optim.lbfgs import (
    LBFGSConfig,
    SolveResult,
    _two_loop,
    update_history,
)
from photon_ml_tpu.optim.linesearch import LineSearchConfig
from photon_ml_tpu.optim.objective import GlmObjective
from photon_ml_tpu.optim.owlqn import OWLQNConfig, _pseudo_gradient

Array = jax.Array


# ---------------------------------------------------------------------------
# Streamed objective: value+grad as one pass over host chunks
# ---------------------------------------------------------------------------


class StreamingObjective:
    """A GlmObjective evaluated by streaming host chunks through the device.

    ``accumulate``: "f32" adds chunk contributions directly; "kahan"
    carries a compensation term per accumulator (value and gradient), so
    the cross-chunk summation error stays O(ε) instead of O(n_chunks·ε) —
    the scale-robust option for very long streams (the reference
    accumulates in f64 via Breeze; TPUs have no fast f64, compensation is
    the idiomatic equivalent).

    With ``mesh`` (and chunks built with ``n_shards == mesh size``) each
    chunk is placed sharded over the mesh's first axis and the per-chunk
    reduction runs under ``shard_map`` with one fused psum — streamed data
    parallelism.

    Transfers ride the coalesced ingest pipeline: each chunk moves as a
    few large dtype-segregated staging buffers (data/staging.py) whose
    compiled unpack is traced into the per-chunk program, and a
    background producer thread keeps ``prefetch_depth`` chunks in flight
    (data/prefetch.py; depth 2 = the classic double buffer, preserving
    the ≤2-chunks-in-HBM invariant).  ``transfer_stats`` accumulates
    per-chunk h2d timing, achieved GB/s, and queue-stall counters across
    passes — reset it around a measurement window (bench_streaming
    does).
    """

    def __init__(
        self,
        task_or_objective,
        stream: StreamingGlmData,
        normalization=None,
        mesh=None,
        accumulate: str = "f32",
        prefetch_depth: int = 2,
    ):
        from photon_ml_tpu.ops import losses as losses_lib

        if isinstance(task_or_objective, GlmObjective):
            self.objective = task_or_objective
        else:
            self.objective = GlmObjective(
                losses_lib.get(task_or_objective), normalization
            )
        if accumulate not in ("f32", "kahan"):
            raise ValueError(f"accumulate must be f32|kahan, got {accumulate}")
        if prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {prefetch_depth}"
            )
        self.stream = stream
        self.mesh = mesh
        self.accumulate = accumulate
        self.prefetch_depth = int(prefetch_depth)
        self.transfer_stats = TransferStats()
        # Coalesce to staging buffers (no-op when the builder already
        # did); falls back to per-leaf pytree transfers only for
        # hand-built disk-backed stores, which cannot pack in RAM.
        stream.ensure_staged()
        self._staging = stream.staging
        self._sharding = None
        # Multi-host (pod) mode: every process holds a chunk store over
        # ITS host-local rows only (n_shards = local device count) and
        # feeds just its own shards of each globally-sharded chunk — the
        # streamed analogue of multihost.assemble_global, so no host ever
        # materializes a global chunk.  Row order across hosts differs
        # from the single-host layout, which is immaterial: every
        # streamed reduction is a permutation-invariant sum over rows.
        self._multihost = jax.process_count() > 1
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            expect = (
                jax.local_device_count() if self._multihost
                else mesh.devices.size
            )
            if stream.n_shards != expect:
                raise ValueError(
                    f"stream has n_shards={stream.n_shards}; this "
                    f"{'process' if self._multihost else 'mesh'} needs "
                    f"{expect}"
                )
            if stream.n_shards == 1 and not self._multihost:
                # Single-shard chunks carry NO shard axis (data/streaming
                # builds the stacked layout only for n_shards > 1).  The
                # mesh path's x[0] unstack would then strip a DATA axis
                # and silently compute the objective over wrong slices —
                # no error, wrong numbers (verified).  Refuse loudly.
                raise ValueError(
                    "single-shard chunks carry no shard axis; the mesh "
                    "path would silently compute over wrong data — pass "
                    "mesh=None for single-device streams"
                )
            if stream.n_shards == 1 and self._multihost:
                raise ValueError(
                    "multi-host streams need n_shards == "
                    "jax.local_device_count() > 1 per process; a "
                    "1-local-device pod member is unsupported"
                )
            if self._multihost:
                self._align_multihost_chunks()
            self._axis = mesh.axis_names[0]
            self._sharding = NamedSharding(mesh, P(self._axis))
        elif stream.n_shards != 1:
            raise ValueError("sharded chunks need a mesh")

        obj = self.objective
        staging = self._staging

        def unpack(chunk_in):
            # The compiled on-device unpack (slice + reshape) restoring
            # the GlmData view from the coalesced staging buffers —
            # traced INTO each per-chunk program, so coalescing costs no
            # extra dispatch.  Identity for unstaged (fallback) streams.
            # Under shard_map the buffers arrive as per-device blocks;
            # unpack_device reads the local leading dim off the trace.
            if staging is None:
                return chunk_in
            return staging.unpack_device(chunk_in)

        def chunk_vg(w, off, chunk):
            # ``off``: extra per-row margin offsets (coordinate descent —
            # the other coordinates' scores); a traced scalar 0 when
            # absent, so the plain-GLM trace carries no extra transfer.
            # Under a mesh, a non-scalar ``off`` arrives SHARDED like the
            # chunk (leading shard axis) — the streamed-GAME × DP
            # composition.
            chunk = unpack(chunk)
            if mesh is not None:
                local = jax.tree.map(lambda x: x[0], chunk)
                off_local = off if off.ndim == 0 else off[0]
                local = dataclasses.replace(
                    local, offsets=local.offsets + off_local
                )
                v, g = obj.raw_value_and_grad(w, local)
                return lax.psum(v, self._axis), lax.psum(g, self._axis)
            chunk = dataclasses.replace(chunk, offsets=chunk.offsets + off)
            return obj.raw_value_and_grad(w, chunk)

        def acc_step(carry, w, off, chunk):
            v, g = chunk_vg(w, off, chunk)
            if accumulate == "f32":
                vacc, gacc = carry
                return (vacc + v, gacc + g)
            # Kahan: carry = (vacc, vcomp, gacc, gcomp)
            vacc, vc, gacc, gc = carry
            yv = v - vc
            tv = vacc + yv
            vc = (tv - vacc) - yv
            yg = g - gc
            tg = gacc + yg
            gc = (tg - gacc) - yg
            return (tv, vc, tg, gc)

        def chunk_hvp(w, v, off, chunk):
            # Recomputes the d2 weights inside the chunk program (one extra
            # margins matvec) — the streamed analogue of the reference's
            # HessianVectorAggregator, which recomputes per-row d2 on every
            # treeAggregate round (SURVEY.md §3.1).  The resident TRON's
            # per-iterate d2 cache (optim/tron.py) is an HBM-resident
            # luxury the chunk store deliberately forgoes: caching would
            # mean either holding n_rows of d2 weights in HBM (not
            # out-of-core) or round-tripping them host↔device per CG step.
            chunk = unpack(chunk)
            if mesh is not None:
                local = jax.tree.map(lambda x: x[0], chunk)
                off_local = off if off.ndim == 0 else off[0]
                local = dataclasses.replace(
                    local, offsets=local.offsets + off_local
                )
                return lax.psum(obj.raw_hvp(w, v, local), self._axis)
            chunk = dataclasses.replace(chunk, offsets=chunk.offsets + off)
            return obj.raw_hvp(w, v, chunk)

        def hvp_step(acc, w, v, off, chunk):
            h = chunk_hvp(w, v, off, chunk)
            if accumulate == "f32":
                return acc + h
            hacc, hc = acc  # Kahan, matching acc_step's gradient pair
            yh = h - hc
            th = hacc + yh
            return (th, (th - hacc) - yh)

        def chunk_diag(w, off, chunk):
            chunk = unpack(chunk)
            if mesh is not None:
                local = jax.tree.map(lambda x: x[0], chunk)
                off_local = off if off.ndim == 0 else off[0]
                local = dataclasses.replace(
                    local, offsets=local.offsets + off_local
                )
                d2w = obj.d2_weights(w, local)
                return lax.psum(
                    local.features.sq_rmatvec(d2w), self._axis
                )
            chunk = dataclasses.replace(chunk, offsets=chunk.offsets + off)
            d2w = obj.d2_weights(w, chunk)
            return chunk.features.sq_rmatvec(d2w)

        def diag_step(diag, w, off, chunk):
            return diag + chunk_diag(w, off, chunk)

        def score_step(w, chunk):
            chunk = unpack(chunk)
            if mesh is not None:
                local = jax.tree.map(lambda x: x[0], chunk)
                return obj.margins(w, local)
            return obj.margins(w, chunk)

        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            spec = P(self._axis)
            n_acc = 2 if accumulate == "f32" else 4
            acc_carry = (P(),) * n_acc
            hvp_carry = P() if accumulate == "f32" else (P(), P())
            # Two shard_map variants per pass, built lazily and cached:
            # scalar offsets (plain GLM — a replicated traced 0, no
            # transfer) vs ROW offsets sharded like the chunk (streamed
            # GAME × data parallelism, the other coordinates' scores).
            self._mesh_progs: dict = {}
            builders = {
                "acc": lambda off_spec: shard_map(
                    acc_step, mesh=mesh,
                    in_specs=(acc_carry, P(), off_spec, spec),
                    out_specs=acc_carry, check_vma=False,
                ),
                "diag": lambda off_spec: shard_map(
                    diag_step, mesh=mesh,
                    in_specs=(P(), P(), off_spec, spec), out_specs=P(),
                    check_vma=False,
                ),
                "hvp": lambda off_spec: shard_map(
                    hvp_step, mesh=mesh,
                    in_specs=(hvp_carry, P(), P(), off_spec, spec),
                    out_specs=hvp_carry, check_vma=False,
                ),
            }

            def _program(name: str, row_off: bool):
                key = (name, row_off)
                if key not in self._mesh_progs:
                    self._mesh_progs[key] = jax.jit(
                        builders[name](spec if row_off else P())
                    )
                return self._mesh_progs[key]

            self._mesh_program = _program
            self._score = jax.jit(shard_map(
                score_step, mesh=mesh, in_specs=(P(), spec), out_specs=spec,
                check_vma=False,
            ))
        else:
            self._acc = jax.jit(acc_step)
            self._diag = jax.jit(diag_step)
            self._hvp = jax.jit(hvp_step)
            self._score = jax.jit(score_step)
        self._finish = jax.jit(
            lambda v, g, w, l2: (
                v + 0.5 * l2 * jnp.dot(w, w), g + l2 * w
            )
        )
        self._hvp_finish = jax.jit(lambda h, v, l2: h + l2 * v)

    @property
    def n_features(self) -> int:
        return self.stream.n_features

    def _align_multihost_chunks(self) -> None:
        """Pod-wide agreement checks the streamed loop's collectives need.

        Every process runs one psum per chunk, so (a) chunk COUNTS must
        match — an uneven ``host_local_rows`` split is equalized by
        appending all-padding (zero-weight) chunks locally, which add
        exactly zero to every reduction; (b) chunk leaf SHAPES must match
        — each process's store pads to its OWN nnz budget / layout, and a
        mismatch would compile different SPMD executables per process
        (hang or crash deep in XLA), so it is refused loudly here with
        the fix spelled out."""
        import zlib

        from jax.experimental import multihost_utils

        chunks = self.stream.chunks
        leaves = jax.tree.leaves(chunks[0])
        # The structure signature is hashed to a SCALAR before the
        # allgather: a raw per-leaf shape vector would have a
        # process-dependent LENGTH exactly when structures mismatch, and
        # process_allgather on ragged inputs dies (or hangs) deep in the
        # collective instead of reaching the explanatory error below.
        shape_sig = ",".join(
            f"{len(leaf.shape)}:{leaf.shape}" for leaf in leaves
        )
        crc = zlib.crc32(f"{len(leaves)}|{shape_sig}".encode())
        sig = np.asarray([len(chunks), crc], np.int64)
        all_sigs = np.asarray(multihost_utils.process_allgather(sig))
        if not (all_sigs[1:, 1] == all_sigs[0, 1]).all():
            raise ValueError(
                "multi-host chunk stores have mismatched leaf shapes "
                "across processes (per-process nnz budgets / layouts "
                "differ) — build every process's store with the same "
                "chunk_rows and a COMMON coo_budget "
                "(make_streaming_glm_data(..., coo_budget=N)), and "
                "use_pallas=False"
            )
        max_chunks = int(all_sigs[:, 0].max())
        if len(chunks) < max_chunks:
            pad = max_chunks - len(chunks)
            if self.stream.staged is not None:
                # Equalization chunks ride the staged representation
                # too: one shared all-zero buffer set (read-only) and a
                # view over it, so every transfer path stays coalesced.
                blank_bufs = tuple(
                    np.zeros_like(np.asarray(b))
                    for b in self.stream.staged[0]
                )
                blank = self.stream.staging.view(blank_bufs)
                self.stream.staged = (
                    list(self.stream.staged) + [blank_bufs] * pad
                )
            else:
                blank = jax.tree.map(np.zeros_like, chunks[0])
            self.stream.chunks = chunks + [blank] * pad

    def _put_local_block(self, x) -> Array:
        """Assemble one globally-sharded array from THIS process's local
        shard block (multihost.assemble_global's contract): global shard
        axis = processes x local shards, this process's block slotting in
        at its process index."""
        total = self.mesh.devices.size
        gshape = (total,) + tuple(x.shape[1:])
        return jax.make_array_from_process_local_data(
            self._sharding, np.asarray(x), gshape
        )

    def _put(self, chunk):
        if self._sharding is not None:
            if self._multihost:
                # Each process contributes ONLY its local shard block of
                # the global chunk, per leaf.
                return jax.tree.map(self._put_local_block, chunk)
            return jax.device_put(chunk, self._sharding)
        return jax.device_put(chunk)

    def _select(self, name: str, per_chunk) -> Callable:
        """The compiled per-chunk program for pass ``name`` — on a mesh,
        picked by whether the offset slices are scalars or sharded rows
        (two distinct shard_map signatures)."""
        if self.mesh is None:
            return {
                "acc": self._acc, "diag": self._diag, "hvp": self._hvp,
            }[name]
        row_off = getattr(per_chunk[0], "ndim", 0) != 0
        return self._mesh_program(name, row_off)

    def offset_slices(self, offsets) -> list:
        """Per-chunk slices of coordinate-descent offsets (the other
        coordinates' scores), zero-padded to the chunk grid; a traced
        scalar 0 per chunk when absent (no extra transfer, own trace).
        Callers evaluating many passes against FIXED offsets (a whole
        L-BFGS solve) should call this once and pass the list to
        ``value_and_grad`` — it is accepted in place of the raw array."""
        if isinstance(offsets, list):  # already sliced
            return offsets
        cr = self.stream.chunk_rows
        n_chunks = self.stream.n_chunks
        if offsets is None:
            zero = jnp.zeros((), jnp.float32)
            return [zero] * n_chunks
        if offsets.shape[0] != self.stream.n_rows:
            # A silently zero-padded short array would train the tail rows
            # against offset 0 and converge to a wrong model.
            raise ValueError(
                f"offsets has {offsets.shape[0]} rows; the stream has "
                f"{self.stream.n_rows}"
            )
        if self.mesh is not None:
            # Streamed GAME × DP: each chunk's offset slice is reshaped to
            # the chunk's (shard, row) grid and placed SHARDED over the
            # mesh, so the per-chunk program adds it to the local rows with
            # no gather (row k of shard s is chunk row s·per_shard + k,
            # matching data/streaming's reshape layout).
            #
            # On a POD, per-row CD state is PROCESS-LOCAL (the reference's
            # layout: score RDDs live partitioned next to the data): the
            # offsets are THIS PROCESS's rows — exactly the rows its chunk
            # store holds — and each reshaped slice feeds only the local
            # shard block of the global chunk, the same assemble_global
            # contract the data chunks use.  Blank equalization chunks
            # (appended past the local rows) get zero offsets from the
            # padding below, matching their zero weights.
            n_sh = self.stream.n_shards
            off = np.asarray(offsets, np.float32)
            pad = n_chunks * cr - off.shape[0]
            if pad:
                off = np.pad(off, (0, pad))
            blocks = [
                off[k * cr:(k + 1) * cr].reshape(n_sh, cr // n_sh)
                for k in range(n_chunks)
            ]
            if self._multihost:
                return [self._put_local_block(b) for b in blocks]
            return [
                jax.device_put(b, self._sharding) for b in blocks
            ]
        off = jnp.asarray(offsets, jnp.float32)
        pad = n_chunks * cr - off.shape[0]
        if pad:
            off = jnp.pad(off, (0, pad))
        return [off[k * cr:(k + 1) * cr] for k in range(n_chunks)]

    def _host_item(self, k: int):
        """What crosses the wire for chunk ``k``: the coalesced staging
        buffers when the store is staged, the leaf pytree otherwise."""
        if self.stream.staged is not None:
            return self.stream.staged[k]
        return self.stream.chunks[k]

    def _stream_accumulate(self, step: Callable, init, args=(),
                           per_chunk=None):
        """Run ``carry = step(carry, *args, per_chunk[k], chunk)`` over
        all chunks through the prefetch pipeline: a producer thread
        dispatches transfers up to ``prefetch_depth`` chunks ahead
        (depth 2 = chunk k+1 moving while chunk k computes), so host-side
        packing/dispatch overhead overlaps device compute.  The per-chunk
        sync on the (tiny) carry is the backpressure that makes the
        pipeline's depth bound actual HBM residency — without it the host
        would enqueue every chunk's compute and HBM would hold the whole
        dataset again."""
        n = self.stream.n_chunks
        carry_box = [init]

        def consume(k, dev):
            extra = (per_chunk[k],) if per_chunk is not None else ()
            carry_box[0] = step(carry_box[0], *args, *extra, dev)
            jax.block_until_ready(jax.tree.leaves(carry_box[0])[0])

        run_prefetched(
            n, self._host_item, self._put, consume,
            depth=self.prefetch_depth, stats=self.transfer_stats,
        )
        return carry_box[0]

    def value_and_grad(
        self, w: Array, l2_weight=0.0, offsets=None
    ) -> tuple[Array, Array]:
        """One full streamed pass; returns device (value, grad) with the L2
        term applied.  ``offsets``: optional (n_rows,) extra margins added
        per row (coordinate descent)."""
        d = self.stream.n_features
        if self.accumulate == "f32":
            init = (jnp.zeros((), jnp.float32), jnp.zeros((d,), jnp.float32))
        else:
            init = (
                jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32),
                jnp.zeros((d,), jnp.float32), jnp.zeros((d,), jnp.float32),
            )
        slices = self.offset_slices(offsets)
        out = self._stream_accumulate(
            self._select("acc", slices), init, args=(w,), per_chunk=slices,
        )
        v, g = (out[0], out[1]) if self.accumulate == "f32" else (
            out[0], out[2]
        )
        return self._finish(v, g, w, jnp.asarray(l2_weight, jnp.float32))

    def hessian_diagonal(self, w: Array, offsets=None) -> Array:
        """Σᵢ wᵢ·d2ᵢ·X²ᵢⱼ streamed over chunks (for coefficient variances)."""
        d = self.stream.n_features
        slices = self.offset_slices(offsets)
        return self._stream_accumulate(
            self._select("diag", slices), jnp.zeros((d,), jnp.float32),
            args=(w,), per_chunk=slices,
        )

    def hvp(self, w: Array, v: Array, l2_weight=0.0, offsets=None) -> Array:
        """H(w)·v = Xᵀ(d2w ⊙ (Xv)) + λ·v as ONE streamed pass over the
        chunks — the ``HessianVectorAggregator`` ``treeAggregate`` round of
        the reference's distributed TRON (SURVEY.md §3.1), here a
        double-buffered chunk stream.  Callers issuing many HVPs against
        fixed offsets (a whole CG solve) should pre-slice via
        :meth:`offset_slices` and pass the list."""
        d = self.stream.n_features
        zero = jnp.zeros((d,), jnp.float32)
        init = zero if self.accumulate == "f32" else (zero, zero)
        slices = self.offset_slices(offsets)
        h = self._stream_accumulate(
            self._select("hvp", slices), init, args=(w, v),
            per_chunk=slices,
        )
        if self.accumulate != "f32":
            h = h[0]
        return self._hvp_finish(h, v, jnp.asarray(l2_weight, jnp.float32))

    def scores(self, w: Array) -> np.ndarray:
        """Margins for every row of THIS STORE, streamed.

        On a pod the contract is PROCESS-LOCAL (the defined edge VERDICT
        r4 missing #3 asked for): each process gets the margins of its
        own rows — the rows its chunk store holds — read from its
        addressable shards of the globally-sharded per-chunk result.
        That matches the pod CD layout (per-row state lives partitioned
        next to the data, like the reference's score RDDs); GLOBAL
        metrics over these scores reduce with one psum
        (evaluation/device.py) or an explicit allgather, never by
        materializing global rows on one host."""
        outs: list = [None] * self.stream.n_chunks

        def consume(k, dev):
            m = self._score(w, dev)
            if self._multihost:
                # Local shard blocks, in global (= process-major) order:
                # together they are exactly this process's contiguous
                # local rows of the chunk, laid out (local_shard, row).
                shards = sorted(
                    m.addressable_shards, key=lambda s: s.index[0].start
                )
                outs[k] = np.concatenate(
                    [np.asarray(s.data).reshape(-1) for s in shards]
                )
            else:
                # The readback is the per-chunk sync (backpressure).
                outs[k] = np.asarray(m).reshape(-1)

        run_prefetched(
            self.stream.n_chunks, self._host_item, self._put, consume,
            depth=self.prefetch_depth, stats=self.transfer_stats,
        )
        return np.concatenate(outs)[: self.stream.n_rows]


# ---------------------------------------------------------------------------
# Host-loop L-BFGS (the streamed outer loop)
# ---------------------------------------------------------------------------


@jax.jit
def _direction_jit(grad, S, Y, rho, gamma, n_pairs):
    return -_two_loop(grad, S, Y, rho, gamma, n_pairs)


@jax.jit
def _history_jit(S, Y, rho, gamma, n_pairs, w_new, w_old, g_new, g_old):
    return update_history(
        S, Y, rho, gamma, n_pairs, w_new - w_old, g_new - g_old
    )


@jax.jit
def _axpy_jit(w0, t, direction):
    return w0 + t * direction


@jax.jit
def _vdot_jit(a, b):
    return jnp.vdot(a, b)


class _HostLS:
    """Result of the host-loop weak-Wolfe search (mirrors LineSearchResult)."""

    __slots__ = ("step", "w", "value", "grad", "n_evals", "success")

    def __init__(self, step, w, value, grad, n_evals, success):
        self.step = step
        self.w = w
        self.value = value
        self.grad = grad
        self.n_evals = n_evals
        self.success = success


def _host_wolfe(vg, w0, f0, g0, direction, initial_step, cfg: LineSearchConfig):
    """Weak-Wolfe bisection search with host control flow — the same
    bracketing rules as optim/linesearch.wolfe_line_search, but each trial
    evaluation is a full streamed pass, so host round trips are free by
    comparison."""
    dg0 = float(_vdot_jit(direction, g0))

    def evaluate(t):
        w = _axpy_jit(w0, jnp.float32(t), direction)
        f, g = vg(w)
        return w, float(f), g, float(_vdot_jit(direction, g))

    t = float(initial_step)
    lo, hi = 0.0, math.inf
    w, f, g, dg = evaluate(t)
    n_evals = 1
    while True:
        armijo_ok = f <= f0 + cfg.c1 * t * dg0
        curvature_ok = dg >= cfg.c2 * dg0
        if armijo_ok and curvature_ok:
            break
        if n_evals >= cfg.max_evals:
            break
        if armijo_ok:
            lo = max(lo, t)
        else:
            hi = min(hi, t)
        t_next = 2.0 * lo if math.isinf(hi) else 0.5 * (lo + hi)
        t_next = min(max(t_next, cfg.min_step), cfg.max_step)
        if t_next == t or hi - lo < cfg.min_step:
            break
        t = t_next
        w, f, g, dg = evaluate(t)
        n_evals += 1
    success = (
        f <= f0 + cfg.c1 * t * dg0 and dg >= cfg.c2 * dg0
    )
    return _HostLS(t, w, f, g, n_evals, success)


def streaming_lbfgs_solve(
    value_and_grad: Callable[[Array], tuple[Array, Array]],
    w0: Array,
    config: LBFGSConfig = LBFGSConfig(),
) -> SolveResult:
    """L-BFGS with the outer loop on the host: ``value_and_grad`` may do
    arbitrary host work per call (stream chunks, launch many programs).

    Math mirrors optim/lbfgs.lbfgs_solve exactly — same two-loop recursion
    and curvature-history update (via the SAME functions, jitted), same
    weak-Wolfe bracketing constants, same stall rule (a failed,
    non-improving line search keeps the incumbent), same convergence tests.
    """
    m = config.history
    d = w0.shape[0]
    dtype = w0.dtype
    w0 = jnp.asarray(w0)

    f_dev, g = value_and_grad(w0)
    f = float(f_dev)
    g_norm = float(jnp.linalg.norm(g))
    tol_scale = max(1.0, g_norm)

    values = np.full(config.max_iters + 1, np.nan, np.float64)
    gnorms = np.full(config.max_iters + 1, np.nan, np.float64)
    values[0] = f
    gnorms[0] = g_norm

    S = jnp.zeros((m, d), dtype)
    Y = jnp.zeros((m, d), dtype)
    rho = jnp.zeros((m,), dtype)
    gamma = jnp.asarray(1.0, dtype)
    n_pairs = jnp.asarray(0, jnp.int32)

    w = w0
    k = 0
    converged = g_norm <= config.tolerance * tol_scale
    while not converged and k < config.max_iters:
        direction = _direction_jit(g, S, Y, rho, gamma, n_pairs)
        dg = float(_vdot_jit(direction, g))
        if dg >= 0.0:  # non-descent from a stale history → steepest descent
            direction = -g
        first = int(n_pairs) == 0
        init_step = min(1.0, 1.0 / g_norm) if first else 1.0

        ls = _host_wolfe(
            value_and_grad, w, f, g, direction, init_step, config.line_search
        )

        S, Y, rho, gamma, n_pairs = _history_jit(
            S, Y, rho, gamma, n_pairs, ls.w, w, ls.grad, g
        )

        k += 1
        rel_impr = abs(f - ls.value) / max(abs(f), 1e-12)
        stalled = (not ls.success) and ls.value >= f
        if stalled:
            # Keep the incumbent; convergence measured at the kept point
            # (mirrors the resident solver's stall rule).
            converged = g_norm <= config.tolerance * tol_scale
        else:
            w, f, g = ls.w, ls.value, ls.grad
            g_norm = float(jnp.linalg.norm(ls.grad))
            converged = (
                g_norm <= config.tolerance * tol_scale
                or rel_impr <= config.tolerance * 1e-2
            )
        values[k] = f
        gnorms[k] = g_norm
        if stalled:
            break

    return SolveResult(
        w=w,
        value=jnp.asarray(f, jnp.float32),
        grad=g,
        iterations=jnp.asarray(k, jnp.int32),
        converged=jnp.asarray(bool(converged)),
        values=jnp.asarray(values, jnp.float32),
        grad_norms=jnp.asarray(gnorms, jnp.float32),
    )


# ---------------------------------------------------------------------------
# Host-loop OWL-QN (streamed L1 / elastic-net)
# ---------------------------------------------------------------------------


@jax.jit
def _ow_pseudo_jit(w, grad, l1, mask):
    return _pseudo_gradient(w, grad, l1, mask)


@jax.jit
def _ow_dir_jit(pg, S, Y, rho, gamma, n_pairs):
    direction = -_two_loop(pg, S, Y, rho, gamma, n_pairs)
    # Orthant alignment (Andrew & Gao §3.2): zero coordinates whose sign
    # disagrees with -pg; all-zero direction degrades to steepest descent.
    direction = jnp.where(direction * (-pg) > 0, direction, 0.0)
    deg = jnp.vdot(direction, direction) == 0.0
    return jnp.where(deg, -pg, direction)


@jax.jit
def _ow_trial_jit(w, t, direction, xi):
    wt = w + t * direction
    return jnp.where(wt * xi >= 0, wt, 0.0)  # orthant projection


@jax.jit
def _ow_l1_jit(w, l1, mask):
    return l1 * jnp.vdot(mask, jnp.abs(w))


def streaming_owlqn_solve(
    value_and_grad: Callable[[Array], tuple[Array, Array]],
    w0: Array,
    l1_weight: float,
    config: OWLQNConfig = OWLQNConfig(),
    l1_mask: Optional[Array] = None,
) -> SolveResult:
    """OWL-QN with the outer loop on the host — the streamed counterpart
    of optim/owlqn.owlqn_solve (same pseudo-gradient, orthant alignment
    and projection, projected-step Armijo with non-strict backtracking,
    smooth-gradient history, stall rule, convergence tests).
    ``value_and_grad`` evaluates only the smooth part."""
    m = config.history
    d = w0.shape[0]
    dtype = w0.dtype
    w0 = jnp.asarray(w0)
    l1 = jnp.asarray(l1_weight, jnp.float32)
    mask = (
        jnp.ones((d,), dtype) if l1_mask is None
        else jnp.asarray(l1_mask, dtype)
    )

    def full_value(w, smooth) -> float:
        return float(smooth) + float(_ow_l1_jit(w, l1, mask))

    f_smooth, g = value_and_grad(w0)
    w = w0
    f = full_value(w, f_smooth)
    pg = _ow_pseudo_jit(w, g, l1, mask)
    pg_norm = float(jnp.linalg.norm(pg))
    tol_scale = max(1.0, pg_norm)

    values = np.full(config.max_iters + 1, np.nan, np.float64)
    gnorms = np.full(config.max_iters + 1, np.nan, np.float64)
    values[0] = f
    gnorms[0] = pg_norm

    S = jnp.zeros((m, d), dtype)
    Y = jnp.zeros((m, d), dtype)
    rho = jnp.zeros((m,), dtype)
    gamma = jnp.asarray(1.0, dtype)
    n_pairs = jnp.asarray(0, jnp.int32)

    k = 0
    converged = pg_norm <= config.tolerance * tol_scale
    while not converged and k < config.max_iters:
        pg = _ow_pseudo_jit(w, g, l1, mask)
        direction = _ow_dir_jit(pg, S, Y, rho, gamma, n_pairs)
        # Orthant: sign(w) where nonzero, else the step's sign.
        xi = jnp.where(w != 0, jnp.sign(w), jnp.sign(-pg))
        t = (
            min(1.0, 1.0 / float(jnp.linalg.norm(pg)))
            if int(n_pairs) == 0 else 1.0
        )

        def trial(t):
            wt = _ow_trial_jit(w, jnp.float32(t), direction, xi)
            smooth, grad = value_and_grad(wt)
            return wt, full_value(wt, smooth), grad

        w_new, f_new, g_new = trial(t)
        n_evals = 1
        # Armijo on the PROJECTED step, non-strict (a fully-clamped trial
        # must keep backtracking) — mirrors the resident solver.
        while (
            f_new >= f + config.armijo_c1 * float(_vdot_jit(pg, w_new - w))
            and n_evals < config.max_line_search_evals
        ):
            t *= config.backtrack
            w_new, f_new, g_new = trial(t)
            n_evals += 1

        S, Y, rho, gamma, n_pairs = _history_jit(
            S, Y, rho, gamma, n_pairs, w_new, w, g_new, g
        )

        k += 1
        rel_impr = abs(f - f_new) / max(abs(f), 1e-12)
        stalled = f_new >= f
        if stalled:
            converged = (
                float(jnp.linalg.norm(pg)) <= config.tolerance * tol_scale
            )
        else:
            w, f, g = w_new, f_new, g_new
            pg_new = _ow_pseudo_jit(w, g, l1, mask)
            pg_norm = float(jnp.linalg.norm(pg_new))
            converged = (
                pg_norm <= config.tolerance * tol_scale
                or rel_impr <= config.tolerance * 1e-2
            )
        values[k] = f
        gnorms[k] = pg_norm
        if stalled:
            break

    pg_final = _ow_pseudo_jit(w, g, l1, mask)
    return SolveResult(
        w=w,
        value=jnp.asarray(f, jnp.float32),
        grad=pg_final,
        iterations=jnp.asarray(k, jnp.int32),
        converged=jnp.asarray(bool(converged)),
        values=jnp.asarray(values, jnp.float32),
        grad_norms=jnp.asarray(gnorms, jnp.float32),
    )


# ---------------------------------------------------------------------------
# Host-loop TRON (streamed trust-region Newton)
# ---------------------------------------------------------------------------


def _host_steihaug_cg(hvp, g, delta, max_iters, tol):
    """Steihaug CG with host control flow — same math as optim/tron.py's
    ``_steihaug_cg`` (negative-curvature and radius-crossing exits to the
    boundary, residual kept consistent with the returned step), but each
    Hessian-vector product is a full streamed pass, so host round-trips
    are free by comparison.

    Returns ``(s, r, n_hvp)`` with ``r = -g - H·s`` for the returned ``s``
    (so sᵀHs is recoverable without another streamed pass)."""
    s = jnp.zeros_like(g)
    r = _axpy_jit(jnp.zeros_like(g), jnp.float32(-1.0), g)
    p = r
    rr = float(_vdot_jit(r, r))
    if math.sqrt(rr) <= tol:
        return s, r, 0
    n_hvp = 0
    for _ in range(max_iters):
        Hp = hvp(p)
        n_hvp += 1
        pHp = float(_vdot_jit(p, Hp))
        neg_curv = pHp <= 0.0
        alpha = rr / (pHp if pHp > 0.0 else 1.0)
        s_next = _axpy_jit(s, jnp.float32(alpha), p)
        crosses = math.sqrt(float(_vdot_jit(s_next, s_next))) >= delta
        if neg_curv or crosses:
            # Go to the trust-region boundary along p: ‖s + τp‖ = delta.
            pp = float(_vdot_jit(p, p))
            sp = float(_vdot_jit(s, p))
            ss = float(_vdot_jit(s, s))
            disc = max(sp * sp + pp * (delta * delta - ss), 0.0)
            tau = (-sp + math.sqrt(disc)) / max(pp, 1e-30)
            s = _axpy_jit(s, jnp.float32(tau), p)
            r = _axpy_jit(r, jnp.float32(-tau), Hp)
            break
        s = s_next
        r = _axpy_jit(r, jnp.float32(-alpha), Hp)
        rr_new = float(_vdot_jit(r, r))
        if math.sqrt(rr_new) <= tol:
            break
        beta = rr_new / max(rr, 1e-30)
        p = _axpy_jit(r, jnp.float32(beta), p)
        rr = rr_new
    return s, r, n_hvp


def streaming_tron_solve(
    value_and_grad: Callable[[Array], tuple[Array, Array]],
    hvp_fn: Callable[[Array, Array], Array],
    w0: Array,
    config=None,
) -> SolveResult:
    """Trust-region Newton-CG with the outer loop on the host — the
    streamed counterpart of optim/tron.tron_solve, closing the last
    optimizer×residency cell: the reference runs TRON distributed, one
    ``HessianVectorAggregator`` treeAggregate round per CG step
    (SURVEY.md §3.1 / BASELINE config 3); here each CG step is one
    streamed :meth:`StreamingObjective.hvp` pass.

    Math mirrors the resident solver step-for-step: LIBLINEAR initial
    radius ``‖g0‖``, the same forcing tolerance, acceptance threshold and
    radius-update constants (via the shared ``TRONConfig``), the same
    boundary-consistent residual trick recovering sᵀHs without an extra
    HVP, and the same convergence/stall rules — so a single-chunk streamed
    solve tracks the resident trajectory to float tolerance.

    ``hvp_fn(w, v)`` must return the REGULARIZED Hessian-vector product.
    """
    from photon_ml_tpu.optim.tron import TRONConfig

    if config is None:
        config = TRONConfig()
    w = jnp.asarray(w0)

    f_dev, g = value_and_grad(w)
    f = float(f_dev)
    g_norm = float(jnp.linalg.norm(g))
    tol_scale = max(1.0, g_norm)
    delta = g_norm  # LIBLINEAR: initial radius = ||g0||

    values = np.full(config.max_iters + 1, np.nan, np.float64)
    gnorms = np.full(config.max_iters + 1, np.nan, np.float64)
    values[0] = f
    gnorms[0] = g_norm

    k = 0
    converged = g_norm <= config.tolerance * tol_scale
    while not converged and k < config.max_iters:
        cg_tol = config.cg_tol * g_norm
        step, residual, _ = _host_steihaug_cg(
            lambda v: hvp_fn(w, v), g, delta, config.max_cg_iters, cg_tol
        )

        w_try = _axpy_jit(w, jnp.float32(1.0), step)
        f_try_dev, g_try = value_and_grad(w_try)
        f_try = float(f_try_dev)

        gs = float(_vdot_jit(g, step))
        # r = -g - H·s  ⇒  sᵀHs = -s·r - s·g (one saved streamed pass per
        # outer iteration, as in the resident solver).
        sHs = -float(_vdot_jit(step, residual)) - gs
        pred = -(gs + 0.5 * sHs)
        ared = f - f_try
        rho = ared / (pred if pred > 0.0 else 1e-30)
        accept = rho > config.eta0 and pred > 0.0

        # Radius update (LIBLINEAR-style, same constants as the resident).
        snorm = math.sqrt(max(float(_vdot_jit(step, step)), 0.0))
        if rho < config.eta1:
            delta_new = max(config.sigma1 * snorm, config.sigma2 * delta)
            if rho < config.eta0:
                delta_new *= config.sigma2
        elif rho > config.eta2:
            delta_new = max(delta, config.sigma3 * snorm)
        else:
            delta_new = delta
        delta = max(delta_new, 1e-20)

        k += 1
        if accept:
            rel_impr = abs(ared) / max(abs(f), 1e-12)
            w, f, g = w_try, f_try, g_try
            g_norm = float(jnp.linalg.norm(g))
        else:
            rel_impr = math.inf
        converged = (
            g_norm <= config.tolerance * tol_scale
            or rel_impr <= config.tolerance * 1e-2
        )
        values[k] = f
        gnorms[k] = g_norm
        if delta <= 1e-18:  # radius collapsed: no further progress possible
            break

    return SolveResult(
        w=w,
        value=jnp.asarray(f, jnp.float32),
        grad=g,
        iterations=jnp.asarray(k, jnp.int32),
        converged=jnp.asarray(bool(converged)),
        values=jnp.asarray(values, jnp.float32),
        grad_norms=jnp.asarray(gnorms, jnp.float32),
    )


# ---------------------------------------------------------------------------
# Grid sweep over a streamed dataset
# ---------------------------------------------------------------------------


def ensure_streamable(config) -> None:
    """Reject configs the streamed path cannot train — callable BEFORE the
    (possibly hours-long) chunk-store ingest, and always re-checked by
    :func:`streaming_run_grid`.

    Every optimizer now streams (L-BFGS, OWL-QN, and smooth TRON via
    :func:`streaming_tron_solve`), so this currently accepts everything;
    it remains the single gate future unstreamable features must fail
    loudly through."""


def streaming_run_grid(
    problem,
    stream: StreamingGlmData,
    reg_weights: Sequence[float],
    w0: Optional[Array] = None,
    mesh=None,
    warm_start: bool = True,
    solved: Optional[dict] = None,
    on_solved=None,
    accumulate: str = "f32",
    l1_mask: Optional[Array] = None,
    prefetch_depth: int = 2,
):
    """The λ-grid warm-start chain (optim.problem.grid_loop) over a
    streamed dataset.  L1/elastic-net routes to the streamed OWL-QN and
    smooth TRON to the streamed trust-region solver (exactly like the
    resident problem.solve's static routing).
    """
    from photon_ml_tpu.optim.problem import OptimizerType
    from photon_ml_tpu.optim.tron import TRONConfig

    cfg = problem.config
    ensure_streamable(cfg)
    sobj = StreamingObjective(
        problem.objective, stream, mesh=mesh, accumulate=accumulate,
        prefetch_depth=prefetch_depth,
    )
    opt = cfg.optimizer
    lbfgs_cfg = LBFGSConfig(
        max_iters=opt.max_iters,
        tolerance=opt.tolerance,
        history=opt.history,
    )
    owlqn_cfg = OWLQNConfig(
        max_iters=opt.max_iters,
        tolerance=opt.tolerance,
        history=opt.history,
    )
    l1_frac = cfg.regularization.l1_weight(1.0)

    def solve_fn(lam, w_prev):
        l1 = l1_frac * float(lam)
        l2 = cfg.regularization.l2_weight(1.0) * float(lam)
        if w_prev is None:
            w_prev = jnp.zeros((stream.n_features,), jnp.float32)
        # Static routing, as in problem.solve: any L1 component needs the
        # orthant machinery.
        if opt.optimizer is OptimizerType.OWLQN or l1_frac > 0.0:
            return streaming_owlqn_solve(
                lambda w: sobj.value_and_grad(w, l2), w_prev, l1,
                owlqn_cfg, l1_mask=l1_mask,
            )
        if opt.optimizer is OptimizerType.TRON:
            return streaming_tron_solve(
                lambda w: sobj.value_and_grad(w, l2),
                lambda w, v: sobj.hvp(w, v, l2),
                w_prev,
                TRONConfig(max_iters=opt.max_iters, tolerance=opt.tolerance),
            )
        return streaming_lbfgs_solve(
            lambda w: sobj.value_and_grad(w, l2), w_prev, lbfgs_cfg
        )

    variance_fn = None
    if cfg.compute_variances:
        def variance_fn(w, lam):
            l2 = cfg.regularization.l2_weight(1.0) * float(lam)
            diag = sobj.hessian_diagonal(w)
            return 1.0 / jnp.maximum(diag + l2, 1e-12)

    return problem.grid_loop(
        solve_fn, reg_weights, w0, warm_start, solved, on_solved, variance_fn
    )
