"""Weak-Wolfe line search as a bounded ``lax.while_loop``.

The reference's L-BFGS delegates line search to Breeze's
``StrongWolfeLineSearch`` (SURVEY.md §2, Optimizers).  On TPU the line search
must live *inside* the jitted optimizer step — a host round-trip per trial
point would dominate the epoch time — so we use the classic bisection /
doubling weak-Wolfe search (Lewis & Overton style): it needs no nested zoom
stage, is branchless-friendly, and terminates in a bounded number of
objective evaluations, which is exactly what ``lax.while_loop`` wants.

Each trial point costs one fused value+gradient evaluation (for distributed
objectives, one ``psum`` over ICI — the analogue of one ``treeAggregate``
round in the reference's hot loop, SURVEY.md §3.1).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array

# f(w) -> (value, grad): the only thing the line search needs.
ValueAndGrad = Callable[[Array], tuple[Array, Array]]


def pvdot(a: Array, b: Array, w_axis: str | None = None) -> Array:
    """w-space inner product; with ``w_axis`` the vectors are SHARDS of a
    coefficient vector sharded over that mesh axis (feature-dim / tensor
    parallelism — SURVEY.md §2 parallelism table, TP row) and the partial
    dot is psum'd so every device sees the global value."""
    r = jnp.vdot(a, b)
    return lax.psum(r, w_axis) if w_axis is not None else r


def pnorm(a: Array, w_axis: str | None = None) -> Array:
    """w-space 2-norm, global under w-sharding (see :func:`pvdot`)."""
    if w_axis is None:
        return jnp.linalg.norm(a)
    return jnp.sqrt(pvdot(a, a, w_axis))


@dataclasses.dataclass(frozen=True)
class LineSearchConfig:
    c1: float = 1e-4  # Armijo (sufficient decrease) constant
    c2: float = 0.9  # curvature constant (0.9 is standard for quasi-Newton)
    max_evals: int = 30
    min_step: float = 1e-20
    max_step: float = 1e20


class LineSearchResult(NamedTuple):
    step: Array  # accepted step size t
    w: Array  # w0 + t * direction
    value: Array  # f(w)
    grad: Array  # ∇f(w)
    n_evals: Array  # objective evaluations used
    success: Array  # bool — both Wolfe conditions met


class _SearchState(NamedTuple):
    lo: Array  # lower bracket (largest t known to satisfy Armijo)
    hi: Array  # upper bracket (smallest t known to violate Armijo); inf if none
    t: Array
    w: Array
    value: Array
    grad: Array
    dg: Array  # directional derivative at t
    n_evals: Array
    done: Array


def wolfe_line_search(
    value_and_grad: ValueAndGrad,
    w0: Array,
    f0: Array,
    g0: Array,
    direction: Array,
    initial_step: Array | float = 1.0,
    config: LineSearchConfig = LineSearchConfig(),
    w_axis: str | None = None,
) -> LineSearchResult:
    """Find t satisfying the weak Wolfe conditions along ``direction``.

    Bisection bracketing: Armijo failure shrinks the upper bracket, curvature
    failure grows the lower bracket; the next trial is the midpoint (or 2·lo
    while unbracketed).  Always returns the last evaluated point; ``success``
    reports whether the Wolfe conditions actually held (callers fall back to
    steepest descent / skip the curvature pair when it is False).

    ``w_axis``: mesh axis name when w/grad/direction are feature-dim shards
    (directional derivatives are then psum'd globals).
    """
    dg0 = pvdot(direction, g0, w_axis)
    # Step sizes live in w-space dtype: with f64 VALUE accumulation
    # (GlmObjective accumulate="f64") f0 is float64 while w stays float32 —
    # tying t to f0.dtype would silently upcast every trial iterate (and
    # the feature matvec behind it) to f64.
    t0 = jnp.asarray(initial_step, dtype=w0.dtype)

    def evaluate(t):
        w = w0 + t * direction
        value, grad = value_and_grad(w)
        return w, value, grad, pvdot(direction, grad, w_axis)

    def cond(s: _SearchState):
        return jnp.logical_and(~s.done, s.n_evals < config.max_evals)

    def body(s: _SearchState):
        armijo_ok = s.value <= f0 + config.c1 * s.t * dg0
        curvature_ok = s.dg >= config.c2 * dg0
        done = jnp.logical_and(armijo_ok, curvature_ok)

        # Armijo fails → bracket from above; curvature fails → from below.
        hi = jnp.where(armijo_ok, s.hi, jnp.minimum(s.hi, s.t))
        lo = jnp.where(armijo_ok, jnp.maximum(s.lo, s.t), s.lo)
        t_next = jnp.where(jnp.isinf(hi), 2.0 * lo, 0.5 * (lo + hi))
        t_next = jnp.clip(t_next, config.min_step, config.max_step)

        # Degenerate bracket → stop where we are.
        stuck = jnp.logical_or(t_next == s.t, hi - lo < config.min_step)
        done = jnp.logical_or(done, stuck)

        def step(_):
            w, value, grad, dg = evaluate(t_next)
            return _SearchState(
                lo, hi, t_next, w, value, grad, dg, s.n_evals + 1, done
            )

        def stay(_):
            return _SearchState(
                lo, hi, s.t, s.w, s.value, s.grad, s.dg, s.n_evals, done
            )

        return lax.cond(done, stay, step, None)

    w1, f1, g1, dg1 = evaluate(t0)
    init = _SearchState(
        lo=jnp.zeros_like(t0),
        hi=jnp.full_like(t0, jnp.inf),
        t=t0,
        w=w1,
        value=f1,
        grad=g1,
        dg=dg1,
        n_evals=jnp.asarray(1, jnp.int32),
        done=jnp.asarray(False),
    )
    final = lax.while_loop(cond, body, init)

    armijo_ok = final.value <= f0 + config.c1 * final.t * dg0
    curvature_ok = final.dg >= config.c2 * dg0
    success = jnp.logical_and(armijo_ok, curvature_ok)
    return LineSearchResult(
        step=final.t,
        w=final.w,
        value=final.value,
        grad=final.grad,
        n_evals=final.n_evals,
        success=success,
    )


# (OWL-QN's Armijo backtracking lives inline in owlqn.py because each trial
# point must be orthant-projected before evaluation.)
