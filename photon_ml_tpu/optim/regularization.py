"""Regularization configuration.

The analogue of the reference's ``RegularizationContext`` /
``RegularizationType`` (SURVEY.md §2): L2 is folded into the differentiable
objective (value, gradient, Hessian all see it); L1 is *not* differentiable
and is handled by the OWL-QN optimizer's orthant machinery; elastic net
splits one regularization weight λ into α·λ toward L1 and (1-α)·λ toward L2.
"""

from __future__ import annotations

import dataclasses
import enum


class RegularizationType(enum.Enum):
    NONE = "none"
    L1 = "l1"
    L2 = "l2"
    ELASTIC_NET = "elastic_net"


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    """Splits a total regularization weight into its L1 and L2 components."""

    reg_type: RegularizationType = RegularizationType.NONE
    # Elastic-net mixing weight α: fraction of λ applied as L1 (as in the
    # reference's ElasticNetRegularizationContext).
    alpha: float = 0.5

    def l1_weight(self, reg_weight: float) -> float:
        if self.reg_type is RegularizationType.L1:
            return reg_weight
        if self.reg_type is RegularizationType.ELASTIC_NET:
            return self.alpha * reg_weight
        return 0.0

    def l2_weight(self, reg_weight: float) -> float:
        if self.reg_type is RegularizationType.L2:
            return reg_weight
        if self.reg_type is RegularizationType.ELASTIC_NET:
            return (1.0 - self.alpha) * reg_weight
        return 0.0

    @staticmethod
    def none() -> "RegularizationContext":
        return RegularizationContext(RegularizationType.NONE)

    @staticmethod
    def l1() -> "RegularizationContext":
        return RegularizationContext(RegularizationType.L1)

    @staticmethod
    def l2() -> "RegularizationContext":
        return RegularizationContext(RegularizationType.L2)

    @staticmethod
    def elastic_net(alpha: float) -> "RegularizationContext":
        return RegularizationContext(RegularizationType.ELASTIC_NET, alpha)
