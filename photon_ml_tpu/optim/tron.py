"""TRON: Trust-Region Newton method, fully on-device.

The analogue of the reference's ``TRON`` optimizer (photon-lib; a port of
LIBLINEAR's trust-region Newton — SURVEY.md §2; BASELINE.json: "TRON
trust-region Newton with on-device Hessian-vector products").  Outer loop:
propose a step by approximately minimizing the quadratic model within a trust
region via Steihaug conjugate gradient; accept/reject by the actual-vs-
predicted reduction ratio; grow/shrink the radius.  Inner CG needs one
Hessian-vector product per step — in the reference that is one
``HessianVectorAggregator`` ``treeAggregate`` round per CG step
(SURVEY.md §3.1); here it is one (sparse) matvec pair, with ``psum`` when
distributed.

The GLM structure is exploited exactly as the reference does: the Hessian at
a fixed ``w`` is ``Xᵀ diag(weight·d2(m)) X + λI``, so ``d2_weights`` is
computed ONCE per accepted outer iterate and every CG step reuses it
(``hvp_fn(w, v, aux)`` with cached ``aux``).

Both loops are ``lax.while_loop``s inside one jitted program — no host
round-trips, matching lbfgs.py.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.lbfgs import SolveResult
from photon_ml_tpu.optim.linesearch import ValueAndGrad, pnorm, pvdot

Array = jax.Array

# hvp_fn(w, v, aux) -> H(w) @ v, where aux = d2_fn(w) is per-iterate cache.
HvpFn = Callable[[Array, Array, object], Array]
D2Fn = Callable[[Array], object]


@dataclasses.dataclass(frozen=True)
class TRONConfig:
    max_iters: int = 100
    tolerance: float = 1e-7
    max_cg_iters: int = 50
    # CG forcing tolerance: stop when ||r|| <= cg_tol · ||g|| (LIBLINEAR xi).
    cg_tol: float = 0.1
    # Step-acceptance threshold and radius-update constants (LIBLINEAR).
    eta0: float = 1e-4
    eta1: float = 0.25
    eta2: float = 0.75
    sigma1: float = 0.25
    sigma2: float = 0.5
    sigma3: float = 4.0


class _CGState(NamedTuple):
    s: Array  # current step estimate
    r: Array  # residual -g - H s
    p: Array  # search direction
    rr: Array  # <r, r>
    i: Array
    done: Array
    hit_boundary: Array


def _steihaug_cg(
    hvp: Callable[[Array], Array],
    g: Array,
    delta: Array,
    max_iters: int,
    tol: Array,
    w_axis: Optional[str] = None,
) -> tuple[Array, Array, Array]:
    """Approximately minimize g·s + ½ sᵀHs subject to ‖s‖ ≤ delta.

    Returns (s, r, hit_boundary) with r = -g - H·s the final residual
    (kept consistent with s even on boundary exits, so sᵀHs is recoverable
    without another HVP).  Negative-curvature and radius-crossing cases move
    to the trust-region boundary along the current direction.
    """
    d = g.shape[0]
    dtype = g.dtype

    def boundary_tau(s, p):
        # Solve ‖s + τ p‖ = delta for τ ≥ 0.
        pp = pvdot(p, p, w_axis)
        sp = pvdot(s, p, w_axis)
        ss = pvdot(s, s, w_axis)
        disc = jnp.maximum(sp * sp + pp * (delta * delta - ss), 0.0)
        return (-sp + jnp.sqrt(disc)) / jnp.maximum(pp, 1e-30)

    init = _CGState(
        s=jnp.zeros((d,), dtype),
        r=-g,
        p=-g,
        rr=pvdot(g, g, w_axis),
        i=jnp.asarray(0, jnp.int32),
        done=pnorm(g, w_axis) <= tol,
        hit_boundary=jnp.asarray(False),
    )

    def cond(c: _CGState):
        return jnp.logical_and(~c.done, c.i < max_iters)

    def body(c: _CGState):
        Hp = hvp(c.p)
        pHp = pvdot(c.p, Hp, w_axis)

        # Negative curvature → go to the boundary along p.
        neg_curv = pHp <= 0.0

        alpha = c.rr / jnp.where(pHp > 0, pHp, 1.0)
        s_next = c.s + alpha * c.p
        crosses = pnorm(s_next, w_axis) >= delta

        take_boundary = jnp.logical_or(neg_curv, crosses)
        tau = boundary_tau(c.s, c.p)
        step_len = jnp.where(take_boundary, tau, alpha)
        s_new = c.s + step_len * c.p
        # Maintain r = -g - H s for the RETURNED step, including the
        # boundary case, so callers can recover sᵀHs from r without an
        # extra Hessian-vector product.
        r_new = c.r - step_len * Hp

        rr_new = pvdot(r_new, r_new, w_axis)
        small = jnp.sqrt(rr_new) <= tol
        beta = rr_new / jnp.maximum(c.rr, 1e-30)
        p_new = r_new + beta * c.p

        done = jnp.logical_or(take_boundary, small)
        return _CGState(
            s=s_new,
            r=r_new,
            p=jnp.where(take_boundary, c.p, p_new),
            rr=rr_new,
            i=c.i + 1,
            done=done,
            hit_boundary=jnp.logical_or(c.hit_boundary, take_boundary),
        )

    final = lax.while_loop(cond, body, init)
    return final.s, final.r, final.hit_boundary


class _TRONState(NamedTuple):
    w: Array
    value: Array
    grad: Array
    aux: object  # cached d2 weights for the current iterate
    delta: Array  # trust-region radius
    k: Array
    done: Array
    converged: Array
    values: Array
    grad_norms: Array


def tron_solve(
    value_and_grad: ValueAndGrad,
    hvp_fn: HvpFn,
    w0: Array,
    config: TRONConfig = TRONConfig(),
    d2_fn: Optional[D2Fn] = None,
    w_axis: Optional[str] = None,
) -> SolveResult:
    """Minimize via trust-region Newton-CG.

    ``hvp_fn(w, v, aux)`` must return the (regularized) Hessian-vector
    product; ``d2_fn(w)`` produces the reusable per-iterate cache passed as
    ``aux`` (pass None to recompute inside hvp_fn each call).

    ``w_axis``: mesh axis name when ``w0``/gradients/HVPs are feature-dim
    SHARDS (tensor parallelism) — every w-space inner product and norm in
    the outer loop and the Steihaug CG then reduces over that axis.
    """
    dtype = w0.dtype
    make_aux = d2_fn if d2_fn is not None else (lambda w: jnp.zeros((0,), dtype))

    f0, g0 = value_and_grad(w0)
    g0_norm = pnorm(g0, w_axis)
    tol_scale = jnp.maximum(1.0, g0_norm)

    n_track = config.max_iters + 1
    values0 = jnp.full((n_track,), jnp.nan, dtype).at[0].set(f0.astype(dtype))
    gnorms0 = jnp.full((n_track,), jnp.nan, dtype).at[0].set(g0_norm)

    init = _TRONState(
        w=w0,
        value=f0,
        grad=g0,
        aux=make_aux(w0),
        delta=g0_norm,  # LIBLINEAR: initial radius = ||g0||
        k=jnp.asarray(0, jnp.int32),
        done=g0_norm <= config.tolerance * tol_scale,
        converged=g0_norm <= config.tolerance * tol_scale,
        values=values0,
        grad_norms=gnorms0,
    )

    def cond(s: _TRONState):
        return jnp.logical_and(~s.done, s.k < config.max_iters)

    def body(s: _TRONState):
        cg_tol = config.cg_tol * pnorm(s.grad, w_axis)
        step, residual, _ = _steihaug_cg(
            lambda v: hvp_fn(s.w, v, s.aux),
            s.grad,
            s.delta,
            config.max_cg_iters,
            cg_tol,
            w_axis,
        )

        w_try = s.w + step
        f_try, g_try = value_and_grad(w_try)

        gs = pvdot(s.grad, step, w_axis)
        # r = -g - H·s  ⇒  sᵀHs = -s·r - s·g; saves one HVP (and its psum
        # round when distributed) per outer iteration, as LIBLINEAR does.
        sHs = -pvdot(step, residual, w_axis) - gs
        pred = -(gs + 0.5 * sHs)
        ared = s.value - f_try
        rho = ared / jnp.where(pred > 0, pred, 1e-30)

        accept = jnp.logical_and(rho > config.eta0, pred > 0)
        w_new = jnp.where(accept, w_try, s.w)
        f_new = jnp.where(accept, f_try, s.value)
        g_new = jnp.where(accept, g_try, s.grad)
        aux_new = jax.tree.map(
            lambda a, b: jnp.where(accept, a, b), make_aux(w_try), s.aux
        )

        # Radius update (LIBLINEAR-style).
        snorm = pnorm(step, w_axis)
        delta = jnp.where(
            rho < config.eta1,
            jnp.maximum(config.sigma1 * snorm, config.sigma2 * s.delta)
            * jnp.where(rho < config.eta0, config.sigma2, 1.0),
            jnp.where(
                rho > config.eta2,
                jnp.maximum(s.delta, config.sigma3 * snorm),
                s.delta,
            ),
        )
        delta = jnp.maximum(delta, 1e-20)

        k = s.k + 1
        g_norm = pnorm(g_new, w_axis)
        rel_impr = jnp.where(
            accept,
            jnp.abs(ared) / jnp.maximum(jnp.abs(s.value), 1e-12),
            jnp.asarray(jnp.inf, dtype),
        )
        converged = jnp.logical_or(
            g_norm <= config.tolerance * tol_scale,
            rel_impr <= config.tolerance * 1e-2,
        )
        # If the radius collapsed, no further progress is possible.
        stalled = delta <= 1e-18

        return _TRONState(
            w=w_new,
            value=f_new,
            grad=g_new,
            aux=aux_new,
            delta=delta,
            k=k,
            done=jnp.logical_or(converged, stalled),
            converged=converged,
            values=s.values.at[k].set(f_new.astype(s.values.dtype)),
            grad_norms=s.grad_norms.at[k].set(g_norm),
        )

    final = lax.while_loop(cond, body, init)
    return SolveResult(
        w=final.w,
        value=final.value,
        grad=final.grad,
        iterations=final.k,
        converged=final.converged,
        values=final.values,
        grad_norms=final.grad_norms,
    )
