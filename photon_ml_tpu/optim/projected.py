"""Box-constrained solves: spectral projected gradient (SPG), on-device.

The reference's optimizer layer supports box-constrained convex
optimization — per-coefficient bounds supplied as a constraint map to the
legacy ``Driver`` (SURVEY.md §2 Optimizers row: "box-constrained /
unconstrained convex optimization").  A Breeze-style L-BFGS-B port would
be the translation; the TPU-native choice is SPG (Birgin–Martínez–Raydan):
each iteration is ONE projection (``jnp.clip``), a Barzilai–Borwein step
length, and an Armijo backtrack along the feasible segment — branchless,
static-shape, a single ``lax.while_loop`` with no per-iteration host
round trips, and exact for the convex GLM objectives this framework
trains.  Convergence is measured by the projected-gradient norm
``‖P(w − g) − w‖`` (zero exactly at a constrained stationary point).

Feasibility is maintained by construction: the search direction is
``d = P(w − α·g) − w`` and trial points ``w + λ·d`` for λ ∈ (0, 1] stay
inside the box (it is convex), so no trial ever needs re-projection.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.lbfgs import SolveResult
from photon_ml_tpu.optim.linesearch import ValueAndGrad, pnorm, pvdot

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class SPGConfig:
    max_iters: int = 100
    tolerance: float = 1e-7  # relative, on the projected-gradient norm
    alpha_min: float = 1e-10  # BB step clamp
    alpha_max: float = 1e10
    armijo_c: float = 1e-4
    max_backtracks: int = 30


def spg_solve(
    value_and_grad: ValueAndGrad,
    w0: Array,
    lower: Array,
    upper: Array,
    config: SPGConfig = SPGConfig(),
    w_axis: str | None = None,
) -> SolveResult:
    """Minimize subject to ``lower <= w <= upper`` (±inf entries leave a
    coefficient unconstrained).  Returns the same :class:`SolveResult`
    as the unconstrained solvers; ``grad_norms`` tracks the
    projected-gradient norm (the constrained optimality measure).

    ``converged`` is True ONLY when the projected-gradient norm met the
    tolerance — the constrained stationarity test.  An
    objective-plateau (ftol) or failed-backtrack exit that never met it
    ends the loop with ``converged=False`` and ``stalled=True``
    instead: reporting a plateau as convergence hid genuinely stuck
    solves behind a green flag (ADVICE r5)."""
    f0, g0 = value_and_grad(jnp.clip(w0, lower, upper))
    # The objective's gradient dtype governs the whole carry (a f32 w0
    # against a f64 objective would otherwise promote mid-loop and break
    # the while_loop's carry-type invariant).
    dtype = g0.dtype
    lower = jnp.asarray(lower, dtype)
    upper = jnp.asarray(upper, dtype)

    def project(w):
        return jnp.clip(w, lower, upper)

    w0 = project(w0.astype(dtype))
    f0 = f0.astype(dtype)
    pg0 = pnorm(w0 - project(w0 - g0), w_axis)
    tol_scale = jnp.maximum(1.0, pg0)

    n_track = config.max_iters + 1
    values0 = jnp.full((n_track,), jnp.nan, dtype).at[0].set(f0.astype(dtype))
    gnorms0 = jnp.full((n_track,), jnp.nan, dtype).at[0].set(pg0)

    init = (
        w0, f0, g0,
        jnp.asarray(1.0, dtype),  # BB step length α
        jnp.asarray(0, jnp.int32),  # k
        pg0 <= config.tolerance * tol_scale,  # done
        pg0 <= config.tolerance * tol_scale,  # converged
        values0, gnorms0,
    )

    def cond(s):
        _w, _f, _g, _a, k, done, _c, _v, _gn = s
        return jnp.logical_and(~done, k < config.max_iters)

    def body(s):
        w, f, g, alpha, k, _done, _conv, values, gnorms = s
        d = project(w - alpha * g) - w
        gd = pvdot(g, d, w_axis)

        # Armijo backtrack along the feasible segment w + λ·d, λ = 2^-t.
        # Written as ~(ft <= bound) so a NaN trial (overflowing Poisson
        # exp) counts as an Armijo FAILURE and keeps backtracking — the
        # same NaN semantics as the Wolfe search in linesearch.py; the
        # inverted comparison would silently accept the NaN iterate.
        def ls_cond(c):
            lamb, ft, _wt, _gt, tries = c
            return jnp.logical_and(
                ~(ft <= f + config.armijo_c * lamb * gd),
                tries < config.max_backtracks,
            )

        def ls_body(c):
            lamb, _ft, _wt, _gt, tries = c
            lamb = lamb * 0.5
            wt = w + lamb * d
            ft, gt = value_and_grad(wt)
            return lamb, ft, wt, gt, tries + 1

        w1 = w + d
        f1, g1 = value_and_grad(w1)
        lamb, ft, wt, gt, tries = lax.while_loop(
            ls_cond, ls_body, (jnp.asarray(1.0, dtype), f1, w1, g1,
                               jnp.asarray(0, jnp.int32))
        )
        # A stalled backtrack (no decrease within max_backtracks — or a
        # still-NaN trial) keeps the incumbent, mirroring the L-BFGS
        # discipline.
        stalled = ~(ft <= f + config.armijo_c * lamb * gd)
        w_next = jnp.where(stalled, w, wt)
        f_next = jnp.where(stalled, f, ft)
        g_next = jnp.where(stalled, g, gt)

        # Barzilai–Borwein step for the next iteration.
        s_vec = w_next - w
        y_vec = g_next - g
        sy = pvdot(s_vec, y_vec, w_axis)
        ss = pvdot(s_vec, s_vec, w_axis)
        alpha_next = jnp.where(
            sy > 0.0,
            jnp.clip(ss / jnp.maximum(sy, 1e-30),
                     config.alpha_min, config.alpha_max),
            config.alpha_max,
        )

        k = k + 1
        pg = pnorm(w_next - project(w_next - g_next), w_axis)
        rel_impr = jnp.abs(f - f_next) / jnp.maximum(jnp.abs(f), 1e-12)
        # ``converged`` is the stationarity test alone; an ftol plateau
        # (or a stalled backtrack) ends the loop WITHOUT claiming it.
        converged = pg <= config.tolerance * tol_scale
        plateau = jnp.logical_and(
            ~stalled, rel_impr <= config.tolerance * 1e-2
        )
        done = jnp.logical_or(converged, jnp.logical_or(plateau, stalled))
        return (
            w_next, f_next, g_next, alpha_next, k,
            done, converged,
            values.at[k].set(f_next.astype(dtype)),
            gnorms.at[k].set(pg),
        )

    w, f, g, _a, k, done, converged, values, gnorms = lax.while_loop(
        cond, body, init
    )
    return SolveResult(
        w=w, value=f, grad=g, iterations=k, converged=converged,
        values=values, grad_norms=gnorms,
        # Exited early without stationarity (plateau / failed backtrack);
        # False on a max_iters exit, which claims neither.
        stalled=jnp.logical_and(done, ~converged),
    )
