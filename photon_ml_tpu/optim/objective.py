"""GLM objective functions: value / gradient / Hessian-vector product.

The analogue of the reference's ``ObjectiveFunction`` hierarchy —
``DistributedGLMLossFunction`` / ``SingleNodeGLMLossFunction`` and their
``ValueAndGradientAggregator`` / ``HessianVectorAggregator`` hot loops
(SURVEY.md §2, §3.1).  Where the reference splits "distributed" and
"single-node" into separate class trees (Spark treeAggregate vs local loops),
here ONE pure function serves both: computed per-shard, it is the single-node
objective; wrapped in ``shard_map`` with ``axis_name='data'`` it becomes the
distributed objective, with ``lax.psum`` playing the role of
``RDD.treeAggregate`` (see photon_ml_tpu.parallel.distributed).

Semantics follow the reference: the data term is a **weighted sum** (not
mean) of per-example losses; L2 adds ``½·λ·‖w‖²`` to the value, ``λ·w`` to
the gradient, and ``λ·v`` to the HVP.  L1 never appears here — it lives in
OWL-QN's orthant logic (optim/owlqn.py), as in the reference.

The Hessian-vector product uses the Gauss-Newton/GLM closed form
``Xᵀ(weight ⊙ d2(m) ⊙ (X v))`` — what the reference's
``HessianVectorAggregator`` computes with per-row BLAS — rather than
generic forward-over-reverse autodiff, because it reuses the cached margins
and keeps the hot loop at exactly two (sparse) matvecs per CG step.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.data.dataset import GlmData
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.ops.losses import PointwiseLoss

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class GlmObjective:
    """Binds a pointwise loss and optional normalization into a GLM objective.

    All methods are pure and jit/vmap/shard_map-safe.  ``l2_weight`` is a
    method argument (not a field) so a single compiled optimizer can sweep a
    regularization grid without recompilation — the TPU analogue of the
    reference's warm-start loop over regularization weights.
    """

    loss: PointwiseLoss
    normalization: Optional[NormalizationContext] = None
    # "f32": plain XLA tree reduction (default — summands are non-negative
    # for every supported loss, so the tree sum's relative error is already
    # ~log₂(n)·ε).  "f64": the VALUE reduction upcasts to float64 before
    # summing (the reference accumulates in f64 end-to-end via Breeze) and
    # the returned value STAYS f64 so convergence tests in the solvers see
    # the extra precision; needs ``jax_enable_x64`` (works on this TPU —
    # XLA emulates f64 — at a cost on the value pass only; the gradient's
    # per-coordinate sums stay f32 tree reductions).
    accumulate: str = "f32"

    def __post_init__(self):
        if self.accumulate not in ("f32", "f64"):
            raise ValueError(
                f"accumulate must be f32|f64, got {self.accumulate!r}"
            )
        if self.accumulate == "f64":
            import jax as _jax

            if not _jax.config.jax_enable_x64:
                raise ValueError(
                    "accumulate='f64' needs jax_enable_x64 "
                    "(jax.config.update('jax_enable_x64', True))"
                )

    def _wsum(self, weights: Array, vals: Array) -> Array:
        """The objective's weighted-sum reduction (see ``accumulate``)."""
        prod = weights * vals
        if self.accumulate == "f64":
            return jnp.sum(prod.astype(jnp.float64))
        return jnp.sum(prod)

    # -- normalized linear maps (see data/normalization.py) ----------------
    def _matvec(self, data: GlmData, w: Array) -> Array:
        norm = self.normalization
        if norm is None:
            return data.features.matvec(w)
        m = data.features.matvec(w * norm.factors)
        return m - jnp.dot(w, norm.factors * norm.shifts)

    def _rmatvec(self, data: GlmData, u: Array) -> Array:
        norm = self.normalization
        if norm is None:
            return data.features.rmatvec(u)
        g = data.features.rmatvec(u)
        return norm.factors * (g - norm.shifts * jnp.sum(u))

    def margins(self, w: Array, data: GlmData) -> Array:
        return self._matvec(data, w) + data.offsets

    # -- local (per-shard) pieces, no regularization -----------------------
    def raw_value(self, w: Array, data: GlmData) -> Array:
        m = self.margins(w, data)
        return self._wsum(data.weights, self.loss.value(m, data.labels))

    def raw_value_and_grad(self, w: Array, data: GlmData) -> tuple[Array, Array]:
        m = self.margins(w, data)
        value = self._wsum(data.weights, self.loss.value(m, data.labels))
        u = data.weights * self.loss.d1(m, data.labels)
        return value, self._rmatvec(data, u)

    def d2_weights(self, w: Array, data: GlmData) -> Array:
        """``weight ⊙ d2(m, y)`` — compute once per outer iterate and pass to
        :meth:`raw_hvp`/:meth:`hvp` so each CG step costs two matvecs, not three."""
        m = self.margins(w, data)
        return data.weights * self.loss.d2(m, data.labels)

    def raw_hvp(
        self, w: Array, v: Array, data: GlmData, d2w: Array | None = None
    ) -> Array:
        if d2w is None:
            d2w = self.d2_weights(w, data)
        dm = self._matvec(data, v)
        return self._rmatvec(data, d2w * dm)

    # -- full objective (optionally reduced over a mesh axis) --------------
    def value(
        self, w: Array, data: GlmData, l2_weight=0.0, axis_name: str | None = None
    ) -> Array:
        val = self.raw_value(w, data)
        if axis_name is not None:
            val = lax.psum(val, axis_name)
        return val + 0.5 * l2_weight * jnp.dot(w, w)

    def value_and_grad(
        self, w: Array, data: GlmData, l2_weight=0.0, axis_name: str | None = None
    ) -> tuple[Array, Array]:
        val, grad = self.raw_value_and_grad(w, data)
        if axis_name is not None:
            # The treeAggregate analogue: one fused all-reduce over ICI.
            val, grad = lax.psum((val, grad), axis_name)
        return val + 0.5 * l2_weight * jnp.dot(w, w), grad + l2_weight * w

    def hvp(
        self,
        w: Array,
        v: Array,
        data: GlmData,
        l2_weight=0.0,
        axis_name: str | None = None,
        d2w: Array | None = None,
    ) -> Array:
        h = self.raw_hvp(w, v, data, d2w)
        if axis_name is not None:
            h = lax.psum(h, axis_name)
        return h + l2_weight * v

    # -- scoring -----------------------------------------------------------
    def mean(self, w: Array, data: GlmData) -> Array:
        """Mean response (inverse link of the margin) — scoring-time output."""
        return self.loss.mean_fn(self.margins(w, data))
