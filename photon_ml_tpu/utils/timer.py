"""Wall-clock timing utility (the reference's ``Timer`` — SURVEY.md §2 Util).

Used around device computations; callers must block on results
(``jax.block_until_ready``) for the measurement to mean anything, which
:meth:`stop_blocking` does for them.
"""

from __future__ import annotations

import time


class Timer:
    def __init__(self):
        self._start: float | None = None
        self.elapsed: float = 0.0

    def start(self) -> "Timer":
        self._start = time.perf_counter()
        return self

    def stop(self) -> float:
        assert self._start is not None, "Timer not started"
        self.elapsed = time.perf_counter() - self._start
        self._start = None
        return self.elapsed

    def stop_blocking(self, *arrays) -> float:
        """Block until device arrays are ready, then stop."""
        import jax

        for a in arrays:
            jax.block_until_ready(a)
        return self.stop()

    def __enter__(self) -> "Timer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
