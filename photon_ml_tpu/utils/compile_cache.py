"""Persistent XLA compilation cache for driver runs.

The reference pays JVM+Spark startup per job but compiles nothing; this
framework's cost shape is inverted — jit compilation dominates short driver
runs (~30 s of a 38 s a1a-grid job on one v5e).  JAX's persistent
compilation cache removes that cost for every repeat invocation with the
same program shapes (λ re-grids, scoring reruns, resumed jobs), including
across processes.

Verified to work through the axon remote-compile transport: a cached
single-op program loads in ~0.2 s vs a ~2.5 s cold compile.

Opt-out rather than opt-in at the DRIVER layer (``--compile-cache off``);
library users call :func:`enable_compile_cache` themselves.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Sequence

from photon_ml_tpu import telemetry as telemetry_mod

_DEFAULT_ENV = "PHOTON_COMPILE_CACHE"

#: cache dir -> entry count at enable time (for end-of-run miss deltas).
_ENABLE_COUNTS: dict[str, int] = {}


def cache_entry_count(path: Optional[str]) -> Optional[int]:
    """Number of persisted executables in the cache dir (None when the
    dir is unreadable/absent).  JAX writes one flat file per program."""
    if not path:
        return None
    try:
        return sum(
            1 for e in os.scandir(path) if e.is_file()
        )
    except OSError:
        return None


def publish_cache_metrics(path: Optional[str]) -> Optional[int]:
    """End-of-run compile-cache attribution: entries now vs at enable
    time.  New persisted entries are programs this run compiled (cache
    MISSES at the >= min_compile_secs threshold); a run serving entirely
    from cache adds zero.  Returns the delta (None when unknown)."""
    tel = telemetry_mod.current()
    n = cache_entry_count(path)
    if n is None:
        return None
    start = _ENABLE_COUNTS.get(path)
    delta = None if start is None else max(0, n - start)
    if tel.enabled:
        tel.gauge("compile_cache_entries").set(n)
        if delta is not None:
            tel.counter("compile_cache_new_entries").inc(delta)
            tel.event(
                "compile_cache.summary", dir=path, entries=n,
                new_entries=delta,
            )
    return delta


def warmup(fns: Sequence, shapes: Sequence, logger=None) -> int:
    """Pre-compile jitted functions ahead of a latency-sensitive path.

    ``fns[i]`` is called once with zero-filled arguments materialized
    from ``shapes[i]`` — a tuple (or any pytree) of
    ``jax.ShapeDtypeStruct`` leaves (concrete arrays work too: only
    ``.shape``/``.dtype`` are read).  Calling through the normal jit
    entry populates jit's own executable cache — unlike
    ``fn.lower(...).compile()``, whose result a later direct call would
    not reuse — and routes compilations through the persistent
    compilation cache when one is enabled, so a restarted server warms
    from disk instead of recompiling.

    The serving runtime uses this at startup to compile its whole
    padded-batch bucket ladder off the request path.  Returns the number
    of NEW compilations (per-fn jit cache-size delta where the private
    ``_cache_size`` API exists, else the call count), and reports it
    through telemetry (``compile_cache_warmup_compiles`` counter,
    ``compile_cache.warmup`` event with wall seconds).
    """
    import jax
    import jax.numpy as jnp

    if len(fns) != len(shapes):
        raise ValueError(
            f"warmup needs one shape tree per fn: {len(fns)} fns, "
            f"{len(shapes)} shapes"
        )
    tel = telemetry_mod.current()
    t0 = time.perf_counter()

    def cache_size(fn) -> Optional[int]:
        try:
            return fn._cache_size()
        except Exception:
            return None

    compiles = 0
    counted = True
    for fn, args in zip(fns, shapes):
        before = cache_size(fn)
        zeros = jax.tree_util.tree_map(
            lambda leaf: jnp.zeros(leaf.shape, leaf.dtype), args
        )
        out = fn(*zeros)
        jax.block_until_ready(out)
        after = cache_size(fn)
        if before is None or after is None:
            counted = False
            compiles += 1
        else:
            compiles += max(0, after - before)
    wall = time.perf_counter() - t0
    if tel.enabled:
        tel.counter("compile_cache_warmup_compiles").inc(compiles)
        tel.gauge("compile_cache_warmup_seconds").set(round(wall, 4))
        tel.event(
            "compile_cache.warmup", fns=len(fns), compiles=compiles,
            exact=counted, seconds=wall,
        )
    if logger is not None:
        logger.info(
            "warmup: %d fn calls, %d compiles in %.2fs",
            len(fns), compiles, wall,
        )
    return compiles


def add_compile_cache_arg(parser) -> None:
    """The shared ``--compile-cache`` driver flag (one help text for all)."""
    parser.add_argument(
        "--compile-cache",
        default="auto",
        help="persistent XLA compilation-cache dir; 'auto' = "
        "$PHOTON_COMPILE_CACHE or ~/.cache/photon_ml_tpu/jax_cache, "
        "'off' disables (repeat runs recompile from scratch)",
    )


def enable_from_args(args, logger=None) -> Optional[str]:
    """Driver preamble: enable per ``args.compile_cache`` and log the dir."""
    cache_dir = enable_compile_cache(args.compile_cache)
    if cache_dir and logger is not None:
        logger.info(f"compilation cache: {cache_dir}")
    if cache_dir:
        n = cache_entry_count(cache_dir)
        if n is not None:
            _ENABLE_COUNTS[cache_dir] = n
            telemetry_mod.current().event(
                "compile_cache.enabled", dir=cache_dir, entries=n
            )
    return cache_dir


def default_cache_dir() -> str:
    """``$PHOTON_COMPILE_CACHE``, else ``~/.cache/photon_ml_tpu/jax_cache``."""
    env = os.environ.get(_DEFAULT_ENV)
    if env:
        return env
    return os.path.join(
        os.path.expanduser("~"), ".cache", "photon_ml_tpu", "jax_cache"
    )


def enable_compile_cache(
    path: Optional[str] = None, min_compile_secs: float = 0.5
) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``path`` and return it.

    ``path`` may be ``"off"`` (returns None, cache untouched) or ``"auto"``/
    None (use :func:`default_cache_dir`).  Compilations faster than
    ``min_compile_secs`` are not persisted (they'd bloat the cache for no
    win).  Failures are non-fatal: a read-only home dir degrades to an
    uncached run, never a crashed job.
    """
    if path == "off":
        # Actively disable: a previously enabled cache in this process must
        # not keep serving/persisting (bench cold-run measurement relies on
        # this when a prior in-process run enabled it).
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", None)
            try:
                from jax._src import compilation_cache as _cc

                _cc.reset_cache()
            except Exception:
                pass
        except Exception:
            pass
        return None
    if path in (None, "auto"):
        path = default_cache_dir()
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_secs
        )
        # JAX latches the file-cache handle on first use; without a reset a
        # later redirect (tests, multi-job processes) keeps writing to the
        # OLD dir.  Best-effort — the API is private and absent versions
        # just keep the latch semantics.
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass
    except Exception:
        return None
    return path
