"""Transient-failure watchdog: bounded retries around long training runs.

The reference gets elastic recovery for free from Spark's cluster manager
(failed tasks re-run on other executors — SURVEY.md §5.3).  A TPU driver
is one process talking to devices over a transport that can drop
(preemption, coordinator restart, network): the idiomatic SPMD recovery is
checkpoint + resume, which both drivers already persist per solved λ /
per CD iteration (io/checkpoint.py).  This module supplies the missing
AUTOMATIC piece: classify an exception as transient, back off, and re-run
the training closure — which reloads the checkpoint and continues where
the crashed attempt stopped, so a retry never repeats finished work.

Classification is by exception type name + message patterns rather than
imports: the concrete error type for a lost device is
``jaxlib.xla_extension.XlaRuntimeError`` with a gRPC-style status prefix
("UNAVAILABLE: Socket closed", "DEADLINE_EXCEEDED", ...), and importing
jaxlib internals just to isinstance them is brittle across versions.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, Sequence, TypeVar

T = TypeVar("T")

# gRPC-ish status markers + transport phrases that indicate the RUN may
# succeed on retry.  Deliberately NOT included: RESOURCE_EXHAUSTED /
# out-of-memory (a retry recomputes the same allocation and dies again)
# and INVALID_ARGUMENT-style programming errors.
_TRANSIENT_PATTERNS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "CANCELLED",
    "INTERNAL",
    "socket closed",
    "connection reset",
    "connection refused",
    "transport",
    "device lost",
    "heartbeat",
    "preempted",
)

# Status markers that mean a retry will deterministically fail again —
# they VETO the XlaRuntimeError type-name fallback below.
_NON_TRANSIENT_PATTERNS = (
    "RESOURCE_EXHAUSTED",
    "out of memory",
    "INVALID_ARGUMENT",
    "FAILED_PRECONDITION",
    "NOT_FOUND",
    "UNIMPLEMENTED",
)

_TRANSIENT_TYPE_NAMES = ("XlaRuntimeError",)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How a long run reacts to transient failures.

    ``max_retries=0`` disables the watchdog (failures propagate, exactly
    the pre-watchdog behavior).  Backoff is exponential:
    ``backoff_seconds * multiplier**attempt``, capped at ``max_backoff``.
    """

    max_retries: int = 0
    backoff_seconds: float = 5.0
    backoff_multiplier: float = 2.0
    max_backoff_seconds: float = 300.0
    extra_patterns: Sequence[str] = ()

    def is_transient(self, exc: BaseException) -> bool:
        msg = str(exc).lower()
        # Deterministic-failure markers veto everything, including the
        # type-name fallback: an XlaRuntimeError carrying
        # RESOURCE_EXHAUSTED re-runs the same allocation and dies again.
        if any(p.lower() in msg for p in _NON_TRANSIENT_PATTERNS):
            return False
        patterns = tuple(_TRANSIENT_PATTERNS) + tuple(
            p.lower() for p in self.extra_patterns
        )
        if any(p.lower() in msg for p in patterns):
            return True
        return type(exc).__name__ in _TRANSIENT_TYPE_NAMES

    def backoff(self, attempt: int) -> float:
        return min(
            self.backoff_seconds * self.backoff_multiplier**attempt,
            self.max_backoff_seconds,
        )


def run_with_retries(
    fn: Callable[[int], T],
    policy: RetryPolicy,
    logger=None,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Run ``fn(attempt)`` until it returns, retrying transient failures.

    ``fn`` receives the attempt number (0 = first try) and MUST re-read
    its checkpoint state each call — that is what makes a retry resume
    instead of restart (the drivers' closures reload the grid / CD
    checkpointers).  Non-transient exceptions and exhausted budgets
    propagate unchanged.
    """
    attempt = 0
    while True:
        try:
            return fn(attempt)
        except Exception as exc:  # noqa: BLE001 — classified below
            if attempt >= policy.max_retries or not policy.is_transient(exc):
                raise
            delay = policy.backoff(attempt)
            if logger is not None:
                logger.warning(
                    "transient failure (attempt %d/%d), retrying in %.1fs: "
                    "%s: %s",
                    attempt + 1, policy.max_retries, delay,
                    type(exc).__name__, exc,
                )
            sleep(delay)
            attempt += 1
