"""Transient-failure watchdog: bounded retries around long training runs.

The reference gets elastic recovery for free from Spark's cluster manager
(failed tasks re-run on other executors — SURVEY.md §5.3).  A TPU driver
is one process talking to devices over a transport that can drop
(preemption, coordinator restart, network): the idiomatic SPMD recovery is
checkpoint + resume, which both drivers already persist per solved λ /
per CD iteration (io/checkpoint.py).  This module supplies the missing
AUTOMATIC piece: classify an exception as transient, back off, and re-run
the training closure — which reloads the checkpoint and continues where
the crashed attempt stopped, so a retry never repeats finished work.

Classification is by exception type name + message patterns rather than
imports: the concrete error type for a lost device is
``jaxlib.xla_extension.XlaRuntimeError`` with a gRPC-style status prefix
("UNAVAILABLE: Socket closed", "DEADLINE_EXCEEDED", ...), and importing
jaxlib internals just to isinstance them is brittle across versions.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional, Sequence, TypeVar

from photon_ml_tpu import telemetry as telemetry_mod

T = TypeVar("T")

# gRPC-ish status markers + transport phrases that indicate the RUN may
# succeed on retry.  Deliberately NOT included: RESOURCE_EXHAUSTED /
# out-of-memory (a retry recomputes the same allocation and dies again)
# and INVALID_ARGUMENT-style programming errors.
_TRANSIENT_PATTERNS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "CANCELLED",
    "INTERNAL",
    "socket closed",
    "connection reset",
    "connection refused",
    "transport",
    "device lost",
    "heartbeat",
    "preempted",
)

# Status markers that mean a retry will deterministically fail again —
# they VETO the XlaRuntimeError type-name fallback below.
_NON_TRANSIENT_PATTERNS = (
    "RESOURCE_EXHAUSTED",
    "out of memory",
    "INVALID_ARGUMENT",
    "FAILED_PRECONDITION",
    "NOT_FOUND",
    "UNIMPLEMENTED",
)

_TRANSIENT_TYPE_NAMES = ("XlaRuntimeError",)


@dataclasses.dataclass(frozen=True)
class Classification:
    """Why an exception was (or wasn't) judged transient: the verdict plus
    the pattern/type-name that decided it — what the watchdog logs and
    emits as a telemetry event per attempt."""

    transient: bool
    #: the matched message pattern or type name, None when nothing matched
    matched: Optional[str] = None
    #: "non_transient_pattern" | "transient_pattern" | "type_name" | "none"
    source: str = "none"


@dataclasses.dataclass
class RetryStats:
    """Observable retry behavior of one :func:`run_with_retries` call.

    Tests assert on this instead of timing sleeps; drivers surface it in
    their result JSON.  ``failures`` holds one dict per caught exception
    (attempt, exception type, message head, verdict, matched pattern,
    backoff seconds — backoff is None when the failure propagated)."""

    attempts: int = 0  # fn invocations started
    retries: int = 0  # sleeps taken (= transient failures retried)
    sleep_seconds: float = 0.0  # total backoff requested
    succeeded: bool = False
    gave_up: bool = False  # budget exhausted on a transient failure
    failures: list = dataclasses.field(default_factory=list)

    def snapshot(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How a long run reacts to transient failures.

    ``max_retries=0`` disables the watchdog (failures propagate, exactly
    the pre-watchdog behavior).  Backoff is exponential:
    ``backoff_seconds * multiplier**attempt``, capped at ``max_backoff``.
    """

    max_retries: int = 0
    backoff_seconds: float = 5.0
    backoff_multiplier: float = 2.0
    max_backoff_seconds: float = 300.0
    extra_patterns: Sequence[str] = ()
    #: "none" = the deterministic exponential above; "decorrelated" =
    #: AWS-style decorrelated jitter (sleep ~ U[base, 3·previous sleep],
    #: capped).  Parallel clients sharing one backoff schedule retry in
    #: lockstep and re-overload whatever just failed (the thundering
    #: herd — exactly the tuning orchestrator's W parallel trials after
    #: a coordinator blip); jitter decorrelates them.  The RNG is
    #: injected at run_with_retries (tests pass a seeded random.Random).
    jitter: str = "none"

    def __post_init__(self):
        if self.jitter not in ("none", "decorrelated"):
            raise ValueError(
                f"jitter must be 'none' or 'decorrelated', got "
                f"{self.jitter!r}"
            )

    def classify(self, exc: BaseException) -> Classification:
        """Verdict + the pattern that decided it (see Classification)."""
        if isinstance(exc, (KeyboardInterrupt, SystemExit)):
            # A user interrupt / deliberate exit is NEVER retryable, no
            # matter what its message says (SystemExit("UNAVAILABLE: ..")
            # from a CLI guard must not put the process back to work).
            return Classification(False, type(exc).__name__, "interrupt")
        msg = str(exc).lower()
        # Deterministic-failure markers veto everything, including the
        # type-name fallback: an XlaRuntimeError carrying
        # RESOURCE_EXHAUSTED re-runs the same allocation and dies again.
        for p in _NON_TRANSIENT_PATTERNS:
            if p.lower() in msg:
                return Classification(False, p, "non_transient_pattern")
        patterns = tuple(_TRANSIENT_PATTERNS) + tuple(self.extra_patterns)
        for p in patterns:
            if p.lower() in msg:
                return Classification(True, p, "transient_pattern")
        name = type(exc).__name__
        if name in _TRANSIENT_TYPE_NAMES:
            return Classification(True, name, "type_name")
        return Classification(False)

    def is_transient(self, exc: BaseException) -> bool:
        return self.classify(exc).transient

    def backoff(
        self,
        attempt: int,
        rng: Optional[random.Random] = None,
        previous: Optional[float] = None,
    ) -> float:
        """Seconds to sleep before retrying after failure ``attempt``.

        With ``jitter="none"`` (or no RNG supplied): the deterministic
        capped exponential.  With ``jitter="decorrelated"`` and an RNG:
        ``min(cap, U[base, 3·previous])`` where ``previous`` is the last
        delay actually slept (``base`` on the first retry) — each
        client's schedule random-walks away from its peers' instead of
        colliding at base·2^k.
        """
        if self.jitter == "decorrelated" and rng is not None:
            prev = self.backoff_seconds if previous is None else previous
            hi = max(self.backoff_seconds, 3.0 * prev)
            return min(
                self.max_backoff_seconds,
                rng.uniform(self.backoff_seconds, hi),
            )
        return min(
            self.backoff_seconds * self.backoff_multiplier**attempt,
            self.max_backoff_seconds,
        )


def run_with_retries(
    fn: Callable[[int], T],
    policy: RetryPolicy,
    logger=None,
    sleep: Callable[[float], None] = time.sleep,
    stats: Optional[RetryStats] = None,
    rng: Optional[random.Random] = None,
) -> T:
    """Run ``fn(attempt)`` until it returns, retrying transient failures.

    ``fn`` receives the attempt number (0 = first try) and MUST re-read
    its checkpoint state each call — that is what makes a retry resume
    instead of restart (the drivers' closures reload the grid / CD
    checkpointers).  Non-transient exceptions and exhausted budgets
    propagate unchanged.

    ``stats`` (a RetryStats, mutated in place) records every attempt's
    classification and backoff — tests assert on it instead of timing
    sleeps.  Each classify/backoff/give-up decision is also emitted as a
    ``watchdog.attempt`` telemetry event and counted on the
    ``watchdog_retries`` metric.

    ``rng`` drives decorrelated-jitter backoff when the policy enables
    it (``jitter="decorrelated"``); pass a seeded ``random.Random`` for
    deterministic tests.  Omitted with jitter enabled, a fresh RNG is
    created — production callers get real decorrelation by default.
    Note ``KeyboardInterrupt``/``SystemExit`` are BaseExceptions: they
    propagate without ever reaching classification, and ``classify``
    refuses them explicitly for callers that classify on their own.
    """
    tel = telemetry_mod.current()
    if stats is None:
        stats = RetryStats()
    if rng is None and policy.jitter != "none":
        rng = random.Random()
    attempt = 0
    prev_delay: Optional[float] = None
    while True:
        stats.attempts += 1
        try:
            result = fn(attempt)
        except Exception as exc:  # noqa: BLE001 — classified below
            verdict = policy.classify(exc)
            retrying = verdict.transient and attempt < policy.max_retries
            delay = (
                policy.backoff(attempt, rng=rng, previous=prev_delay)
                if retrying else None
            )
            stats.gave_up = verdict.transient and not retrying
            stats.failures.append({
                "attempt": attempt,
                "exception": type(exc).__name__,
                "message": str(exc)[:200],
                "transient": verdict.transient,
                "matched": verdict.matched,
                "source": verdict.source,
                "backoff_seconds": delay,
            })
            tel.event(
                "watchdog.attempt",
                attempt=attempt,
                outcome=(
                    "retry" if retrying
                    else "gave_up" if verdict.transient
                    else "non_transient"
                ),
                exception=type(exc).__name__,
                matched=verdict.matched,
                source=verdict.source,
                backoff_seconds=delay,
            )
            if not retrying:
                # Watchdog-fatal: the run is about to die for good —
                # freeze the event window (telemetry/recorder.py; no-op
                # without a recorder-equipped hub).
                telemetry_mod.dump_flight_recorder(
                    reason=(
                        "watchdog-fatal: "
                        f"{type(exc).__name__}: {exc}"
                    )[:300]
                )
                raise
            stats.retries += 1
            stats.sleep_seconds += delay
            prev_delay = delay
            tel.counter("watchdog_retries").inc()
            if logger is not None:
                logger.warning(
                    "transient failure (attempt %d/%d), retrying in %.1fs: "
                    "%s: %s",
                    attempt + 1, policy.max_retries, delay,
                    type(exc).__name__, exc,
                )
            sleep(delay)
            attempt += 1
        else:
            stats.succeeded = True
            if stats.retries or stats.failures:
                tel.event(
                    "watchdog.recovered",
                    attempts=stats.attempts, retries=stats.retries,
                )
            return result
