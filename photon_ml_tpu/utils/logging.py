"""Logging that also lands in the job's output directory.

The analogue of the reference's ``PhotonLogger`` (SURVEY.md §2 Util, §5.5):
a log4j-backed logger duplicated to an HDFS file so the training log ships
with the model artifacts.  Here: stdlib logging duplicated to a file in the
driver's output dir.
"""

from __future__ import annotations

import itertools
import logging
import os
import sys

# Unique per-instance logger suffix.  ``id(self)`` (the previous scheme)
# is only unique among LIVE objects — the allocator reuses addresses, so
# a long process running many drivers could hand a new PhotonLogger a
# dead instance's logging.Logger, inheriting its closed handlers.
_INSTANCE_IDS = itertools.count()


class PhotonLogger:
    """Console + file logger; the file lives next to the job's outputs.

    Each instance registers a uniquely named stdlib logger and OWNS its
    handlers; :meth:`close` detaches and closes them (and drops the
    logger from the process registry), so repeated driver invocations in
    one process — tests, hyperparameter search — don't leak file handles
    or logger entries.  Usable as a context manager::

        with PhotonLogger(output_dir) as logger:
            logger.info("...")
    """

    def __init__(self, output_dir: str | None = None, name: str = "photon_ml_tpu"):
        self._name = f"{name}.{next(_INSTANCE_IDS)}"
        self._logger = logging.getLogger(self._name)
        self._logger.setLevel(logging.INFO)
        self._logger.propagate = False
        self._closed = False
        fmt = logging.Formatter(
            "%(asctime)s %(levelname)s %(message)s", "%Y-%m-%d %H:%M:%S"
        )
        console = logging.StreamHandler(sys.stderr)
        console.setFormatter(fmt)
        self._logger.addHandler(console)
        self._file_handler = None
        if output_dir is not None:
            os.makedirs(output_dir, exist_ok=True)
            self._file_handler = logging.FileHandler(
                os.path.join(output_dir, "photon.log")
            )
            self._file_handler.setFormatter(fmt)
            self._logger.addHandler(self._file_handler)

    def info(self, msg: str, *args) -> None:
        self._logger.info(msg, *args)

    def warning(self, msg: str, *args) -> None:
        self._logger.warning(msg, *args)

    def error(self, msg: str, *args) -> None:
        self._logger.error(msg, *args)

    def debug(self, msg: str, *args) -> None:
        self._logger.debug(msg, *args)

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        """Detach + close every handler and unregister the logger.
        Idempotent; a closed logger's methods are safe no-ops at the
        stdlib level (no handlers, propagate off)."""
        if self._closed:
            return
        self._closed = True
        for h in list(self._logger.handlers):
            self._logger.removeHandler(h)
            h.close()
        self._file_handler = None
        # Drop the entry from logging's process-global registry so the
        # Manager dict doesn't grow one dead Logger per driver run.
        registry = logging.Logger.manager.loggerDict
        registry.pop(self._name, None)

    def __enter__(self) -> "PhotonLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
