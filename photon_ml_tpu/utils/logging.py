"""Logging that also lands in the job's output directory.

The analogue of the reference's ``PhotonLogger`` (SURVEY.md §2 Util, §5.5):
a log4j-backed logger duplicated to an HDFS file so the training log ships
with the model artifacts.  Here: stdlib logging duplicated to a file in the
driver's output dir.
"""

from __future__ import annotations

import logging
import os
import sys


class PhotonLogger:
    """Console + file logger; the file lives next to the job's outputs."""

    def __init__(self, output_dir: str | None = None, name: str = "photon_ml_tpu"):
        self._logger = logging.getLogger(f"{name}.{id(self):x}")
        self._logger.setLevel(logging.INFO)
        self._logger.propagate = False
        fmt = logging.Formatter(
            "%(asctime)s %(levelname)s %(message)s", "%Y-%m-%d %H:%M:%S"
        )
        console = logging.StreamHandler(sys.stderr)
        console.setFormatter(fmt)
        self._logger.addHandler(console)
        self._file_handler = None
        if output_dir is not None:
            os.makedirs(output_dir, exist_ok=True)
            self._file_handler = logging.FileHandler(
                os.path.join(output_dir, "photon.log")
            )
            self._file_handler.setFormatter(fmt)
            self._logger.addHandler(self._file_handler)

    def info(self, msg: str, *args) -> None:
        self._logger.info(msg, *args)

    def warning(self, msg: str, *args) -> None:
        self._logger.warning(msg, *args)

    def error(self, msg: str, *args) -> None:
        self._logger.error(msg, *args)

    def debug(self, msg: str, *args) -> None:
        self._logger.debug(msg, *args)

    def close(self) -> None:
        for h in list(self._logger.handlers):
            h.close()
            self._logger.removeHandler(h)
