from photon_ml_tpu.utils.logging import PhotonLogger  # noqa: F401
from photon_ml_tpu.utils.timer import Timer  # noqa: F401
from photon_ml_tpu.utils.tracker import OptimizationStatesTracker  # noqa: F401
