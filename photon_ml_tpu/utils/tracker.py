"""Convergence-trace bookkeeping.

The analogue of the reference's ``OptimizationStatesTracker`` (SURVEY.md §5.1):
per-iteration objective value and gradient norm for each optimizer run.  The
on-device side already records these into the fixed-size nan-padded arrays of
``SolveResult`` (optim/lbfgs.py); this host-side class turns them into the
human-readable trace the reference logs, plus wall-clock attribution the
device can't know.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class OptimizationStatesTracker:
    values: np.ndarray  # (iterations+1,)
    grad_norms: np.ndarray  # (iterations+1,)
    iterations: int
    converged: bool
    wall_seconds: float = float("nan")

    @staticmethod
    def from_solve_result(res, wall_seconds: float = float("nan")):
        values = np.asarray(res.values, np.float64)
        keep = ~np.isnan(values)
        return OptimizationStatesTracker(
            values=values[keep],
            grad_norms=np.asarray(res.grad_norms, np.float64)[keep],
            iterations=int(res.iterations),
            converged=bool(res.converged),
            wall_seconds=wall_seconds,
        )

    def summary(self) -> str:
        lines = [
            f"iter {i:4d}: value={v:.8g} |grad|={g:.4g}"
            for i, (v, g) in enumerate(zip(self.values, self.grad_norms))
        ]
        status = "converged" if self.converged else "NOT converged"
        lines.append(
            f"{status} after {self.iterations} iterations"
            + (
                f" in {self.wall_seconds:.3f}s"
                if not np.isnan(self.wall_seconds)
                else ""
            )
        )
        return "\n".join(lines)
