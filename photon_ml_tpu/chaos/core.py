"""Deterministic fault injection: named sites, scripted plans.

The reference inherits its fault-tolerance STORY from Spark (task retry,
lineage re-execution — SURVEY.md §5.3) and its fault-tolerance PROOF from
running on clusters where machines actually die.  A single-process TPU
driver has neither: recovery here is checkpoint + resume through
``utils/watchdog.py``, and until this module existed nothing in the repo
ever killed a run mid-flight — the recovery story was asserted, not
verified.

This module is the verification substrate: a seeded, deterministic
fault-injection layer with NAMED sites wired through the hot seams
(prefetch pack/transfer threads, staged h2d puts, the streamed carry
sync, checkpoint save/restore, CD iteration boundaries, grid-point
boundaries, the serving device path, tuning trials).  A
:class:`FaultPlan` — JSON-scriptable, so crash schedules live in test
files and CI recipes — names a site, an occurrence index, and what to
inject (an exception from a small registry, or a delay), and the plan
replays EXACTLY: occurrence counters are plan-local and thread-safe, so
the same plan against the same workload kills at the same boundary
every time.

Cost contract (mirrors the telemetry hub): with no plan installed,
every instrumented seam pays ONE module-global read + one branch
(:func:`maybe_fail`).  ``bench.py``'s ``BENCH_ONLY=chaos`` section
measures that disabled path against the streamed pass wall and gates it
at ≤ 1%.

Usage::

    from photon_ml_tpu import chaos

    plan = chaos.FaultPlan([
        chaos.FaultSpec(site="grid.point", at=1,
                        message="UNAVAILABLE: injected preemption"),
    ])
    with plan:
        ...  # the second grid-point boundary raises InjectedFault

    plan.fired  # -> [{"site": "grid.point", "occurrence": 1, ...}]

Exception messages default to watchdog-transient vocabulary
("UNAVAILABLE: ..."), so an injected fault exercises the SAME
classify/backoff/resume machinery a real lost device would.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Optional, Sequence

from photon_ml_tpu import telemetry as telemetry_mod


class InjectedFault(RuntimeError):
    """An exception raised on purpose by an installed :class:`FaultPlan`.

    Default messages carry watchdog-transient markers so the injected
    fault rides the real recovery path; a plan can override the message
    to exercise the non-transient vocabulary instead."""


class InjectedDeviceLost(InjectedFault):
    """A chaos stand-in for the runtime losing its accelerator (the
    XlaRuntimeError("UNAVAILABLE: ...") family) — what the serving
    degraded-mode path and the training watchdog both classify as
    transient."""


#: Exception types a FaultSpec may name.  Deliberately small: injected
#: faults should either speak the watchdog vocabulary (InjectedFault /
#: InjectedDeviceLost with a gRPC-ish message) or be a plain stdlib type
#: a seam's own error handling already knows.
EXCEPTIONS = {
    "InjectedFault": InjectedFault,
    "InjectedDeviceLost": InjectedDeviceLost,
    "RuntimeError": RuntimeError,
    "OSError": OSError,
    "TimeoutError": TimeoutError,
    "ValueError": ValueError,
}


#: The fault-site catalog: every name ``maybe_fail`` is called with, and
#: what a fault there simulates.  Plans naming an unknown site are
#: refused at construction (a typo'd site would silently never fire and
#: the test would "pass" without killing anything).  docs/robustness.md
#: renders this table.
KNOWN_SITES = {
    "prefetch.pack": (
        "pack thread, before get_item(k): host materialization dies "
        "mid-stream (data/prefetch.py)"
    ),
    "prefetch.transfer": (
        "transfer thread, before put(item): the h2d dispatch path dies "
        "mid-stream (data/prefetch.py)"
    ),
    "staging.put": (
        "the staged device_put of one chunk's coalesced buffers "
        "(optim/streaming.py _put, on the transfer thread)"
    ),
    "streaming.carry_sync": (
        "consumer thread, before dispatching chunk k's program into the "
        "carry window (optim/streaming.py _stream_accumulate)"
    ),
    "staging.decode": (
        "consumer thread, before dispatching the in-program dequant "
        "step for a COMPRESSED item — only fires when a chunk codec is "
        "active (optim/streaming.py _stream_accumulate)"
    ),
    "streaming.cache_evict": (
        "the working-set cache's admission/eviction replan at pass end "
        "(optim/streaming.py HotChunkCache.replan) — the cache clears "
        "itself before the fault propagates, so the next pass streams "
        "everything and stays bitwise clean"
    ),
    "checkpoint.save": (
        "after the checkpoint tmp file is written+fsynced, BEFORE the "
        "atomic rename publishes it (io/checkpoint.py) — a kill here "
        "must leave the previous checkpoint intact"
    ),
    "checkpoint.restore": (
        "at restore entry, before the checkpoint file is opened "
        "(io/checkpoint.py)"
    ),
    "cd.iteration": (
        "GAME coordinate-descent iteration boundary, after that "
        "iteration's checkpoint save (game/descent.py)"
    ),
    "game.repack": (
        "cost-model entity repacker, before the bucket plan is built "
        "(game/data.py build_random_effect_dataset) — a kill here dies "
        "before any block exists; the rebuilt dataset must be bitwise "
        "identical to an uninterrupted build"
    ),
    "game.bucket_shard": (
        "hierarchical random-effect execution, before one device "
        "placement's bucket programs dispatch (game/hierarchical.py) — "
        "a kill here aborts the coordinate update mid-dispatch; the "
        "retried update must be bitwise identical to an uninterrupted "
        "one (per-bucket solves are pure functions of offsets)"
    ),
    "grid.point": (
        "λ-grid point boundary, after on_solved persisted the point "
        "(optim/problem.py grid_loop)"
    ),
    "serving.batch": (
        "batcher dispatch, before the runtime scores a batch "
        "(serving/batcher.py)"
    ),
    "serving.device": (
        "the device scoring kernel call (serving/runtime.py) — a fault "
        "here simulates a lost accelerator and must flip the runtime "
        "into degraded host-side scoring"
    ),
    "serving.replica": (
        "supervisor routing, before a request is handed to the chosen "
        "replica (serving/supervisor.py) — a fault here simulates that "
        "replica crashing; the supervisor must mark it down and "
        "re-route/resubmit with zero failed requests"
    ),
    "serving.worker": (
        "process-pool routing, before a request is framed to the chosen "
        "worker process (serving/procpool.py) — a fault here SIGKILLs "
        "the routed worker for real before raising, so the scripted "
        "crash exercises the actual death-mid-batch path: pipe EOF, "
        "transient failure of in-flight rows, supervisor resubmission "
        "with zero failed requests, jittered respawn"
    ),
    "serving.swap": (
        "model hot-swap critical section (serving/swap.py): touched at "
        "stage 'load' (before the background load), 'prepare' (loaded+"
        "warmed, before the atomic commit) and 'verify' (committed, "
        "before the post-swap probe) — a fault must abort or roll back "
        "with the previous version still serving"
    ),
    "tuning.trial": (
        "worker thread, before a tuning trial's fit runs "
        "(tuning/executor.py)"
    ),
    "publish.delta": (
        "delta publication boundaries (freshness/publisher.py): stage "
        "'journal' (begin record written, before the artifact staging "
        "dir), 'artifact' (artifact staged+digested, before the atomic "
        "rename publishes it) and 'commit' (artifact published, before "
        "the commit record) — a crash at any stage must resume exactly, "
        "never leaving a half-published artifact visible"
    ),
    "publish.apply": (
        "delta hot-apply critical section (serving/swap.py swap_delta): "
        "touched at stage 'load' (before the artifact is read+verified), "
        "'prepare' (patched runtime built, before the atomic commit) and "
        "'verify' (committed, before the post-apply probe) — a fault "
        "must roll back with the previous version still serving"
    ),
    "online.step": (
        "online refinement, before one entity's SGD/AdaGrad step "
        "(freshness/online.py) — a fault must abandon the refinement "
        "pass without corrupting the warm-start model or publishing a "
        "partial delta"
    ),
    "serving.tenant": (
        "dispatch thread, before a tenant-routed group scores against "
        "its tenant-scoped runtime (serving/batcher.py _dispatch; ctx: "
        "tenant, rows) — only fires for tenants with a committed "
        "tenant route, so a fault degrades exactly one tenant: its "
        "breaker opens and its traffic sheds while every other "
        "tenant's requests keep completing"
    ),
    "serving.host": (
        "fleet-router routing seam, after a host is picked but before "
        "the request goes over the wire (serving/fleet.py _route; ctx: "
        "host) — a fault is a HOST dying as it picks up the request: "
        "the router must mark the host DOWN, resubmit to a peer, and "
        "the client future must still resolve (zero failed requests, "
        "the host_kill scenario's gate)"
    ),
    "quota.lease": (
        "fleet lease renewal, before the LeaseClient reaches the "
        "QuotaCoordinator (serving/fleet.py poll_once; ctx: host) — a "
        "fault is a network partition from the coordinator: the host "
        "must degrade to its LAST granted lease (never unlimited, "
        "never zero), bounding fleet over-admission to one lease "
        "window (the quota_partition scenario's gate)"
    ),
    "telemetry.scrape": (
        "fleet aggregator scrape, before one host's /snapshot fetch "
        "(telemetry/fleet.py _scrape_host; ctx: host) — a fault is the "
        "host dropping off the network mid-scrape: the aggregator must "
        "degrade to the host's last-seen snapshot (counted in "
        "fleet_scrape_failures_total, aged by the staleness gauge) and "
        "keep folding every other host — the loop never wedges"
    ),
    "distributed.allreduce": (
        "a distributed solver's outer-iteration reduce seam, before the "
        "round's step program (and its all-reduce) dispatches "
        "(solvers/admm.py, solvers/block_cd.py; ctx: solver, outer) — a "
        "fault is a host dying at the collective: the watchdog re-enters "
        "the grid, the checkpoint warm-start chain replays the in-flight "
        "λ deterministically, and the resumed sweep is bitwise identical"
    ),
    "admm.consensus": (
        "consensus-ADMM z-update boundary, after outer iteration k's "
        "consensus variable (and adapted ρ) is computed "
        "(solvers/admm.py; ctx: solver, outer, rho) — a kill here lands "
        "between outer iterations; resume must replay the λ point to "
        "the SAME consensus trajectory (bitwise, the ISSUE 18 gate)"
    ),
    "cluster.lease": (
        "replicated-coordinator renewal, before a replica is attempted "
        "(cluster/coordination.py ReplicatedQuotaCoordinator.renew; "
        "ctx: host, replica) — a fault is the wire to THAT replica "
        "dying: the walk must move on to the next replica, and only an "
        "all-replica failure surfaces to the LeaseClient, which then "
        "degrades to its LAST lease (never unlimited, never zero)"
    ),
    "cluster.heartbeat": (
        "membership heartbeat, before the agent reaches the registry "
        "(cluster/membership.py HeartbeatAgent.beat_once; ctx: host) — "
        "a fault is the host partitioned from the registry: the beat "
        "fails (cluster_heartbeat_failures_total), the loop keeps "
        "trying, and a partition longer than the heartbeat TTL expires "
        "the host from membership until it re-registers"
    ),
    "cluster.fetch": (
        "publication blob fetch, before one file's HTTP GET "
        "(cluster/distribution.py PublicationClient._get_blob; ctx: "
        "seq, file) — a fault is the wire dying mid-distribution: the "
        "client retries (cluster_fetch_retries), an exhausted retry "
        "budget raises FetchError, and NOTHING half-fetched is ever "
        "visible at the final path (staging dir + atomic rename)"
    ),
}


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scripted fault: fire at site ``site`` on occurrence ``at``
    (0-based, counted per plan per site), for ``count`` consecutive
    occurrences (-1 = every occurrence from ``at`` on).

    ``action`` is ``"raise"`` (build ``exception`` with ``message``) or
    ``"delay"`` (sleep ``delay_seconds`` then continue — for deadline /
    stall scenarios).  The default message speaks the watchdog's
    transient vocabulary and names the site, so logs and RetryStats say
    exactly which scripted fault fired.
    """

    site: str
    at: int = 0
    count: int = 1
    action: str = "raise"  # "raise" | "delay"
    exception: str = "InjectedFault"
    message: Optional[str] = None
    delay_seconds: float = 0.0

    def __post_init__(self):
        if self.site not in KNOWN_SITES:
            raise ValueError(
                f"unknown fault site {self.site!r}; known sites: "
                f"{sorted(KNOWN_SITES)}"
            )
        if self.action not in ("raise", "delay"):
            raise ValueError(
                f"action must be 'raise' or 'delay', got {self.action!r}"
            )
        if self.exception not in EXCEPTIONS:
            raise ValueError(
                f"unknown exception {self.exception!r}; registry: "
                f"{sorted(EXCEPTIONS)}"
            )
        if self.at < 0:
            raise ValueError(f"at must be >= 0, got {self.at}")
        if self.count < -1 or self.count == 0:
            raise ValueError(
                f"count must be positive or -1 (forever), got {self.count}"
            )

    def matches(self, occurrence: int) -> bool:
        if occurrence < self.at:
            return False
        if self.count == -1:
            return True
        return occurrence < self.at + self.count

    def build_exception(self, occurrence: int) -> BaseException:
        msg = self.message
        if msg is None:
            msg = (
                f"UNAVAILABLE: chaos-injected fault at site "
                f"{self.site!r} (occurrence {occurrence})"
            )
        return EXCEPTIONS[self.exception](msg)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "FaultSpec":
        return cls(**d)


class FaultPlan:
    """A repeatable crash schedule: scripted faults + per-site occurrence
    counters + a log of what actually fired.

    Install with :meth:`install` / :meth:`uninstall` or as a context
    manager; only one plan may be installed at a time (two concurrent
    plans would race each other's occurrence counters and neither
    schedule would be deterministic).  Counters persist across
    uninstall/reinstall of the SAME plan object — that is what lets a
    kill/resume scenario arm "occurrence 1" once and have the resumed
    run sail past it.
    """

    def __init__(self, faults: Sequence[FaultSpec] = ()):
        self.faults = list(faults)
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        #: what fired, in order: {"site", "occurrence", "action", ...}
        self.fired: list[dict] = []

    # -- (de)serialization --------------------------------------------------
    def to_json(self) -> str:
        return json.dumps([f.to_dict() for f in self.faults], indent=2)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        specs = json.loads(text)
        if not isinstance(specs, list):
            raise ValueError("a fault plan is a JSON list of fault specs")
        return cls([FaultSpec.from_dict(d) for d in specs])

    # -- installation -------------------------------------------------------
    def install(self) -> "FaultPlan":
        global _PLAN
        with _INSTALL_LOCK:
            if _PLAN is not None and _PLAN is not self:
                raise RuntimeError(
                    "another FaultPlan is already installed; uninstall it "
                    "first (concurrent plans would race occurrence "
                    "counters)"
                )
            _PLAN = self
        return self

    def uninstall(self) -> None:
        global _PLAN
        with _INSTALL_LOCK:
            if _PLAN is self:
                _PLAN = None

    def __enter__(self) -> "FaultPlan":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False

    # -- observation --------------------------------------------------------
    def occurrences(self, site: str) -> int:
        """How many times ``site`` has been reached under this plan."""
        with self._lock:
            return self._counts.get(site, 0)

    def fired_at(self, site: str) -> list[dict]:
        with self._lock:
            return [f for f in self.fired if f["site"] == site]

    # -- the hot path (called via maybe_fail) --------------------------------
    def _hit(self, site: str, ctx: dict) -> None:
        with self._lock:
            occurrence = self._counts.get(site, 0)
            self._counts[site] = occurrence + 1
            spec = next(
                (f for f in self.faults
                 if f.site == site and f.matches(occurrence)),
                None,
            )
            if spec is None:
                return
            record = {
                "site": site,
                "occurrence": occurrence,
                "action": spec.action,
                **{k: telemetry_mod.json_safe(v) for k, v in ctx.items()},
            }
            self.fired.append(record)
        tel = telemetry_mod.current()
        tel.counter("chaos_faults_injected").inc()
        tel.event("chaos.fault", **record)
        if spec.action == "delay":
            time.sleep(spec.delay_seconds)
            return
        # Forensics before the kill: the flight-recorder ring is dumped
        # with the chaos.fault record just emitted as its LAST event, so
        # every fault-injection test doubles as a forensics test
        # (telemetry/recorder.py).  The event window at the moment of
        # injection is exactly what a real crash would have left behind.
        telemetry_mod.dump_flight_recorder(
            reason=f"chaos:{site}@{occurrence}"
        )
        raise spec.build_exception(occurrence)


_INSTALL_LOCK = threading.Lock()
_PLAN: Optional[FaultPlan] = None


def current_plan() -> Optional[FaultPlan]:
    """The installed plan, or None (the default, zero-cost state)."""
    return _PLAN


def maybe_fail(site: str, **ctx) -> None:
    """The instrumented seams' hook: a no-op unless a plan is installed.

    Disabled path = one global read + one branch (the whole cost
    contract); with a plan installed, the plan counts the occurrence
    and fires any matching scripted fault (raise or delay).  ``ctx``
    (chunk index, λ, trial id, ...) rides the injection log and the
    ``chaos.fault`` telemetry event — it is only touched when a fault
    actually fires.
    """
    plan = _PLAN
    if plan is None:
        return
    plan._hit(site, ctx)
