"""Deterministic fault injection + recovery verification (see core.py).

Public surface::

    chaos.maybe_fail(site, **ctx)   # the instrumented seams' hook
    chaos.FaultPlan / chaos.FaultSpec
    chaos.InjectedFault / chaos.InjectedDeviceLost
    chaos.CircuitBreaker            # closed -> open -> half-open probe
    chaos.KNOWN_SITES               # the fault-site catalog

``python -m photon_ml_tpu.chaos --selfcheck`` runs the scripted
kill/resume/degrade scenario end-to-end (docs/robustness.md).
"""

from photon_ml_tpu.chaos.breaker import (  # noqa: F401
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
)
from photon_ml_tpu.chaos.core import (  # noqa: F401
    EXCEPTIONS,
    KNOWN_SITES,
    FaultPlan,
    FaultSpec,
    InjectedDeviceLost,
    InjectedFault,
    current_plan,
    maybe_fail,
)

__all__ = [
    "CLOSED",
    "HALF_OPEN",
    "OPEN",
    "CircuitBreaker",
    "EXCEPTIONS",
    "KNOWN_SITES",
    "FaultPlan",
    "FaultSpec",
    "InjectedDeviceLost",
    "InjectedFault",
    "current_plan",
    "maybe_fail",
]
