"""Circuit breaker: closed → open → half-open probe, injectable clock.

The degraded-mode serving path (serving/runtime.py) must not hammer a
dead device with every batch — a lost accelerator takes seconds-to-
minutes to come back, and each failed probe costs a dispatch timeout on
the request path.  The classic fix is the circuit breaker: after
``failure_threshold`` consecutive failures the circuit OPENS (all
traffic takes the fallback path, the protected call is not attempted at
all); after ``cooldown_seconds`` it goes HALF-OPEN and admits one probe;
a successful probe CLOSES it (re-promotion), a failed probe re-opens it
and restarts the cooldown.

The clock is injectable (``clock=time.monotonic`` by default) so tests
drive the state machine deterministically without sleeping — the same
discipline as the watchdog's injectable ``sleep``.

Single-writer by design: the serving dispatch thread owns all scoring,
so state transitions need no lock; ``snapshot()`` reads are racy-but-
consistent-enough for /stats (plain attribute reads of small values).
"""

from __future__ import annotations

import time
from typing import Callable

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-site breaker guarding an unreliable call's re-promotion."""

    def __init__(
        self,
        cooldown_seconds: float = 5.0,
        failure_threshold: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ):
        if cooldown_seconds < 0:
            raise ValueError(
                f"cooldown_seconds must be >= 0, got {cooldown_seconds}"
            )
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        self.cooldown_seconds = float(cooldown_seconds)
        self.failure_threshold = int(failure_threshold)
        self._clock = clock
        self.state = CLOSED
        self._consecutive_failures = 0
        self._opened_at: float | None = None
        # lifetime counters (stats/telemetry mirrors)
        self.failures = 0
        self.opens = 0
        self.probes = 0
        self.reclosures = 0

    # -- protected-call gating ----------------------------------------------
    def allow_request(self) -> bool:
        """May the protected call be attempted right now?

        CLOSED: always.  OPEN: only once the cooldown has elapsed — and
        that admission IS the transition to HALF_OPEN (the single
        probe).  HALF_OPEN: yes (the probe's own retry loop may ask
        again before reporting an outcome).
        """
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if self._clock() - self._opened_at >= self.cooldown_seconds:
                self.state = HALF_OPEN
                self.probes += 1
                return True
            return False
        return True  # HALF_OPEN

    # -- outcome reporting ---------------------------------------------------
    def record_failure(self) -> None:
        """The protected call failed: trip (or re-trip) the breaker."""
        self.failures += 1
        self._consecutive_failures += 1
        if (
            self.state == HALF_OPEN
            or self._consecutive_failures >= self.failure_threshold
        ):
            if self.state != OPEN:
                self.opens += 1
            self.state = OPEN
            self._opened_at = self._clock()
            self._consecutive_failures = 0

    def record_success(self) -> None:
        """The protected call succeeded: close from a probe, and reset
        the consecutive-failure run in any state."""
        self._consecutive_failures = 0
        if self.state != CLOSED:
            self.state = CLOSED
            self._opened_at = None
            self.reclosures += 1

    # -- observability -------------------------------------------------------
    def snapshot(self) -> dict:
        return {
            "state": self.state,
            "cooldown_seconds": self.cooldown_seconds,
            "failure_threshold": self.failure_threshold,
            "failures": self.failures,
            "opens": self.opens,
            "probes": self.probes,
            "reclosures": self.reclosures,
        }
