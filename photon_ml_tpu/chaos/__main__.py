"""Chaos CLI: the scripted kill/resume/degrade selfcheck.

::

    python -m photon_ml_tpu.chaos --selfcheck

runs the whole recovery story end-to-end on the CPU backend (< 1 min,
device-free, CI-greppable), proving — not asserting — that:

1. a streamed GLM λ-grid killed at a grid-point boundary resumes through
   the watchdog and lands on coefficients BITWISE identical to an
   uninterrupted run;
2. a mid-pass streaming fault (the carry-sync seam) tears down both
   pipeline threads promptly — no deadlock, no leaked daemon thread
   (``prefetch_thread_leak`` stays 0) — and the next clean pass is
   bit-identical to a never-faulted one (no corrupted donated
   accumulators);
3. a GAME coordinate-descent run killed at a CD iteration boundary
   resumes from ``cd_checkpoint.npz`` bitwise identically;
4. a device-lost fault during serving degrades to host-side scoring with
   ZERO request errors (scores correct, degraded flag on /healthz), and
   the circuit breaker re-promotes once the fault clears;
5. checkpoint hardening: a truncated newest checkpoint falls back to the
   previous verifiable one, full corruption raises a pointed
   :class:`~photon_ml_tpu.io.checkpoint.CheckpointCorruptError`, and a
   kill between tmp-write and rename leaves the old checkpoint intact.

``--list-sites`` prints the fault-site catalog; ``--plan FILE`` validates
a JSON fault plan without running anything (CI lint for scripted
scenarios).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

import numpy as np


def _bitwise(a, b) -> bool:
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    return a.shape == b.shape and a.tobytes() == b.tobytes()


# ---------------------------------------------------------------------------
# Scenario 1+2: streamed GLM grid — kill/resume + mid-pass teardown
# ---------------------------------------------------------------------------

def _check_streamed_glm(tmp: str, failures: list[str]) -> None:
    import jax.numpy as jnp
    import scipy.sparse as sp

    from photon_ml_tpu import chaos
    from photon_ml_tpu import telemetry as telemetry_mod
    from photon_ml_tpu.data.streaming import make_streaming_glm_data
    from photon_ml_tpu.io.checkpoint import GridCheckpointer
    from photon_ml_tpu.optim.problem import (
        GlmOptimizationConfig,
        GlmOptimizationProblem,
        OptimizerConfig,
    )
    from photon_ml_tpu.optim.regularization import RegularizationContext
    from photon_ml_tpu.optim.streaming import (
        StreamingObjective,
        streaming_run_grid,
    )
    from photon_ml_tpu.utils.watchdog import (
        RetryPolicy,
        RetryStats,
        run_with_retries,
    )

    rng = np.random.default_rng(7)
    n, d = 240, 12
    X = sp.random(n, d, density=0.4, random_state=3, format="csr",
                  dtype=np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (np.asarray(X @ w_true).ravel() > 0).astype(np.float32)
    stream = make_streaming_glm_data(X, y, chunk_rows=60, use_pallas=False)
    problem = GlmOptimizationProblem(
        "logistic",
        GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=25, tolerance=1e-7),
            regularization=RegularizationContext.l2(),
        ),
    )
    lams = [3.0, 1.0, 0.3]

    # Uninterrupted reference.
    full = streaming_run_grid(problem, stream, lams)
    ref = {lam: np.asarray(m.coefficients.means) for lam, m, _ in full}

    # Killed-and-resumed run: the fault fires at the SECOND grid-point
    # boundary (λ solved + checkpointed, next λ untouched); the watchdog
    # re-enters the closure, which reloads the checkpoint.
    ckpt = GridCheckpointer(os.path.join(tmp, "glm_ck"))
    plan = chaos.FaultPlan([chaos.FaultSpec(site="grid.point", at=1)])

    def train(attempt: int):
        solved = ckpt.load() if attempt else {}
        acc = dict(solved)

        def on_solved(lam, w):
            acc[lam] = np.asarray(w)
            ckpt.save(acc)

        return streaming_run_grid(
            problem, stream, lams, solved=solved, on_solved=on_solved,
        )

    stats = RetryStats()
    with plan:
        resumed = run_with_retries(
            train, RetryPolicy(max_retries=2), sleep=lambda s: None,
            stats=stats,
        )
    if not plan.fired_at("grid.point"):
        failures.append("streamed grid: the scripted kill never fired")
    if stats.retries != 1:
        failures.append(
            f"streamed grid: expected exactly 1 watchdog retry, got "
            f"{stats.retries}"
        )
    for lam, model, res in resumed:
        if not _bitwise(ref[lam], model.coefficients.means):
            failures.append(
                f"streamed grid: resumed λ={lam} coefficients are NOT "
                "bitwise identical to the uninterrupted run"
            )
    restored = sum(1 for _, _, res in resumed if res is None)
    if restored != 2:
        failures.append(
            f"streamed grid: resume restored {restored} points from the "
            "checkpoint, expected 2"
        )

    # Mid-pass teardown: a carry-sync fault aborts the pass promptly,
    # leaks no pipeline thread, and the next clean pass is bit-identical
    # to a never-faulted one.
    sobj = StreamingObjective(problem.objective, stream)
    w0 = jnp.zeros((d,), jnp.float32)
    v_clean, g_clean = sobj.value_and_grad(w0, 1.0)
    v_clean, g_clean = np.asarray(v_clean), np.asarray(g_clean)
    tel = telemetry_mod.current()
    leaks_before = tel.counter("prefetch_thread_leak").value
    midpass = chaos.FaultPlan([
        chaos.FaultSpec(site="streaming.carry_sync", at=2),
    ])
    with midpass:
        try:
            sobj.value_and_grad(w0, 1.0)
            failures.append("mid-pass fault: the scripted fault never fired")
        except chaos.InjectedFault:
            pass
    if tel.counter("prefetch_thread_leak").value != leaks_before:
        failures.append(
            "mid-pass fault: a prefetch pipeline thread leaked during "
            "teardown"
        )
    v2, g2 = sobj.value_and_grad(w0, 1.0)
    if not (_bitwise(v_clean, v2) and _bitwise(g_clean, g2)):
        failures.append(
            "mid-pass fault: the pass AFTER the fault is not bit-identical "
            "to a clean pass (corrupted accumulators?)"
        )


# ---------------------------------------------------------------------------
# Scenario 3: GAME CD — kill at an iteration boundary, resume bitwise
# ---------------------------------------------------------------------------

def _check_game_cd(tmp: str, failures: list[str]) -> None:
    import scipy.sparse as sp

    from photon_ml_tpu import chaos
    from photon_ml_tpu.game.estimator import (
        FixedEffectCoordinateConfig,
        GameEstimator,
        RandomEffectCoordinateConfig,
    )
    from photon_ml_tpu.io.checkpoint import CoordinateDescentCheckpointer
    from photon_ml_tpu.optim.problem import (
        GlmOptimizationConfig,
        OptimizerConfig,
    )
    from photon_ml_tpu.optim.regularization import RegularizationContext
    from photon_ml_tpu.utils.watchdog import (
        RetryPolicy,
        RetryStats,
        run_with_retries,
    )

    rng = np.random.default_rng(13)
    n, n_users = 300, 10
    user_effect = rng.normal(scale=2.0, size=n_users)
    Xg = rng.normal(size=(n, 3)).astype(np.float32)
    users = rng.integers(n_users, size=n)
    margin = 1.3 * Xg[:, 0] - 0.7 * Xg[:, 1] + user_effect[users]
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(np.float32)
    shards = {
        "global": sp.csr_matrix(Xg),
        "userFeatures": sp.csr_matrix(np.ones((n, 1), np.float32)),
    }
    ids = {"userId": np.array([f"u{u}" for u in users])}

    opt = GlmOptimizationConfig(
        optimizer=OptimizerConfig(max_iters=25, tolerance=1e-7),
        regularization=RegularizationContext.l2(),
    )
    configs = lambda: {  # noqa: E731 — fresh configs per estimator
        "fixed": FixedEffectCoordinateConfig(
            feature_shard="global", optimization=opt, reg_weight=0.5
        ),
        "per_user": RandomEffectCoordinateConfig(
            feature_shard="userFeatures", entity_key="userId",
            optimization=opt, reg_weight=0.5,
        ),
    }

    model_full, hist_full = GameEstimator(
        "logistic", configs(), n_iterations=3
    ).fit(shards, ids, y)

    ck = CoordinateDescentCheckpointer(os.path.join(tmp, "cd_ck"))
    plan = chaos.FaultPlan([chaos.FaultSpec(site="cd.iteration", at=1)])

    def attempt(a: int):
        return GameEstimator("logistic", configs(), n_iterations=3).fit(
            shards, ids, y, checkpointer=ck
        )

    stats = RetryStats()
    with plan:
        model_res, hist_res = run_with_retries(
            attempt, RetryPolicy(max_retries=2), sleep=lambda s: None,
            stats=stats,
        )
    if not plan.fired_at("cd.iteration"):
        failures.append("game cd: the scripted kill never fired")
    if stats.retries != 1:
        failures.append(
            f"game cd: expected exactly 1 watchdog retry, got "
            f"{stats.retries}"
        )
    if not _bitwise(
        model_full["fixed"].model.coefficients.means,
        model_res["fixed"].model.coefficients.means,
    ):
        failures.append(
            "game cd: resumed fixed-effect coefficients are NOT bitwise "
            "identical to the uninterrupted run"
        )
    cf = model_full["per_user"].coefficients
    cr = model_res["per_user"].coefficients
    if set(cf) != set(cr) or any(
        not _bitwise(cf[k][1], cr[k][1]) for k in cf
    ):
        failures.append(
            "game cd: resumed per-entity coefficients are NOT bitwise "
            "identical to the uninterrupted run"
        )
    if len(hist_res) != len(hist_full):
        failures.append(
            f"game cd: resumed history has {len(hist_res)} entries, "
            f"uninterrupted has {len(hist_full)}"
        )


# ---------------------------------------------------------------------------
# Scenario 4: serving — degrade on device loss, re-promote via breaker
# ---------------------------------------------------------------------------

def _check_serving(tmp: str, failures: list[str]) -> None:
    from photon_ml_tpu import chaos
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService
    from photon_ml_tpu.serving.synthetic import SyntheticWorkload

    workload = SyntheticWorkload(n_entities=32, seed=5)
    runtime = ScoringRuntime(
        workload.model, workload.index_maps,
        RuntimeConfig(
            max_batch_size=4, hot_entities=8, breaker_cooldown_s=0.0
        ),
    )
    requests = [workload.request(i) for i in range(16)]
    rows = [runtime.parse_request(r) for r in requests]
    # Healthy-path reference BEFORE any plan is installed (these batches
    # must not consume serving.device occurrences).
    reference = np.asarray(
        [runtime.score_rows([row])[0][0] for row in rows], np.float32
    )

    service = ScoringService(runtime, BatcherConfig(
        max_batch_size=4, max_wait_us=0, max_queue=64,
    ))
    # Device lost for 4 consecutive batches, then it "comes back".
    plan = chaos.FaultPlan([
        chaos.FaultSpec(
            site="serving.device", at=0, count=4,
            exception="InjectedDeviceLost",
        ),
    ])
    degraded_seen = False
    errors = 0
    served = np.zeros(len(rows), np.float32)
    with service, plan:
        for i, req in enumerate(requests):
            result = service.score(req)
            if "error" in result:
                errors += 1
            else:
                served[i] = np.float32(result["score"])
            if service.healthz()["degraded"]:
                degraded_seen = True
    if errors:
        failures.append(
            f"serving: {errors} request(s) errored during the device-lost "
            "window — degraded mode must keep every request succeeding"
        )
    if not degraded_seen:
        failures.append("serving: the degraded flag never showed on healthz")
    if not plan.fired_at("serving.device"):
        failures.append("serving: the scripted device fault never fired")
    if runtime.degraded or runtime.breaker.state != "closed":
        failures.append(
            f"serving: breaker did not re-promote after the fault cleared "
            f"(degraded={runtime.degraded}, breaker="
            f"{runtime.breaker.state})"
        )
    if runtime.repromotions < 1 or runtime.degraded_batches < 1:
        failures.append(
            "serving: expected >= 1 degraded batch and >= 1 re-promotion, "
            f"got {runtime.degraded_batches} / {runtime.repromotions}"
        )
    if not np.allclose(served, reference, rtol=1e-5, atol=1e-6):
        bad = int(np.argmax(~np.isclose(served, reference,
                                        rtol=1e-5, atol=1e-6)))
        failures.append(
            "serving: degraded-mode scores diverge from the healthy "
            f"reference (first bad row {bad}: {served[bad]!r} vs "
            f"{reference[bad]!r})"
        )


# ---------------------------------------------------------------------------
# Scenario 5: checkpoint hardening — torn files, fallback, mid-save kill
# ---------------------------------------------------------------------------

def _check_checkpoint_hardening(tmp: str, failures: list[str]) -> None:
    from photon_ml_tpu import chaos
    from photon_ml_tpu.io.checkpoint import (
        CheckpointCorruptError,
        GridCheckpointer,
    )

    ck = GridCheckpointer(os.path.join(tmp, "hard_ck"))
    w1 = {1.0: np.ones(4, np.float32)}
    w2 = {1.0: np.ones(4, np.float32), 0.5: np.full(4, 2.0, np.float32)}
    ck.save(w1)
    ck.save(w2)

    # Kill between tmp-write and rename: the published checkpoint must
    # still be the complete previous one.
    with chaos.FaultPlan([chaos.FaultSpec(site="checkpoint.save", at=0)]):
        try:
            ck.save({**w2, 0.1: np.zeros(4, np.float32)})
            failures.append("hardening: mid-save kill never fired")
        except chaos.InjectedFault:
            pass
    if sorted(ck.load()) != sorted(w2):
        failures.append(
            "hardening: a kill before the atomic rename damaged the "
            "published checkpoint"
        )

    # Truncate the newest file: restore must fall back to the previous
    # verifiable generation (w1), not crash and not return nothing.
    with open(ck.path, "r+b") as f:
        f.truncate(32)
    loaded = ck.load()
    if sorted(loaded) != sorted(w1):
        failures.append(
            f"hardening: fallback after truncation loaded {sorted(loaded)} "
            f"instead of the previous generation {sorted(w1)}"
        )

    # Corrupt every retained generation: a pointed error naming the path.
    with open(ck.path + ".1", "r+b") as f:
        f.truncate(16)
    try:
        ck.load()
        failures.append(
            "hardening: fully-corrupt checkpoints loaded without error"
        )
    except CheckpointCorruptError as exc:
        if ck.path not in str(exc):
            failures.append(
                f"hardening: corruption error does not name the path: {exc}"
            )


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

def run_selfcheck(out_dir: str) -> list[str]:
    """Returns failure strings (empty = pass)."""
    from photon_ml_tpu import telemetry as telemetry_mod

    failures: list[str] = []
    with telemetry_mod.Telemetry(
        output_dir=out_dir, run_name="chaos-selfcheck"
    ) as tel:
        with tel.span("selfcheck", subsystem="chaos"):
            with tel.span("streamed_glm_kill_resume"):
                _check_streamed_glm(out_dir, failures)
            with tel.span("game_cd_kill_resume"):
                _check_game_cd(out_dir, failures)
            with tel.span("serving_degrade"):
                _check_serving(out_dir, failures)
            with tel.span("checkpoint_hardening"):
                _check_checkpoint_hardening(out_dir, failures)
        snap = tel.snapshot()
    injected = snap["counters"].get("chaos_faults_injected", 0)
    if injected < 4:
        failures.append(
            f"chaos_faults_injected counter is {injected}, expected >= 4 "
            "(one per scripted scenario)"
        )
    if snap["counters"].get("prefetch_thread_leak", 0):
        failures.append("prefetch_thread_leak counter is nonzero")
    if not os.path.exists(os.path.join(out_dir, "metrics.json")):
        failures.append(f"missing {os.path.join(out_dir, 'metrics.json')}")
    if not failures:
        print(
            f"chaos selfcheck: {injected} scripted faults injected; "
            "streamed-grid + GAME-CD kill/resume bitwise-identical, "
            "mid-pass teardown leak-free, serving degraded with 0 errors "
            "and re-promoted, checkpoint fallback + pointed corruption "
            "errors verified"
        )
    return failures


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.chaos",
        description="deterministic fault injection / recovery selfcheck",
    )
    p.add_argument("--selfcheck", action="store_true")
    p.add_argument(
        "--list-sites", action="store_true",
        help="print the fault-site catalog as JSON",
    )
    p.add_argument(
        "--plan", metavar="FILE",
        help="validate a JSON fault plan (parse + site/spec checks) "
        "without running anything",
    )
    p.add_argument(
        "--output-dir",
        help="telemetry output dir (selfcheck defaults to a tempdir)",
    )
    args = p.parse_args(argv)

    if args.list_sites:
        from photon_ml_tpu.chaos import KNOWN_SITES

        print(json.dumps(KNOWN_SITES, indent=2))
        return 0

    if args.plan:
        from photon_ml_tpu.chaos import FaultPlan

        with open(args.plan) as f:
            plan = FaultPlan.from_json(f.read())
        print(f"{args.plan}: {len(plan.faults)} fault spec(s) valid")
        return 0

    if not args.selfcheck:
        p.print_help()
        return 2

    if args.output_dir:
        os.makedirs(args.output_dir, exist_ok=True)
        failures = run_selfcheck(args.output_dir)
    else:
        with tempfile.TemporaryDirectory(
            prefix="photon_chaos_selfcheck_"
        ) as td:
            failures = run_selfcheck(td)
    if failures:
        print("chaos selfcheck FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("chaos selfcheck PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
