"""Minimal Avro object-container-file codec, dependency-free.

The reference's wire/storage format is Avro everywhere — training examples,
model coefficients (``BayesianLinearModelAvro``), scores (SURVEY.md §2,
"Avro IO" / "Avro schemas") — so this package speaks real Avro too.  No
Avro library is available in this environment, so this implements the Avro
1.x object container spec directly: files written here are readable by
standard Avro tooling and vice versa.

Supported schema subset (all the reference's schemas need): primitives
(null, boolean, int, long, float, double, bytes, string), records, arrays,
maps, unions, and enums.  Codecs: null (uncompressed), deflate, and snappy
(pure-Python block format — LinkedIn-ecosystem Avro is typically
snappy-compressed, so real reference datasets need it to ingest).
"""

from __future__ import annotations

import io as _io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Iterable, Iterator

MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# Primitive binary encoding
# ---------------------------------------------------------------------------

def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_long(buf: BinaryIO, n: int) -> None:
    n = _zigzag_encode(n)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            return


def read_long(buf: BinaryIO) -> int:
    shift = 0
    acc = 0
    while True:
        byte = buf.read(1)
        if not byte:
            raise EOFError("truncated varint")
        b = byte[0]
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            return _zigzag_decode(acc)
        shift += 7


def write_bytes(buf: BinaryIO, data: bytes) -> None:
    write_long(buf, len(data))
    buf.write(data)


def read_bytes(buf: BinaryIO) -> bytes:
    n = read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise EOFError("truncated bytes")
    return data


# ---------------------------------------------------------------------------
# Schema-directed datum encoding
# ---------------------------------------------------------------------------

def _resolve(schema: Any) -> Any:
    """Normalize shorthand string schemas ("string") to dict form."""
    if isinstance(schema, str):
        return {"type": schema}
    return schema


def write_datum(buf: BinaryIO, schema: Any, datum: Any) -> None:
    if isinstance(schema, list):  # union
        for i, branch in enumerate(schema):
            if _matches(branch, datum):
                write_long(buf, i)
                write_datum(buf, branch, datum)
                return
        raise TypeError(f"datum {datum!r} matches no union branch in {schema}")
    s = _resolve(schema)
    t = s["type"]
    if t == "null":
        return
    if t == "boolean":
        buf.write(b"\x01" if datum else b"\x00")
    elif t in ("int", "long"):
        write_long(buf, int(datum))
    elif t == "float":
        buf.write(struct.pack("<f", float(datum)))
    elif t == "double":
        buf.write(struct.pack("<d", float(datum)))
    elif t == "bytes":
        write_bytes(buf, bytes(datum))
    elif t == "string":
        write_bytes(buf, datum.encode("utf-8"))
    elif t == "enum":
        write_long(buf, s["symbols"].index(datum))
    elif t == "record":
        for field in s["fields"]:
            try:
                write_datum(buf, field["type"], datum[field["name"]])
            except (KeyError, TypeError) as e:
                raise TypeError(
                    f"record field {field['name']!r}: {e}"
                ) from e
    elif t == "array":
        items = list(datum)
        if items:
            write_long(buf, len(items))
            for item in items:
                write_datum(buf, s["items"], item)
        write_long(buf, 0)
    elif t == "map":
        entries = dict(datum)
        if entries:
            write_long(buf, len(entries))
            for k, v in entries.items():
                write_bytes(buf, k.encode("utf-8"))
                write_datum(buf, s["values"], v)
        write_long(buf, 0)
    else:
        raise TypeError(f"unsupported Avro type {t!r}")


def _matches(schema: Any, datum: Any) -> bool:
    s = _resolve(schema)
    t = s["type"]
    if t == "null":
        return datum is None
    if t == "boolean":
        return isinstance(datum, bool)
    if t in ("int", "long"):
        return isinstance(datum, int) and not isinstance(datum, bool)
    if t in ("float", "double"):
        return isinstance(datum, float) or (
            isinstance(datum, int) and not isinstance(datum, bool)
        )
    if t == "bytes":
        return isinstance(datum, (bytes, bytearray))
    if t == "string":
        return isinstance(datum, str)
    if t == "enum":
        return isinstance(datum, str) and datum in s["symbols"]
    if t == "record":
        return isinstance(datum, dict)
    if t == "array":
        return isinstance(datum, (list, tuple))
    if t == "map":
        return isinstance(datum, dict)
    return False


def read_datum(buf: BinaryIO, schema: Any) -> Any:
    if isinstance(schema, list):  # union
        idx = read_long(buf)
        return read_datum(buf, schema[idx])
    s = _resolve(schema)
    t = s["type"]
    if t == "null":
        return None
    if t == "boolean":
        return buf.read(1) == b"\x01"
    if t in ("int", "long"):
        return read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "bytes":
        return read_bytes(buf)
    if t == "string":
        return read_bytes(buf).decode("utf-8")
    if t == "enum":
        return s["symbols"][read_long(buf)]
    if t == "record":
        return {
            field["name"]: read_datum(buf, field["type"]) for field in s["fields"]
        }
    if t == "array":
        out = []
        while True:
            count = read_long(buf)
            if count == 0:
                return out
            if count < 0:  # block with byte size prefix
                count = -count
                read_long(buf)
            for _ in range(count):
                out.append(read_datum(buf, s["items"]))
    if t == "map":
        out = {}
        while True:
            count = read_long(buf)
            if count == 0:
                return out
            if count < 0:
                count = -count
                read_long(buf)
            for _ in range(count):
                k = read_bytes(buf).decode("utf-8")
                out[k] = read_datum(buf, s["values"])
    raise TypeError(f"unsupported Avro type {t!r}")


# ---------------------------------------------------------------------------
# Object container files
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Snappy block format (pure Python — no snappy module in the image)
#
# LinkedIn-ecosystem Avro is typically snappy-compressed; without this
# codec, real reference datasets would not ingest (VERDICT r2 missing #6).
# Avro's snappy framing is the raw snappy BLOCK format followed by a
# 4-byte big-endian CRC32 of the UNCOMPRESSED payload.
# ---------------------------------------------------------------------------


def _snappy_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _snappy_compress(data: bytes) -> bytes:
    """Greedy snappy block-format compressor: 4-byte hash matches within a
    64 KiB window become copy elements (length 4..64, 2-byte offsets), the
    rest literals.  Any conformant snappy decoder reads the output; the
    ratio is modest but real on repetitive payloads (Avro blocks of
    same-schema records are exactly that)."""
    out = bytearray(_snappy_varint(len(data)))
    n = len(data)

    def emit_literal(lo: int, hi: int) -> None:
        nonlocal out
        ln = hi - lo - 1
        if ln < 0:
            return
        if ln < 60:
            out.append(ln << 2)
        else:
            nb = (ln.bit_length() + 7) // 8
            out.append((59 + nb) << 2)
            out += ln.to_bytes(nb, "little")
        out += data[lo:hi]

    table: dict[bytes, int] = {}
    i = 0
    lit = 0
    while i + 4 <= n:
        key = data[i:i + 4]
        j = table.get(key)
        table[key] = i
        if j is not None and 0 < i - j <= 0xFFFF:
            k = 4
            limit = min(64, n - i)
            while k < limit and data[j + k] == data[i + k]:
                k += 1
            emit_literal(lit, i)
            out.append(((k - 1) << 2) | 2)  # 2-byte-offset copy
            out += (i - j).to_bytes(2, "little")
            i += k
            lit = i
        else:
            i += 1
    emit_literal(lit, n)
    return bytes(out)


def _snappy_uncompress(data: bytes) -> bytes:
    """Full snappy block-format decoder (all literal and copy tags,
    including overlapping copies)."""
    n = 0
    shift = 0
    i = 0
    while True:
        if i >= len(data):
            raise ValueError("snappy: truncated preamble")
        b = data[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray()
    while i < len(data):
        tag = data[i]
        i += 1
        t = tag & 3
        if t == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                nb = ln - 59
                if i + nb > len(data):
                    raise ValueError("snappy: truncated literal length")
                ln = int.from_bytes(data[i:i + nb], "little")
                i += nb
            ln += 1
            if i + ln > len(data):
                raise ValueError("snappy: truncated literal")
            out += data[i:i + ln]
            i += ln
            continue
        nb = {1: 1, 2: 2, 3: 4}[t]
        if i + nb > len(data):
            raise ValueError("snappy: truncated copy element")
        if t == 1:  # copy, 1-byte offset
            ln = ((tag >> 2) & 0x7) + 4
            off = ((tag >> 5) << 8) | data[i]
        elif t == 2:  # copy, 2-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[i:i + 2], "little")
        else:  # copy, 4-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[i:i + 4], "little")
        i += nb
        if off == 0 or off > len(out):
            raise ValueError("snappy: copy offset out of range")
        start = len(out) - off
        if off >= ln:
            out += out[start:start + ln]
        else:  # overlapping copy: byte-at-a-time per spec
            for k in range(ln):
                out.append(out[start + k])
    if len(out) != n:
        raise ValueError(
            f"snappy: decoded {len(out)} bytes, preamble said {n}"
        )
    return bytes(out)


def _snappy_frame_avro(raw: bytes) -> bytes:
    return _snappy_compress(raw) + (zlib.crc32(raw) & 0xFFFFFFFF).to_bytes(
        4, "big"
    )


def _snappy_unframe_avro(payload: bytes) -> bytes:
    if len(payload) < 4:
        raise ValueError("snappy: block too short for CRC")
    raw = _snappy_uncompress(payload[:-4])
    crc = int.from_bytes(payload[-4:], "big")
    if (zlib.crc32(raw) & 0xFFFFFFFF) != crc:
        raise ValueError("snappy: CRC mismatch (corrupt block)")
    return raw


_META_SCHEMA = {"type": "map", "values": "bytes"}
_SYNC = bytes(
    [0x70, 0x68, 0x6F, 0x74, 0x6F, 0x6E, 0x2D, 0x74,
     0x70, 0x75, 0x2D, 0x73, 0x79, 0x6E, 0x63, 0x21]
)  # deterministic marker ("photon-tpu-sync!") — valid per spec


def _write_container_header(f: BinaryIO, schema: Any, codec: str) -> None:
    """Container magic + metadata + sync — ONE implementation; the
    columnar scoring writer's byte-parity contract with
    :func:`write_container` depends on them sharing this framing."""
    f.write(MAGIC)
    write_datum(f, _META_SCHEMA, {
        "avro.schema": json.dumps(schema).encode("utf-8"),
        "avro.codec": codec.encode("utf-8"),
    })
    f.write(_SYNC)


def _write_block(f: BinaryIO, count: int, payload: bytes, codec: str) -> None:
    """One container block: codec framing + count + payload + sync."""
    if codec == "deflate":
        payload = zlib.compress(payload)[2:-4]  # raw deflate per spec
    elif codec == "snappy":
        payload = _snappy_frame_avro(payload)
    write_long(f, count)
    write_bytes(f, payload)
    f.write(_SYNC)


def write_container(
    path: str,
    schema: Any,
    records: Iterable[Any],
    codec: str = "deflate",
    records_per_block: int = 4096,
) -> None:
    assert codec in ("null", "deflate", "snappy")
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        _write_container_header(f, schema, codec)

        block: list[Any] = []

        def flush():
            if not block:
                return
            body = _io.BytesIO()
            for rec in block:
                write_datum(body, schema, rec)
            _write_block(f, len(block), body.getvalue(), codec)
            block.clear()

        for rec in records:
            block.append(rec)
            if len(block) >= records_per_block:
                flush()
        flush()


def _encode_scoring_block_native(lib, uids, scores, labels, ids_cols):
    """One columnar ScoringResultAvro block body via the native encoder
    (native/score_encoder.cpp); None when the call cannot proceed."""
    import ctypes

    import numpy as np

    n = len(scores)
    uid_b = [b"" if u is None else str(u).encode("utf-8") for u in uids]
    uid_blob = b"".join(uid_b)
    uid_off = np.zeros(n + 1, np.int64)
    np.cumsum([len(b) for b in uid_b], out=uid_off[1:])
    uid_null = np.frombuffer(
        bytes(1 if u is None else 0 for u in uids), np.uint8
    )
    scores64 = np.ascontiguousarray(scores, np.float64)
    label_null = np.frombuffer(
        bytes(1 if v is None else 0 for v in labels), np.uint8
    )
    labels64 = np.asarray(
        [0.0 if v is None else float(v) for v in labels], np.float64
    )
    keys = list(ids_cols)
    key_b = [k.encode("utf-8") for k in keys]
    keys_blob = b"".join(key_b)
    keys_off = np.zeros(len(keys) + 1, np.int64)
    np.cumsum([len(b) for b in key_b], out=keys_off[1:])
    # Column-major (matching se_encode), one comprehension per column —
    # the per-cell work stays in C-driven list machinery, not an
    # interpreted index loop.
    val_b: list[bytes] = []
    null_cols: list[bytes] = []
    for k in keys:
        col = ids_cols[k]
        val_b.extend(
            b"" if v is None else str(v).encode("utf-8") for v in col
        )
        null_cols.append(bytes(1 if v is None else 0 for v in col))
    val_null = np.frombuffer(b"".join(null_cols) or b"", np.uint8)
    vals_blob = b"".join(val_b)
    vals_off = np.zeros(len(val_b) + 1, np.int64)
    np.cumsum([len(b) for b in val_b], out=vals_off[1:])

    p_i64 = ctypes.POINTER(ctypes.c_int64)
    p_u8 = ctypes.POINTER(ctypes.c_uint8)
    p_f64 = ctypes.POINTER(ctypes.c_double)
    cap = int(
        uid_off[-1] + vals_off[-1] + (keys_off[-1] + 40) * n + 60 * n + 64
    )
    for _ in range(2):
        out = ctypes.create_string_buffer(cap)
        wrote = lib.se_encode(
            n,
            uid_blob,
            uid_off.ctypes.data_as(p_i64),
            uid_null.ctypes.data_as(p_u8),
            scores64.ctypes.data_as(p_f64),
            labels64.ctypes.data_as(p_f64),
            label_null.ctypes.data_as(p_u8),
            len(keys),
            vals_blob,
            vals_off.ctypes.data_as(p_i64),
            val_null.ctypes.data_as(p_u8),
            keys_blob,
            keys_off.ctypes.data_as(p_i64),
            out, cap,
        )
        if wrote >= 0:
            return out.raw[:wrote]
        cap = -int(wrote)
    return None


def write_scoring_container(
    path: str,
    blocks: Iterable[tuple],
    codec: str = "deflate",
    records_per_block: int = 4096,
) -> int:
    """COLUMNAR writer for ScoringResultAvro — the write-side mirror of
    the native block decoder.  ``blocks`` yields ``(uids, scores, labels,
    ids)`` where ``uids`` is a sequence of str-or-None, ``scores`` /
    ``labels`` are float sequences (entries may be None for a null
    label), and ``ids`` maps column name → per-row values (None entries
    are omitted from that row's map, the join-miss contract).  Map keys
    are written in the ITERATION ORDER of ``ids`` — callers wanting the
    canonical layout pass sorted dicts.

    Output is byte-for-byte what :func:`write_container` produces for the
    equivalent record dicts (parity-tested); the per-record Python
    serialization loop — measured ~130k rec/s, an order of magnitude
    under the scoring rate — runs natively instead when the encoder
    library is available.  Returns the number of rows written.
    """
    import numpy as np

    from photon_ml_tpu.io.schemas import SCORING_RESULT
    from photon_ml_tpu.native import load_score_encoder

    assert codec in ("null", "deflate", "snappy")
    lib = load_score_encoder()
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    pend_u: list = []
    pend_s: list = []
    pend_l: list = []
    pend_ids: Optional[dict] = None
    total = 0

    def body_bytes(u, s, l, ids) -> bytes:
        if lib is not None:
            enc = _encode_scoring_block_native(lib, u, s, l, ids)
            if enc is not None:
                return enc
        out = _io.BytesIO()
        for i in range(len(s)):
            write_datum(out, SCORING_RESULT, {
                "uid": u[i],
                "predictionScore": float(s[i]),
                "label": None if l[i] is None else float(l[i]),
                "ids": {
                    k: str(ids[k][i])
                    for k in ids
                    if ids[k][i] is not None
                },
            })
        return out.getvalue()

    with open(path, "wb") as f:
        _write_container_header(f, SCORING_RESULT, codec)

        def flush(count):
            nonlocal pend_u, pend_s, pend_l, total
            u, pend_u = pend_u[:count], pend_u[count:]
            s, pend_s = pend_s[:count], pend_s[count:]
            l, pend_l = pend_l[:count], pend_l[count:]
            ids = {k: v[:count] for k, v in pend_ids.items()}
            for k in pend_ids:
                pend_ids[k] = pend_ids[k][count:]
            _write_block(f, count, body_bytes(u, s, l, ids), codec)
            total += count

        for uids, scores, labels, ids in blocks:
            def tolist(col):
                return (
                    col.tolist() if isinstance(col, np.ndarray)
                    else list(col)
                )

            n_blk = len(scores)
            bad = [
                name for name, col in (
                    ("uids", uids), ("labels", labels),
                    *((f"ids[{k!r}]", v) for k, v in ids.items()),
                )
                if len(col) != n_blk
            ]
            if bad:
                # A misaligned column would silently SHIFT values into
                # the wrong rows (or die deep in the offset math).
                raise ValueError(
                    f"columns {bad} do not match len(scores)={n_blk}"
                )
            if pend_ids is None:
                pend_ids = {k: [] for k in ids}
            else:
                # Columns may come and go across streamed blocks (each
                # block's id set is what its rows carried): a column new
                # to this block backfills pending rows with None, a
                # column absent from it pads with None below — None
                # entries are omitted from that row's map, exactly the
                # old per-record writer's semantics.
                new = [k for k in ids if k not in pend_ids]
                for k in new:
                    pend_ids[k] = [None] * len(pend_s)
                if new:
                    # Canonical (sorted) column order regardless of when
                    # a column first appeared — the resident path sees
                    # the whole-file union up front, and map-entry order
                    # is part of the byte-parity contract.
                    pend_ids = {
                        k: pend_ids[k] for k in sorted(pend_ids)
                    }
            pend_u.extend(tolist(uids))
            pend_s.extend(tolist(scores))
            pend_l.extend(tolist(labels))
            for k in pend_ids:
                pend_ids[k].extend(
                    tolist(ids[k]) if k in ids else [None] * n_blk
                )
            while len(pend_s) >= records_per_block:
                flush(records_per_block)
        if pend_s:
            flush(len(pend_s))
    return total


def _read_header(f: BinaryIO, path: str) -> tuple[Any, str, bytes]:
    """Parse the container header → (schema, codec, sync marker)."""
    if f.read(4) != MAGIC:
        raise ValueError(f"{path}: not an Avro container file")
    meta = read_datum(f, _META_SCHEMA)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    if codec not in ("null", "deflate", "snappy"):
        raise ValueError(f"unsupported codec {codec!r}")
    sync = f.read(16)
    return schema, codec, sync


def iter_blocks(path: str) -> Iterator[tuple[Any, int, bytes]]:
    """Stream a container file block-by-block WITHOUT materializing records:
    yields (schema, record_count, decompressed_block_payload).  This is the
    scale path — a multi-GB file is processed one ~records_per_block chunk
    at a time (the reference streams Avro through Spark partitions the same
    way; SURVEY.md §7 hard-part "host→device ingest")."""
    with open(path, "rb") as f:
        schema, codec, sync = _read_header(f, path)
        while True:
            head = f.read(1)
            if not head:
                break
            f.seek(-1, 1)
            count = read_long(f)
            payload = read_bytes(f)
            if codec == "deflate":
                payload = zlib.decompress(payload, -15)
            elif codec == "snappy":
                payload = _snappy_unframe_avro(payload)
            if f.read(16) != sync:
                raise ValueError(f"{path}: sync marker mismatch (corrupt file)")
            yield schema, count, payload


def iter_container(path: str) -> Iterator[Any]:
    """Yield records one at a time, holding at most one block in memory."""
    for schema, count, payload in iter_blocks(path):
        body = _io.BytesIO(payload)
        for _ in range(count):
            yield read_datum(body, schema)


def read_schema(path: str) -> Any:
    with open(path, "rb") as f:
        schema, _, _ = _read_header(f, path)
    return schema


def read_container(path: str) -> tuple[Any, list[Any]]:
    """Read an Avro object container file → (schema, records).  Convenience
    for small files; use :func:`iter_container` / :func:`iter_blocks` for
    anything large."""
    records: list[Any] = []
    schema = None
    for schema, count, payload in iter_blocks(path):
        body = _io.BytesIO(payload)
        for _ in range(count):
            records.append(read_datum(body, schema))
    if schema is None:
        schema = read_schema(path)
    return schema, records
