"""Avro schemas for models, training data, and scores.

The analogue of the reference's ``photon-avro-schemas`` module (SURVEY.md §2):
``TrainingExampleAvro`` (response + weight + offset + features as
name/term/value triples), ``BayesianLinearModelAvro`` (coefficient means with
optional variances), and ``ScoringResultAvro``.  Field names follow the
reference's conventions (name/term/value feature triples, ``(INTERCEPT)``
magic name) so data round-trips between the two systems.
"""

NAME_TERM_VALUE = {
    "type": "record",
    "name": "NameTermValueAvro",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

TRAINING_EXAMPLE = {
    "type": "record",
    "name": "TrainingExampleAvro",
    "fields": [
        {"name": "uid", "type": ["null", "string"]},
        {"name": "response", "type": "double"},
        {"name": "weight", "type": ["null", "double"]},
        {"name": "offset", "type": ["null", "double"]},
        {"name": "features", "type": {"type": "array", "items": NAME_TERM_VALUE}},
    ],
}

BAYESIAN_LINEAR_MODEL = {
    "type": "record",
    "name": "BayesianLinearModelAvro",
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": "string"},
        {"name": "lossFunction", "type": "string"},
        {
            "name": "means",
            "type": {
                "type": "array",
                "items": {
                    "type": "record",
                    "name": "CoefficientAvro",
                    "fields": [
                        {"name": "name", "type": "string"},
                        {"name": "term", "type": "string"},
                        {"name": "value", "type": "double"},
                    ],
                },
            },
        },
        {
            "name": "variances",
            "type": [
                "null",
                {
                    "type": "array",
                    "items": {
                        "type": "record",
                        "name": "CoefficientVarianceAvro",
                        "fields": [
                            {"name": "name", "type": "string"},
                            {"name": "term", "type": "string"},
                            {"name": "value", "type": "double"},
                        ],
                    },
                },
            ],
        },
    ],
}

SCORING_RESULT = {
    "type": "record",
    "name": "ScoringResultAvro",
    "fields": [
        {"name": "uid", "type": ["null", "string"]},
        {"name": "predictionScore", "type": "double"},
        {"name": "label", "type": ["null", "double"]},
        {"name": "ids", "type": {"type": "map", "values": "string"}},
    ],
}

FEATURE_SUMMARY = {
    "type": "record",
    "name": "FeatureSummaryAvro",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "mean", "type": "double"},
        {"name": "variance", "type": "double"},
        {"name": "min", "type": "double"},
        {"name": "max", "type": "double"},
        {"name": "nonzeroCount", "type": "long"},
        {"name": "totalWeight", "type": "double"},
    ],
}
