"""GLM model persistence.

The analogue of the reference's ``ModelProcessingUtils`` save/load path
(SURVEY.md §2, "Avro IO"): coefficients are written as real Avro
(``BayesianLinearModelAvro``-shaped records, one coefficient per
name/term/value entry) so models interchange with reference tooling.
Coefficients with value 0 are not written (the reference's sparse model
files do the same); loading uses an index map to place named coefficients.
"""

from __future__ import annotations

import os
from typing import Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.io import avro
from photon_ml_tpu.io.schemas import BAYESIAN_LINEAR_MODEL
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel

_MODEL_CLASS = {
    "logistic": "LogisticRegressionModel",
    "squared": "LinearRegressionModel",
    "poisson": "PoissonRegressionModel",
    "smoothed_hinge": "SmoothedHingeLossLinearSVMModel",
}
_CLASS_TO_TASK = {v: k for k, v in _MODEL_CLASS.items()}


def _split_key(key: str) -> tuple[str, str]:
    name, sep, term = key.partition("\x01")
    return name, term if sep else ""


def save_glm_model(
    model: GeneralizedLinearModel,
    index_map: IndexMap,
    path: str,
    model_id: str = "",
    sparsify: bool = True,
) -> None:
    """Write a model as an Avro container file (.avro)."""
    means = np.asarray(model.coefficients.means, np.float64)
    variances = (
        None
        if model.coefficients.variances is None
        else np.asarray(model.coefficients.variances, np.float64)
    )

    def entries(vec):
        out = []
        for j, v in enumerate(vec):
            if sparsify and v == 0.0:
                continue
            name, term = _split_key(index_map.index_to_name(j))
            out.append({"name": name, "term": term, "value": float(v)})
        return out

    record = {
        "modelId": model_id,
        "modelClass": _MODEL_CLASS[model.task],
        "lossFunction": model.task,
        "means": entries(means),
        "variances": None if variances is None else entries(variances),
    }
    avro.write_container(path, BAYESIAN_LINEAR_MODEL, [record])


def load_glm_model(
    path: str, index_map: Optional[IndexMap] = None
) -> tuple[GeneralizedLinearModel, IndexMap]:
    """Read a model written by :func:`save_glm_model`.

    Without an index map, one is reconstructed from the coefficient names in
    file order (sufficient for scoring data indexed with the same map)."""
    _, records = avro.read_container(path)
    if len(records) != 1:
        raise ValueError(f"{path}: expected 1 model record, found {len(records)}")
    rec = records[0]
    task = _CLASS_TO_TASK.get(rec["modelClass"], rec["lossFunction"])

    keys = [feature_key(e["name"], e["term"]) for e in rec["means"]]
    if index_map is None:
        # Union of means and variances keys: a coefficient sparsified out of
        # the means (value 0) can still carry a nonzero variance, and must
        # keep a slot or the variance is silently dropped on round trip.
        all_keys = list(keys)
        seen = set(keys)
        for e in rec["variances"] or []:
            key = feature_key(e["name"], e["term"])
            if key not in seen:
                seen.add(key)
                all_keys.append(key)
        index_map = IndexMap.build(all_keys)
    d = len(index_map)
    means = np.zeros(d, np.float32)
    for e, key in zip(rec["means"], keys):
        idx = index_map.get_index(key)
        if idx >= 0:
            means[idx] = e["value"]
    variances = None
    if rec["variances"] is not None:
        variances = np.zeros(d, np.float32)
        for e in rec["variances"]:
            idx = index_map.get_index(feature_key(e["name"], e["term"]))
            if idx >= 0:
                variances[idx] = e["value"]
    model = GeneralizedLinearModel(
        Coefficients(
            jnp.asarray(means),
            None if variances is None else jnp.asarray(variances),
        ),
        task,
    )
    return model, index_map
