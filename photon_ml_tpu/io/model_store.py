"""GLM model persistence.

The analogue of the reference's ``ModelProcessingUtils`` save/load path
(SURVEY.md §2, "Avro IO"): coefficients are written as real Avro
(``BayesianLinearModelAvro``-shaped records, one coefficient per
name/term/value entry) so models interchange with reference tooling.
Coefficients with value 0 are not written (the reference's sparse model
files do the same); loading uses an index map to place named coefficients.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
from typing import Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.io import avro
from photon_ml_tpu.io.schemas import BAYESIAN_LINEAR_MODEL
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel

_MODEL_CLASS = {
    "logistic": "LogisticRegressionModel",
    "squared": "LinearRegressionModel",
    "poisson": "PoissonRegressionModel",
    "smoothed_hinge": "SmoothedHingeLossLinearSVMModel",
}
_CLASS_TO_TASK = {v: k for k, v in _MODEL_CLASS.items()}


def _split_key(key: str) -> tuple[str, str]:
    name, sep, term = key.partition("\x01")
    return name, term if sep else ""


# ---------------------------------------------------------------------------
# Fingerprints: a save-time identity the load path verifies, so a
# truncated / hand-edited / wrong-version coefficient file fails LOUDLY at
# load instead of silently serving garbage scores.
# ---------------------------------------------------------------------------

def coefficient_checksum(entry_lists) -> str:
    """sha256 over (name, term, value) coefficient entries, in file order.

    ``entry_lists`` is a sequence of entry lists (means, then variances
    when present, separated by a marker) — both save and load feed the
    RAW record entries, so the checksum binds to the Avro content
    regardless of which index map later places the coefficients.  Values
    hash as their exact float64 bit pattern (Avro stores doubles)."""
    h = hashlib.sha256()
    for entries in entry_lists:
        h.update(b"\x00SECTION\x00")
        if entries is None:
            continue
        for e in entries:
            h.update(str(e["name"]).encode())
            h.update(b"\x00")
            h.update(str(e["term"]).encode())
            h.update(struct.pack("<d", float(e["value"])))
    return h.hexdigest()


def _warn_unverified(path: str, why: str) -> None:
    """A model loading WITHOUT fingerprint verification is a quiet hole
    in the tamper story (a flipped bit serves wrong scores with no
    error) — make it loud: a pointed warning for the operator reading
    logs plus ``model_load_unverified_total`` for the fleet dashboard.
    Re-save with the current writer to get a sidecar."""
    import warnings

    from photon_ml_tpu import telemetry as telemetry_mod

    telemetry_mod.current().counter("model_load_unverified_total").inc()
    warnings.warn(
        f"{path}: loading UNVERIFIED ({why}); content tampering or "
        "truncation cannot be detected on this model — re-save it with "
        "the current writer to attach a fingerprint sidecar",
        stacklevel=3,
    )


def glm_fingerprint(task: str, feature_count: int, record: dict) -> dict:
    return {
        "version": 1,
        "task": task,
        "feature_count": int(feature_count),
        "n_coefficients": len(record["means"]),
        "coefficient_checksum": coefficient_checksum(
            [record["means"], record["variances"]]
        ),
    }


def _reject_nonfinite(vec: Optional[np.ndarray], what: str, path: str):
    """NaN/inf coefficients persist silently in Avro and then poison every
    score downstream; refuse at save time with a pointed error."""
    if vec is None:
        return
    bad = ~np.isfinite(vec)
    if bad.any():
        idx = np.flatnonzero(bad)
        raise ValueError(
            f"refusing to save {path}: {idx.size} non-finite {what} "
            f"value(s) (first at index {int(idx[0])}: {vec[idx[0]]!r}); "
            "a model with NaN/inf coefficients scores NaN — fix the "
            "training run (check for exploding optimizer steps or bad "
            "regularization) instead of persisting it"
        )


def save_glm_model(
    model: GeneralizedLinearModel,
    index_map: IndexMap,
    path: str,
    model_id: str = "",
    sparsify: bool = True,
) -> dict:
    """Write a model as an Avro container file (.avro) plus a
    ``<path>.meta.json`` sidecar carrying the model fingerprint (feature
    count, task, coefficient checksum) that :func:`load_glm_model`
    verifies.  Returns the fingerprint.  Non-finite coefficients are
    rejected here rather than silently persisted."""
    means = np.asarray(model.coefficients.means, np.float64)
    variances = (
        None
        if model.coefficients.variances is None
        else np.asarray(model.coefficients.variances, np.float64)
    )
    _reject_nonfinite(means, "coefficient", path)
    _reject_nonfinite(variances, "variance", path)

    def entries(vec):
        out = []
        for j, v in enumerate(vec):
            if sparsify and v == 0.0:
                continue
            name, term = _split_key(index_map.index_to_name(j))
            out.append({"name": name, "term": term, "value": float(v)})
        return out

    record = {
        "modelId": model_id,
        "modelClass": _MODEL_CLASS[model.task],
        "lossFunction": model.task,
        "means": entries(means),
        "variances": None if variances is None else entries(variances),
    }
    avro.write_container(path, BAYESIAN_LINEAR_MODEL, [record])
    fingerprint = glm_fingerprint(model.task, len(index_map), record)
    with open(path + ".meta.json", "w") as f:
        json.dump({"fingerprint": fingerprint}, f, indent=2)
    return fingerprint


def verify_glm_fingerprint(
    path: str, task: str, record: dict, index_map: Optional[IndexMap]
) -> Optional[dict]:
    """Check file content against the save-time fingerprint sidecar.
    Returns the fingerprint when one was verified; a pre-fingerprint
    file (no sidecar) loads UNVERIFIED — loudly: a pointed warning plus
    the ``model_load_unverified_total`` counter, so a fleet serving
    unverifiable models is visible on /metrics, not just in a log
    nobody tails."""
    meta_path = path + ".meta.json"
    if not os.path.exists(meta_path):
        _warn_unverified(path, "no .meta.json fingerprint sidecar")
        return None
    with open(meta_path) as f:
        fingerprint = json.load(f).get("fingerprint")
    if not fingerprint:
        _warn_unverified(meta_path, "sidecar carries no fingerprint")
        return None
    actual = coefficient_checksum([record["means"], record["variances"]])
    if actual != fingerprint.get("coefficient_checksum"):
        raise ValueError(
            f"{path}: coefficient checksum mismatch (file {actual[:16]}…, "
            f"fingerprint {str(fingerprint.get('coefficient_checksum'))[:16]}…) "
            "— the model file was modified/truncated after save, or the "
            "sidecar belongs to a different save"
        )
    if fingerprint.get("task") != task:
        raise ValueError(
            f"{path}: task mismatch — file says {task!r}, fingerprint "
            f"says {fingerprint.get('task')!r}"
        )
    if (
        index_map is not None
        and fingerprint.get("feature_count") is not None
        and len(index_map) != fingerprint["feature_count"]
    ):
        raise ValueError(
            f"{path}: model was saved with "
            f"{fingerprint['feature_count']} features but the provided "
            f"index map has {len(index_map)}; read the data with the "
            "model's saved index maps"
        )
    return fingerprint


def read_fingerprints(path: str) -> dict:
    """Read the ``<path>.meta.json`` fingerprint sidecar WITHOUT loading
    the coefficient arrays — the cheap HEAD the delta differ
    (``freshness/delta.py``) and ops tooling use to decide whether a
    model changed at all before paying for an Avro parse.

    Returns the fingerprint dict (``task``, ``feature_count``,
    ``n_coefficients``, ``coefficient_checksum``).  A pre-fingerprint
    file (no sidecar) raises a pointed error: there is nothing to diff
    against, and quietly answering "unknown" would make a delta differ
    treat every legacy model as unchanged."""
    meta_path = path + ".meta.json"
    if not os.path.exists(meta_path):
        raise ValueError(
            f"{path}: no .meta.json fingerprint sidecar — this model "
            "predates fingerprinting, so its content cannot be compared "
            "or delta-diffed; re-save it with the current writer "
            "(save_glm_model) to attach a fingerprint"
        )
    with open(meta_path) as f:
        fingerprint = json.load(f).get("fingerprint")
    if not fingerprint:
        raise ValueError(
            f"{meta_path}: sidecar carries no fingerprint — re-save the "
            "model with the current writer (save_glm_model) to attach one"
        )
    return fingerprint


def load_glm_model(
    path: str, index_map: Optional[IndexMap] = None
) -> tuple[GeneralizedLinearModel, IndexMap]:
    """Read a model written by :func:`save_glm_model`.

    Without an index map, one is reconstructed from the coefficient names in
    file order (sufficient for scoring data indexed with the same map).

    When the save-time ``<path>.meta.json`` fingerprint sidecar is
    present (absent on pre-fingerprint files: those load unverified), the
    file content is verified against it — coefficient checksum, task,
    and, when ``index_map`` is given, feature count — and a mismatch
    raises instead of returning a silently-wrong model."""
    _, records = avro.read_container(path)
    if len(records) != 1:
        raise ValueError(f"{path}: expected 1 model record, found {len(records)}")
    rec = records[0]
    task = _CLASS_TO_TASK.get(rec["modelClass"], rec["lossFunction"])
    verify_glm_fingerprint(path, task, rec, index_map)

    keys = [feature_key(e["name"], e["term"]) for e in rec["means"]]
    if index_map is None:
        # Union of means and variances keys: a coefficient sparsified out of
        # the means (value 0) can still carry a nonzero variance, and must
        # keep a slot or the variance is silently dropped on round trip.
        all_keys = list(keys)
        seen = set(keys)
        for e in rec["variances"] or []:
            key = feature_key(e["name"], e["term"])
            if key not in seen:
                seen.add(key)
                all_keys.append(key)
        index_map = IndexMap.build(all_keys)
    d = len(index_map)
    means = np.zeros(d, np.float32)
    for e, key in zip(rec["means"], keys):
        idx = index_map.get_index(key)
        if idx >= 0:
            means[idx] = e["value"]
    variances = None
    if rec["variances"] is not None:
        variances = np.zeros(d, np.float32)
        for e in rec["variances"]:
            idx = index_map.get_index(feature_key(e["name"], e["term"]))
            if idx >= 0:
                variances[idx] = e["value"]
    model = GeneralizedLinearModel(
        Coefficients(
            jnp.asarray(means),
            None if variances is None else jnp.asarray(variances),
        ),
        task,
    )
    return model, index_map
