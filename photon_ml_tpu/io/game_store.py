"""GAME model persistence.

The analogue of the reference's ``ModelProcessingUtils`` GAME save/load
(SURVEY.md §3.2 "save GameModel ... Avro: fixed-effect + per-entity
coefficient files"): a directory with one Avro file per coordinate —
``fixed-effect/<name>/coefficients.avro`` holding one
BayesianLinearModelAvro record, ``random-effect/<name>/coefficients.avro``
holding one record per entity — plus per-shard index maps and a metadata
manifest for coordinate order/types.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct

import numpy as np

from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.game.model import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.io import avro
from photon_ml_tpu.io.model_store import (
    _warn_unverified,
    load_glm_model,
    save_glm_model,
)

RANDOM_EFFECT_MODEL_SCHEMA = {
    "type": "record",
    "name": "RandomEffectCoefficientsAvro",
    "fields": [
        {"name": "entityId", "type": "string"},
        {
            "name": "coefficients",
            "type": {
                "type": "array",
                "items": {
                    "type": "record",
                    "name": "EntityCoefficientAvro",
                    "fields": [
                        {"name": "name", "type": "string"},
                        {"name": "term", "type": "string"},
                        {"name": "value", "type": "double"},
                        # Optional per-coefficient variance (the reference's
                        # BayesianLinearModelAvro carries variances too).
                        {"name": "variance", "type": ["null", "double"]},
                    ],
                },
            },
        },
    ],
}


def random_effect_checksum(records) -> str:
    """sha256 over per-entity (entityId, name, term, value, variance)
    entries in file order — both save and load feed the raw Avro records,
    so the checksum binds to the persisted content."""
    h = hashlib.sha256()
    for rec in records:
        h.update(b"\x00ENTITY\x00")
        h.update(str(rec["entityId"]).encode())
        for e in rec["coefficients"]:
            h.update(str(e["name"]).encode())
            h.update(b"\x00")
            h.update(str(e["term"]).encode())
            h.update(struct.pack("<d", float(e["value"])))
            var = e.get("variance")
            h.update(b"\x01" if var is None else struct.pack("<d", float(var)))
    return h.hexdigest()


def save_game_model(
    model: GameModel, index_maps: dict, directory: str
) -> None:
    """``index_maps`` maps feature-shard name → IndexMap.

    ``metadata.json`` carries a per-coordinate fingerprint (feature
    count, task, coefficient checksum) that :func:`load_game_model`
    verifies; non-finite coefficients are rejected here instead of being
    silently persisted."""
    os.makedirs(directory, exist_ok=True)
    manifest = {"task": model.task, "coordinates": [], "fingerprints": {}}
    for name, sub in model.models.items():
        if isinstance(sub, FixedEffectModel):
            sub_dir = os.path.join(directory, "fixed-effect", name)
            os.makedirs(sub_dir, exist_ok=True)
            manifest["fingerprints"][name] = save_glm_model(
                sub.model,
                index_maps[sub.feature_shard],
                os.path.join(sub_dir, "coefficients.avro"),
                model_id=name,
            )
            manifest["coordinates"].append(
                {"name": name, "type": "fixed", "feature_shard": sub.feature_shard}
            )
        else:
            sub_dir = os.path.join(directory, "random-effect", name)
            os.makedirs(sub_dir, exist_ok=True)
            imap = index_maps[sub.feature_shard]
            records = []
            for entity, (cols, vals) in sub.coefficients.items():
                variances = (
                    sub.variances.get(entity)
                    if sub.variances is not None
                    else None
                )
                if not np.all(np.isfinite(vals)) or (
                    variances is not None
                    and not np.all(np.isfinite(variances))
                ):
                    raise ValueError(
                        f"refusing to save coordinate {name!r}: entity "
                        f"{entity!r} carries non-finite coefficients — a "
                        "model with NaN/inf coefficients scores NaN; fix "
                        "the training run instead of persisting it"
                    )
                coefs = []
                for j, (c, v) in enumerate(zip(cols, vals)):
                    fname, _, term = imap.index_to_name(int(c)).partition("\x01")
                    coefs.append({
                        "name": fname,
                        "term": term,
                        "value": float(v),
                        "variance": (
                            float(variances[j]) if variances is not None
                            else None
                        ),
                    })
                records.append({"entityId": str(entity), "coefficients": coefs})
            avro.write_container(
                os.path.join(sub_dir, "coefficients.avro"),
                RANDOM_EFFECT_MODEL_SCHEMA,
                records,
            )
            manifest["fingerprints"][name] = {
                "version": 1,
                "task": model.task,
                "feature_count": sub.n_features,
                "n_entities": len(records),
                "coefficient_checksum": random_effect_checksum(records),
            }
            manifest["coordinates"].append({
                "name": name,
                "type": "random",
                "feature_shard": sub.feature_shard,
                "entity_key": sub.entity_key,
                "n_features": sub.n_features,
            })
    for shard, imap in index_maps.items():
        imap.save(os.path.join(directory, "index-maps", shard))
    with open(os.path.join(directory, "metadata.json"), "w") as f:
        json.dump(manifest, f, indent=2)


def read_fingerprints(directory: str) -> dict:
    """Read per-coordinate fingerprints from ``metadata.json`` WITHOUT
    loading any coefficient Avro — the cheap HEAD the delta differ
    (``freshness/delta.py``) and ops tooling use to decide which
    coordinates changed before paying for a full parse.

    Returns coordinate name → fingerprint dict (``task``,
    ``feature_count``, ``coefficient_checksum``, and ``n_entities`` for
    random-effect coordinates).  A legacy directory whose manifest lacks
    fingerprints (entirely or for some coordinate) raises a pointed
    error: "unknown" would make a differ treat it as unchanged."""
    meta_path = os.path.join(directory, "metadata.json")
    with open(meta_path) as f:
        manifest = json.load(f)
    fingerprints = manifest.get("fingerprints") or {}
    missing = [
        c["name"] for c in manifest["coordinates"]
        if c["name"] not in fingerprints
    ]
    if missing:
        raise ValueError(
            f"{meta_path}: no fingerprint for coordinate(s) "
            f"{', '.join(repr(m) for m in missing)} — this model predates "
            "fingerprinting, so its content cannot be compared or "
            "delta-diffed; re-save it with the current writer "
            "(save_game_model) to attach fingerprints"
        )
    return fingerprints


def load_game_model(directory: str) -> tuple[GameModel, dict]:
    """Returns (model, index_maps-by-shard).

    Models saved with manifest fingerprints are verified per coordinate
    (random-effect checksums here, fixed-effect sidecars inside
    ``load_glm_model``); pre-fingerprint directories load unverified —
    with a pointed warning per unverified coordinate and the
    ``model_load_unverified_total`` counter (tampering on such a model
    is undetectable, so the condition must be visible on /metrics)."""
    with open(os.path.join(directory, "metadata.json")) as f:
        manifest = json.load(f)
    fingerprints = manifest.get("fingerprints") or {}
    index_maps: dict = {}
    imap_root = os.path.join(directory, "index-maps")
    if os.path.isdir(imap_root):
        for shard in os.listdir(imap_root):
            index_maps[shard] = IndexMap.load(os.path.join(imap_root, shard))

    models: dict = {}
    for coord in manifest["coordinates"]:
        name = coord["name"]
        if coord["type"] == "fixed":
            path = os.path.join(
                directory, "fixed-effect", name, "coefficients.avro"
            )
            glm, imap = load_glm_model(path, index_maps.get(coord["feature_shard"]))
            index_maps.setdefault(coord["feature_shard"], imap)
            models[name] = FixedEffectModel(glm, coord["feature_shard"])
        else:
            path = os.path.join(
                directory, "random-effect", name, "coefficients.avro"
            )
            _, records = avro.read_container(path)
            fp = fingerprints.get(name)
            if fp:
                actual = random_effect_checksum(records)
                if actual != fp.get("coefficient_checksum"):
                    raise ValueError(
                        f"{path}: coefficient checksum mismatch (file "
                        f"{actual[:16]}…, fingerprint "
                        f"{str(fp.get('coefficient_checksum'))[:16]}…) — "
                        "the coefficient file was modified/truncated "
                        "after save"
                    )
                if fp.get("n_entities") is not None and len(records) != \
                        fp["n_entities"]:
                    raise ValueError(
                        f"{path}: {len(records)} entities on disk, "
                        f"fingerprint says {fp['n_entities']}"
                    )
            else:
                _warn_unverified(
                    path, "no fingerprint in the metadata.json manifest"
                )
            imap = index_maps[coord["feature_shard"]]
            table = {}
            var_table: dict = {}
            for rec in records:
                cols, vals, variances = [], [], []
                for e in rec["coefficients"]:
                    idx = imap.get_index(feature_key(e["name"], e["term"]))
                    if idx >= 0:
                        cols.append(idx)
                        vals.append(e["value"])
                        # Older files lack the variance field entirely.
                        variances.append(e.get("variance"))
                cols = np.asarray(cols, np.int32)
                vals = np.asarray(vals, np.float32)
                # Store invariant: columns ascending (coefficient_matrix_for
                # binary-searches them).
                order = np.argsort(cols, kind="stable")
                table[rec["entityId"]] = (cols[order], vals[order])
                if any(v is not None for v in variances):
                    var = np.asarray(
                        [0.0 if v is None else v for v in variances],
                        np.float32,
                    )
                    var_table[rec["entityId"]] = var[order]
            models[name] = RandomEffectModel(
                coefficients=table,
                feature_shard=coord["feature_shard"],
                entity_key=coord["entity_key"],
                task=manifest["task"],
                n_features=coord.get("n_features", len(imap)),
                variances=var_table or None,
            )
    return GameModel(models=models, task=manifest["task"]), index_maps
