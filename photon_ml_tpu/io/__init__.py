from photon_ml_tpu.io.model_store import (  # noqa: F401
    load_glm_model,
    save_glm_model,
)
