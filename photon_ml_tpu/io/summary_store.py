"""Per-feature summary persistence (Avro).

The reference writes feature summaries as Avro artifacts (SURVEY.md §5.5
"feature summary output (per-feature stats as Avro)") — one record per
feature with the name/term split, weighted moments, range, and nonzero
count.  Mirrors the BasicStatisticalSummary produced by data/stats.py.
"""

from __future__ import annotations

import numpy as np

from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.io import avro
from photon_ml_tpu.io.schemas import FEATURE_SUMMARY


def save_feature_summary(summary, index_map: IndexMap, path: str) -> None:
    """``summary``: a data/stats.BasicStatisticalSummary (device or host)."""
    mean = np.asarray(summary.mean, np.float64)
    var = np.asarray(summary.variance, np.float64)
    mins = np.asarray(summary.min, np.float64)
    maxs = np.asarray(summary.max, np.float64)
    nnz = np.asarray(summary.nnz, np.int64)
    count = float(np.asarray(summary.count))

    def records():
        for j in range(len(mean)):
            fname, _, term = index_map.index_to_name(j).partition("\x01")
            yield {
                "name": fname,
                "term": term,
                "mean": float(mean[j]),
                "variance": float(var[j]),
                "min": float(mins[j]),
                "max": float(maxs[j]),
                "nonzeroCount": int(nnz[j]),
                "totalWeight": count,
            }

    avro.write_container(path, FEATURE_SUMMARY, records())


def load_feature_summary(path: str) -> list[dict]:
    """Summary records in column order as written."""
    _, recs = avro.read_container(path)
    return recs
