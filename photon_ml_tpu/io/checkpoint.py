"""Checkpoint / resume for training jobs.

The reference inherits fault tolerance from Spark (task retry, lineage
re-execution — SURVEY.md §5.3) and offers warm restarts via prior-model
inputs ("incremental training", §5.4).  A TPU job has no lineage to replay,
so the analogue is explicit state checkpointing:

- ``CoordinateDescentCheckpointer`` — persists the full GAME coordinate-
  descent state (per-coordinate device states, per-coordinate scores, the
  running ``total`` offsets, the iteration counter, and the metric history)
  to the job's output directory after every CD iteration.  A killed job
  restarted with ``--resume`` continues from the last completed iteration
  and reproduces the uninterrupted result bit-for-bit: the restored
  ``total``/scores ARE the accumulated float values, not recomputations.
- ``GridCheckpointer`` — the legacy GLM driver's λ-grid analogue: records
  each solved (λ → coefficients) so a restart skips finished λs and
  continues the warm-start chain from the last solution.

Write protocol: ONE ``.npz`` file per checkpoint holding both the arrays
and an embedded JSON metadata string, written to a temp path and atomically
renamed — a kill at any instant leaves either the previous complete
checkpoint or the new complete one, never a torn pairing of old metadata
with new arrays.

Hardening (the fsync above the rename guards the NAMESPACE; these guard
the BYTES):

- every save embeds a sha256 digest of the payload arrays
  (``__checksum__``), recomputed and compared at restore — bit rot or a
  torn write raises :class:`CheckpointCorruptError` naming the path and
  both digests instead of a raw ``zipfile``/``OSError`` from deep inside
  numpy;
- saves retain the last ``keep_last`` checkpoints (``path`` newest,
  ``path.1`` previous, ...), and restore automatically falls back to the
  NEWEST VERIFIABLE one — a corrupted latest checkpoint costs one
  checkpoint interval of progress, not the whole run.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

import numpy as np

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.chaos import core as chaos_mod

#: retained checkpoint generations per path (newest + K-1 fallbacks).
DEFAULT_KEEP_LAST = 2

_CHECKSUM_KEY = "__checksum__"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file exists but cannot be trusted: unreadable npz
    (truncated/torn) or a payload-checksum mismatch.  Carries the path
    and the reason so the operator knows WHICH file to delete or restore
    from backup — the raw ``zipfile.BadZipFile`` this used to surface
    named neither."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"corrupt checkpoint {path}: {reason}")
        self.path = path
        self.reason = reason


def fsync_file(f) -> None:
    """Flush + fsync an open file object: the durability barrier every
    crash-safe writer in this package shares (checkpoints here, the
    tuning journal's per-record appends — tuning/state.py)."""
    f.flush()
    os.fsync(f.fileno())


def _payload_digest(arrays: dict) -> str:
    """sha256 over every payload array's (name, dtype, shape, bytes), in
    sorted name order — deterministic, and covering exactly what
    ``np.load`` hands back, so save-time and restore-time digests agree
    iff the arrays round-tripped intact."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        a = np.asarray(arrays[name])
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(tuple(a.shape)).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def _retained_paths(path: str, keep_last: int) -> list[str]:
    """Newest-first candidate list: ``path``, ``path.1``, ..."""
    return [path] + [f"{path}.{i}" for i in range(1, max(keep_last, 1))]


def _atomic_savez(
    path: str, arrays: dict, keep_last: int = 1
) -> None:
    arrays = dict(arrays)
    arrays[_CHECKSUM_KEY] = np.asarray(_payload_digest(arrays))
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        # fsync BEFORE the rename: os.replace is atomic in the namespace
        # but not a data barrier — a power cut after the rename could
        # otherwise leave a complete-looking checkpoint with torn bytes.
        fsync_file(f)
    # Mid-save crash boundary: tmp is complete but unpublished — a kill
    # here must leave the previous checkpoint (and its fallbacks) intact.
    chaos_mod.maybe_fail("checkpoint.save", path=path)
    # Keep-last-K rotation (newest -> .1 -> .2 ...), oldest dropped by
    # overwrite.  Each shift is its own atomic replace, so any crash
    # point leaves every retained slot either its old or new complete
    # file — never a torn one.
    for i in range(max(keep_last, 1) - 1, 0, -1):
        src = path if i == 1 else f"{path}.{i - 1}"
        if os.path.exists(src):
            os.replace(src, f"{path}.{i}")
    os.replace(tmp, path)


def _checkpoint_event(kind: str, path: str, **attrs) -> None:
    """One telemetry event + counter per checkpoint save/restore, with
    the on-disk size when the file exists (host stat, never a device
    touch)."""
    tel = telemetry_mod.current()
    if not tel.enabled:
        return
    try:
        attrs["bytes"] = os.path.getsize(path)
    except OSError:
        pass
    tel.event(f"checkpoint.{kind}", path=path, **attrs)
    tel.counter(f"checkpoint_{kind}s").inc()


def _flatten_state(prefix: str, st, arrays: dict):
    """Flatten a (possibly nested) coordinate state into ``arrays``.
    Returns a JSON-able structure spec: "array", a list of child specs
    (lists AND tuples both load back as lists — coordinates accept either),
    or None."""
    if st is None:
        return None
    if isinstance(st, (list, tuple)):
        return [
            _flatten_state(f"{prefix}__{i}", child, arrays)
            for i, child in enumerate(st)
        ]
    arrays[prefix] = np.asarray(st)
    return "array"


def _unflatten_state(prefix: str, spec, arrays: dict):
    if spec is None:
        return None
    if spec == "array":
        return arrays[prefix]
    return [
        _unflatten_state(f"{prefix}__{i}", child, arrays)
        for i, child in enumerate(spec)
    ]


def _verified_load(path: str) -> tuple[dict, dict]:
    """Load + verify ONE npz checkpoint file; raises
    :class:`CheckpointCorruptError` on a torn/truncated file or a
    checksum mismatch.  Files written before the checksum era (no
    ``__checksum__`` entry) load unverified."""
    try:
        with np.load(path) as z:
            arrays = {k: z[k] for k in z.files}
    except Exception as exc:  # noqa: BLE001 — numpy surfaces zipfile/
        # OSError/EOFError/ValueError depending on WHERE the file is torn;
        # all of them mean the same thing here.
        raise CheckpointCorruptError(
            path,
            f"unreadable npz ({type(exc).__name__}: {exc}) — the file is "
            "truncated or torn (killed mid-write on a pre-atomic-rename "
            "writer, or disk corruption)",
        ) from exc
    recorded = arrays.pop(_CHECKSUM_KEY, None)
    if recorded is not None:
        computed = _payload_digest(arrays)
        if str(recorded) != computed:
            raise CheckpointCorruptError(
                path,
                f"payload checksum mismatch (recorded {recorded}, "
                f"computed {computed}) — the arrays do not match what "
                "was saved",
            )
    try:
        meta = json.loads(str(arrays.pop("__meta__")))
    except (KeyError, ValueError) as exc:
        raise CheckpointCorruptError(
            path, f"missing/unparseable __meta__ record ({exc})"
        ) from exc
    return meta, arrays


def _load_npz_with_meta(
    path: str, keep_last: int = 1
) -> Optional[tuple[dict, dict]]:
    """Returns (meta, arrays) from the newest VERIFIABLE retained
    checkpoint, or None if none exists.

    Corruption handling: a corrupt newest file falls back to the next
    retained generation (with a warning + ``checkpoint_corruptions``
    counter); when every existing candidate is corrupt, the NEWEST one's
    error propagates — silently returning None there would restart the
    run from scratch as if no checkpoint had ever been written."""
    chaos_mod.maybe_fail("checkpoint.restore", path=path)
    first_error: Optional[CheckpointCorruptError] = None
    tel = telemetry_mod.current()
    for p in _retained_paths(path, keep_last):
        if not os.path.exists(p):
            continue
        try:
            result = _verified_load(p)
        except CheckpointCorruptError as exc:
            first_error = first_error or exc
            tel.counter("checkpoint_corruptions").inc()
            tel.event("checkpoint.corrupt", path=p, reason=exc.reason)
            import logging

            logging.getLogger(__name__).warning(
                "%s; trying the previous retained checkpoint", exc
            )
            continue
        if p != path:
            tel.counter("checkpoint_fallbacks").inc()
            tel.event("checkpoint.fallback", path=p, wanted=path)
        return result
    if first_error is not None:
        raise first_error
    return None


class CoordinateDescentCheckpointer:
    """Persist / restore CoordinateDescent loop state.

    Array layout inside ``cd_checkpoint.npz``:
      ``total``                  — (N,) accumulated offsets
      ``score__<coord>``        — (N,) that coordinate's scores
      ``state__<coord>...``     — that coordinate's state arrays: a bare
                                  vector (fixed effects), per-bucket
                                  ``__<i>`` arrays (random effects), or
                                  arbitrarily nested ``__<i>__<j>...``
                                  (factored random effects: (u_list, V))
      ``__meta__``              — JSON: iteration counter, coordinate
                                  names, per-coordinate state STRUCTURE
                                  specs ("array" | [specs...] | null),
                                  history
    """

    FILENAME = "cd_checkpoint.npz"

    def __init__(self, directory: str, keep_last: int = DEFAULT_KEEP_LAST):
        self.directory = directory
        self.path = os.path.join(directory, self.FILENAME)
        self.keep_last = max(int(keep_last), 1)

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def clear(self) -> None:
        for p in _retained_paths(self.path, self.keep_last):
            if os.path.exists(p):
                os.remove(p)

    def save(
        self,
        iteration: int,
        total,
        scores: dict,
        states: dict,
        history: list,
        locked: list | tuple = (),
    ) -> None:
        os.makedirs(self.directory, exist_ok=True)
        arrays = {"total": np.asarray(total)}
        for name, s in scores.items():
            arrays[f"score__{name}"] = np.asarray(s)
        specs: dict = {}
        for name, st in states.items():
            specs[name] = _flatten_state(f"state__{name}", st, arrays)
        arrays["__meta__"] = np.asarray(
            json.dumps(
                {
                    "iteration": iteration,
                    "coordinates": list(scores),
                    "state_specs": specs,
                    "history": history,
                    # Partial-retraining locked set: a resume must train
                    # the SAME coordinates the checkpointed run did, or
                    # the output model's coordinates were never trained
                    # against each other.
                    "locked": sorted(locked),
                    # Bucket-padding generation: tight per-bucket dims
                    # (round 4) changed random-effect state SHAPES, so a
                    # checkpoint from the geometric-grid era must not be
                    # restored into tightly-padded rebuilt datasets (the
                    # vmap would crash with an opaque shape mismatch).
                    "padding_gen": 2,
                }
            )
        )
        _atomic_savez(self.path, arrays, keep_last=self.keep_last)
        _checkpoint_event("save", self.path, store="cd", iteration=iteration)

    def load(self) -> Optional[dict]:
        """Returns {iteration, total, scores, states, history} or None.

        A checkpoint from a different bucket-padding generation is
        refused (None, with a warning): its random-effect state shapes
        were padded to the OLD grid and would shape-crash deep inside
        the rebuilt coordinates' vmapped solvers."""
        loaded = _load_npz_with_meta(self.path, keep_last=self.keep_last)
        if loaded is None:
            return None
        meta, arrays = loaded
        if meta.get("padding_gen", 1) != 2:
            # Only BUCKETED (list-structured) states carry padding-
            # dependent shapes; bare-vector fixed-effect states are safe
            # to restore from any generation.
            specs = meta.get("state_specs") or {
                name: ["array"] * n
                for name, n in meta.get("list_states", {}).items()
            }
            if any(isinstance(s, list) for s in specs.values()):
                import logging

                logging.getLogger(__name__).warning(
                    "%s: checkpoint written under bucket-padding "
                    "generation %s (current: 2) carries per-bucket "
                    "states — shapes are incompatible with tightly-"
                    "padded datasets; starting fresh",
                    self.path, meta.get("padding_gen", 1),
                )
                return None
        scores = {
            name: arrays[f"score__{name}"] for name in meta["coordinates"]
        }
        specs = meta.get("state_specs")
        if specs is None:
            # Pre-nesting checkpoint format: "list_states" held only the
            # per-coordinate list lengths (flat lists or bare arrays).
            specs = {}
            for name in meta["coordinates"]:
                if name in meta.get("list_states", {}):
                    specs[name] = ["array"] * meta["list_states"][name]
                elif f"state__{name}" in arrays:
                    specs[name] = "array"
                else:
                    specs[name] = None
        states = {
            name: _unflatten_state(f"state__{name}", specs.get(name), arrays)
            for name in meta["coordinates"]
        }
        _checkpoint_event(
            "restore", self.path, store="cd", iteration=int(meta["iteration"])
        )
        return {
            "iteration": int(meta["iteration"]),
            "total": arrays["total"],
            "scores": scores,
            "states": states,
            "history": meta["history"],
            "locked": meta.get("locked", []),
        }


class GridCheckpointer:
    """λ-grid checkpoint for the legacy GLM driver: one coefficient vector
    per solved regularization weight, so a restart skips finished λs and
    keeps the warm-start chain intact."""

    FILENAME = "grid_checkpoint.npz"

    def __init__(self, directory: str, keep_last: int = DEFAULT_KEEP_LAST):
        self.directory = directory
        self.path = os.path.join(directory, self.FILENAME)
        self.keep_last = max(int(keep_last), 1)

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def clear(self) -> None:
        for p in _retained_paths(self.path, self.keep_last):
            if os.path.exists(p):
                os.remove(p)

    def save(self, solved: dict, extra_meta: Optional[dict] = None) -> None:
        """``solved``: λ (float) → coefficient vector, in solve order.

        ``extra_meta``: JSON-able run-configuration metadata persisted
        alongside (e.g. the driver's ``--coefficient-bounds``
        fingerprint) so a ``--resume`` can refuse a checkpoint written
        under a different configuration."""
        os.makedirs(self.directory, exist_ok=True)
        arrays = {
            f"w__{i}": np.asarray(w) for i, w in enumerate(solved.values())
        }
        meta = {"lambdas": [float(lam) for lam in solved]}
        if extra_meta:
            meta.update(extra_meta)
        arrays["__meta__"] = np.asarray(json.dumps(meta))
        _atomic_savez(self.path, arrays, keep_last=self.keep_last)
        _checkpoint_event(
            "save", self.path, store="grid", solved=len(solved)
        )

    def load(self) -> dict:
        """Returns λ → coefficient vector (insertion order = solve order)."""
        loaded = _load_npz_with_meta(self.path, keep_last=self.keep_last)
        if loaded is None:
            return {}
        meta, arrays = loaded
        _checkpoint_event(
            "restore", self.path, store="grid", solved=len(meta["lambdas"])
        )
        return {lam: arrays[f"w__{i}"] for i, lam in enumerate(meta["lambdas"])}

    def load_meta(self) -> dict:
        """The checkpoint's metadata dict ({} when no checkpoint exists):
        ``lambdas`` plus whatever ``extra_meta`` the writer recorded."""
        loaded = _load_npz_with_meta(self.path, keep_last=self.keep_last)
        return {} if loaded is None else loaded[0]


class GameGridCheckpointer:
    """Per-grid-point checkpoint for the GAME coordinate-config grid.

    The CD-level checkpointer covers a single config; a config GRID used
    to restart whole on retry (the round-3 gap).  This persists each
    COMPLETED grid point — the trained GameModel (via the standard model
    store) plus metric/history metadata — so a retried or ``--resume``d
    grid skips finished points and re-fits only the interrupted one.

    A fingerprint of the grid point's configs (coordinate names, types,
    regularization weights) is stored with each point; a checkpoint whose
    fingerprint does not match the current grid layout is ignored, so a
    changed grid never silently serves stale models.
    """

    DIRNAME = "grid"

    def __init__(self, directory: str, index_maps: dict):
        self.root = os.path.join(directory, self.DIRNAME)
        self.index_maps = index_maps

    def _point_dir(self, gi: int) -> str:
        return os.path.join(self.root, f"point_{gi}")

    @staticmethod
    def fingerprint(configs: dict) -> dict:
        """JSON-stable image of the ENTIRE config per coordinate — any
        field change (optimizer settings, regularization type, sampling,
        streaming) must invalidate the point, not just reg_weight."""
        import dataclasses as _dc
        import enum

        def conv(o):
            if _dc.is_dataclass(o) and not isinstance(o, type):
                return {
                    f.name: conv(getattr(o, f.name))
                    for f in _dc.fields(o)
                }
            if isinstance(o, enum.Enum):
                return o.value
            if isinstance(o, (list, tuple)):
                return [conv(x) for x in o]
            if isinstance(o, dict):
                return {str(k): conv(v) for k, v in o.items()}
            if isinstance(o, (int, float, str, bool)) or o is None:
                return o
            return repr(o)

        return {
            name: {"type": type(cfg).__name__, "config": conv(cfg)}
            for name, cfg in configs.items()
        }

    def clear(self) -> None:
        import shutil

        shutil.rmtree(self.root, ignore_errors=True)

    def save_point(
        self, gi: int, configs: dict, model, metric, metric_key: str,
        history: list,
    ) -> None:
        import shutil

        from photon_ml_tpu.io.game_store import save_game_model

        def _default(o):
            if isinstance(o, np.generic):
                return o.item()
            if isinstance(o, np.ndarray):
                return o.tolist()
            return float(o)

        d = self._point_dir(gi)
        tmp = d + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        save_game_model(model, self.index_maps, tmp)
        meta = {
            "fingerprint": self.fingerprint(configs),
            "metric": None if metric is None else float(metric),
            "metric_key": metric_key,
            "history": history,
        }
        with open(os.path.join(tmp, "grid_meta.json"), "w") as f:
            json.dump(meta, f, default=_default)
        # Directory-level atomic publish: the meta file is written INSIDE
        # tmp before the rename, so a surviving point dir always carries
        # complete model + metadata.
        shutil.rmtree(d, ignore_errors=True)
        os.replace(tmp, d)
        telemetry_mod.current().event(
            "checkpoint.save", store="game_grid", grid_index=gi, path=d
        )
        telemetry_mod.current().counter("checkpoint_saves").inc()

    def load_point(self, gi: int, configs: dict, metric_key: str):
        """Returns ``(model, metric, history)`` for a completed matching
        point, else None.  ``metric_key`` must match the saved point's —
        a point selected by train metric must not be compared against
        other points' validation metrics (different kind, possibly
        opposite direction) when the validation setup changed between
        runs."""
        from photon_ml_tpu.io.game_store import load_game_model

        meta_path = os.path.join(self._point_dir(gi), "grid_meta.json")
        if not os.path.exists(meta_path):
            return None
        with open(meta_path) as f:
            meta = json.load(f)
        if meta.get("fingerprint") != self.fingerprint(configs):
            return None
        if meta.get("metric_key") != metric_key:
            return None
        model, _ = load_game_model(self._point_dir(gi))
        telemetry_mod.current().event(
            "checkpoint.restore", store="game_grid", grid_index=gi,
            path=self._point_dir(gi),
        )
        telemetry_mod.current().counter("checkpoint_restores").inc()
        return model, meta["metric"], meta.get("history", [])
