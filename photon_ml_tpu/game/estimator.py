"""GAME estimator / transformer: the programmatic API.

The analogue of the reference's spark.ml-style ``GameEstimator`` /
``GameTransformer`` (SURVEY.md §2, §3.4): ``fit`` builds per-coordinate
datasets from feature shards + entity-id columns, runs coordinate descent,
and returns a ``GameModel``; ``transform`` scores data with a trained model
(unseen entities contribute 0, as in the reference).

Reference call shape (SURVEY.md §3.2):
    GameEstimator.fit(trainData, validationData, coordinateConfigs)
Here the "DataFrame" is (shards, ids, response, weight, offset) host arrays:
``shards`` maps feature-shard name → scipy CSR (the reference's per-shard
feature bags), ``ids`` maps id-column name → per-row entity keys.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.dataset import make_glm_data
from photon_ml_tpu.evaluation.evaluators import Evaluator
from photon_ml_tpu.game.coordinates import (
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.data import (
    FixedEffectDataset,
    build_random_effect_dataset,
)
from photon_ml_tpu.game.descent import CoordinateDescent
from photon_ml_tpu.game.model import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.ops import losses as losses_lib
from photon_ml_tpu.optim.problem import GlmOptimizationConfig


@dataclasses.dataclass(frozen=True)
class FixedEffectCoordinateConfig:
    """Reference: ``FixedEffectCoordinateConfiguration`` (incl. its
    down-sampling rate, applied to this coordinate's TRAINING loss only)."""

    feature_shard: str
    optimization: GlmOptimizationConfig = GlmOptimizationConfig()
    reg_weight: float = 0.0
    #: <1.0 down-samples training rows for this coordinate (negatives only
    #: for binary tasks, uniform otherwise), re-weighting survivors so the
    #: objective stays unbiased.  Scoring always covers every row: dropped
    #: rows get training weight 0, not removal, so shapes stay static.
    down_sampling_rate: float = 1.0
    #: >0 trains this coordinate OUT-OF-CORE: the shard lives in host RAM
    #: as chunks of this many rows, streamed through HBM per objective
    #: pass (game/streaming.py) — for fixed-effect datasets larger than
    #: device memory.  All three optimizers stream (L-BFGS, OWL-QN for
    #: L1/elastic-net, smooth TRON).
    streaming_chunk_rows: int = 0
    #: chunks the ingest pipeline keeps in flight when streaming (2 = the
    #: classic double buffer; the consumer additionally syncs a window of
    #: this many carries behind dispatch, so HBM holds ≤ 2× this many
    #: chunks).
    prefetch_depth: int = 2
    #: chunks folded per device dispatch via an in-program lax.scan when
    #: streaming (single-device only) — amortizes per-dispatch overhead
    #: for small chunks; 1 disables fusion.
    chunk_fuse: int = 1
    #: evaluate a bracket of line-search candidates per streamed pass
    #: (identical trial sequence, roughly half the passes per solve).
    batch_linesearch: bool = True
    #: compressed chunk wire format when streaming: off|lossless|fp16|
    #: int8 (data/staging.py).  Chunks cross the link encoded and are
    #: dequantized on device inside the per-chunk program; "lossless"
    #: keeps every solve bitwise identical to the raw stream.
    stream_compress: str = "off"
    #: >0 keeps up to this many MB of (wire) chunk buffers RESIDENT in
    #: HBM across streamed passes, admission/eviction re-scored each
    #: pass from per-chunk gradient contributions — hot chunks skip
    #: pack + transfer entirely (single-device only, bitwise neutral).
    stream_hot_budget_mb: float = 0.0


@dataclasses.dataclass(frozen=True)
class RandomEffectCoordinateConfig:
    """Reference: ``RandomEffectCoordinateConfiguration`` (entity id column +
    feature shard + optimization; ``max_rows_per_entity`` is the active-set
    cap of the reference's active/passive split)."""

    feature_shard: str
    entity_key: str
    optimization: GlmOptimizationConfig = GlmOptimizationConfig()
    reg_weight: float = 0.0
    max_rows_per_entity: Optional[int] = None
    #: geometric bucket grid for per-entity size bucketing (2.0 = pow2);
    #: larger values consolidate long tails into fewer compiled programs.
    bucket_growth: float = 2.0
    #: bucket-boundary policy (game/data.py): "geometric" keeps the
    #: classic growth ladder; "cost_model" runs the repacker —
    #: boundaries chosen from the entity size histogram to minimize
    #: padding FLOPs under the compiled-program budget (deterministic
    #: under repack_seed).
    repack: str = "geometric"
    #: max compiled per-bucket programs the repacker may spend.
    program_budget: int = 16
    #: tie-break seed for the repacker (results are a pure function of
    #: (histogram, budget, seed)).
    repack_seed: int = 0
    #: mesh placement threshold (game/hierarchical.py): a bucket whose
    #: solve cost is >= split_factor × the ideal per-device share is
    #: SPLIT over the mesh; smaller buckets pack whole onto devices by
    #: cost-balanced assignment.  Applies to the mesh resident path and
    #: the out-of-core path.
    split_factor: float = 0.5
    #: >0 trains this coordinate OUT-OF-CORE: entity blocks stay in host
    #: RAM and stream through HBM in double-buffered pass groups bounded
    #: by this many bytes (game/ooc_random.py) — for random-effect
    #: datasets larger than device memory.  Per-entity coefficients live
    #: host-resident between passes.  Composes with a mesh (the budget
    #: then bounds per-device bytes).
    device_budget_bytes: int = 0
    #: pass groups the ingest pipeline keeps in flight when out-of-core
    #: (each group sized to device_budget_bytes / prefetch_depth).
    prefetch_depth: int = 2
    #: >0 keeps up to this many MB of out-of-core pass groups' STATIC
    #: slice payloads resident across passes (the streamed fixed
    #: effect's hot working-set cache, generalized): hot groups skip
    #: host pack + h2d transfer and stream only warm starts /
    #: coefficients.  Bitwise neutral.
    hot_budget_mb: float = 0.0


@dataclasses.dataclass(frozen=True)
class FactoredRandomEffectCoordinateConfig:
    """Reference: ``FactoredRandomEffectCoordinateConfiguration`` — random
    effects constrained to a shared rank-``rank`` projection (w_e = V u_e,
    see game/factored.py).  Dataset shape is identical to a plain random
    effect, so grid points share built datasets with it."""

    feature_shard: str
    entity_key: str
    rank: int
    optimization: GlmOptimizationConfig = GlmOptimizationConfig()
    reg_weight: float = 0.0
    projection_reg_weight: Optional[float] = None
    alternations: int = 2
    max_rows_per_entity: Optional[int] = None
    bucket_growth: float = 2.0
    #: bucket-boundary policy + budget + seed — shared with the plain
    #: random-effect config (identical dataset shape, shared cache).
    repack: str = "geometric"
    program_budget: int = 16
    repack_seed: int = 0
    #: >0 trains this coordinate OUT-OF-CORE (game/ooc_factored.py):
    #: entity blocks stream in budget-bounded pass groups, latent vectors
    #: host-resident between passes, and the shared projection V fits by
    #: host-loop L-BFGS with one streamed pass per evaluation.
    device_budget_bytes: int = 0
    #: pass groups the ingest pipeline keeps in flight when out-of-core.
    prefetch_depth: int = 2


CoordinateConfig = (
    FixedEffectCoordinateConfig
    | RandomEffectCoordinateConfig
    | FactoredRandomEffectCoordinateConfig
)


class GameEstimator:
    """Reference: ``GameEstimator`` (SURVEY.md §3.4).

    ``coordinate_configs`` is an ORDERED name→config mapping; coordinate
    update order is the reference's ``coordinateUpdateSequence``.
    """

    def __init__(
        self,
        task: str,
        coordinate_configs: dict[str, CoordinateConfig],
        n_iterations: int = 1,
        logger=None,
        mesh=None,
        device_metrics: bool = False,
        pipeline: bool = False,
    ):
        """``mesh``: a ``jax.sharding.Mesh`` with a ``"data"`` axis enables
        the multi-chip path — rows sharded for fixed effects (whole solver
        inside shard_map, one fused psum per objective evaluation) and the
        entity axis sharded for random effects (the reference's Spark
        executor-parallel layout — SURVEY.md §2 parallelism table).

        ``device_metrics``: per-update train/validation metrics compute ON
        DEVICE (evaluation/device.py) — score arrays never cross to host,
        only metric scalars do (the 1B-row validation contract; the
        reference computes metrics where the data lives).  Requires an
        ungrouped suite; evaluators with no device implementation fall
        back to one host pullback.

        ``pipeline``: overlap coordinate updates' offset-independent
        host work — while one coordinate solves, the NEXT one prestages
        its first pass groups (game/descent.py).  Results are bitwise
        identical to the serial schedule."""
        self.task = losses_lib.get(task).name  # canonicalize aliases
        self.coordinate_configs = dict(coordinate_configs)
        self.device_metrics = device_metrics
        self.n_iterations = n_iterations
        self.logger = logger
        self.mesh = mesh
        self.pipeline = bool(pipeline)

    def build_coordinates(self, shards, ids, response, weight=None, offset=None):
        """Build per-coordinate datasets + coordinate objects once.  Tuning
        loops reuse them across evaluations (mutating ``coord.reg_weight``,
        a traced argument — no recompilation, no dataset rebuild)."""
        return self._build_coordinates(
            self.coordinate_configs, shards, ids, response, weight, offset
        )

    @staticmethod
    def dataset_key(cfg: "CoordinateConfig") -> tuple:
        """Cache key identifying the DATASET a config needs — grid points
        differing only in optimizer/regularization share built datasets (the
        reference builds per-coordinate datasets once, outside the config
        grid — SURVEY.md §3.2)."""
        if isinstance(cfg, FixedEffectCoordinateConfig):
            return (
                "fixed", cfg.feature_shard, cfg.down_sampling_rate,
                cfg.streaming_chunk_rows,
            )
        # Plain and factored random effects need the SAME dataset shape,
        # so they share cache entries deliberately.  The repack knobs
        # change the realized block layout, so they are part of the
        # dataset's identity.
        return (
            "random",
            cfg.feature_shard,
            cfg.entity_key,
            cfg.max_rows_per_entity,
            cfg.bucket_growth,
            cfg.repack,
            cfg.program_budget,
            cfg.repack_seed,
        )

    def _build_coordinates(
        self,
        coordinate_configs,
        shards,
        ids,
        response,
        weight,
        offset,
        dataset_cache: Optional[dict] = None,
    ):
        n = len(response)
        weight = np.ones(n, np.float32) if weight is None else np.asarray(weight, np.float32)
        cache = {} if dataset_cache is None else dataset_cache
        coordinates = []
        for name, cfg in coordinate_configs.items():
            shard = shards[cfg.feature_shard]
            key = self.dataset_key(cfg)
            if isinstance(cfg, FixedEffectCoordinateConfig):
                def train_weight(cfg=cfg):
                    # Down-sampling runs ONLY on cache miss — grid/tuning
                    # points hitting the cache never pay the O(n) pass.
                    if cfg.down_sampling_rate >= 1.0:
                        return weight
                    from photon_ml_tpu.data.sampling import (
                        BinaryClassificationDownSampler,
                        DefaultDownSampler,
                    )

                    binary = self.task in ("logistic", "smoothed_hinge")
                    sampler = (
                        BinaryClassificationDownSampler(cfg.down_sampling_rate)
                        if binary
                        else DefaultDownSampler(cfg.down_sampling_rate)
                    )
                    idx, w_kept = sampler.downsample(response, weight)
                    tw = np.zeros(n, np.float32)
                    tw[idx] = w_kept
                    return tw

                if cfg.streaming_chunk_rows > 0:
                    from photon_ml_tpu.data.streaming import (
                        make_streaming_glm_data,
                    )
                    from photon_ml_tpu.game.streaming import (
                        StreamingFixedEffectCoordinate,
                    )

                    stream = cache.get(key)
                    if stream is None:
                        # With a mesh, chunks are built pre-sharded (one
                        # row block per device) and each objective pass
                        # runs under shard_map with one fused psum —
                        # streamed DP composed with the rest of the
                        # descent (BASELINE config 5's shape: streaming
                        # AND multi-device AND GAME at once).
                        stream = make_streaming_glm_data(
                            shard, response, weights=train_weight(),
                            chunk_rows=cfg.streaming_chunk_rows,
                            n_shards=(
                                1 if self.mesh is None
                                else self.mesh.devices.size
                            ),
                        )
                        cache[key] = stream
                    coordinates.append(StreamingFixedEffectCoordinate(
                        name, stream, self.task, cfg.optimization,
                        cfg.reg_weight, feature_shard=cfg.feature_shard,
                        mesh=self.mesh,
                        prefetch_depth=cfg.prefetch_depth,
                        chunk_fuse=cfg.chunk_fuse,
                        batch_linesearch=cfg.batch_linesearch,
                        compress=cfg.stream_compress,
                        hot_budget_bytes=int(
                            cfg.stream_hot_budget_mb * 1e6
                        ),
                    ))
                    continue
                if self.mesh is not None:
                    coordinates.append(
                        self._distributed_fixed(
                            name, cfg, shard, response, train_weight,
                            cache, key,
                        )
                    )
                    continue
                dataset = cache.get(key)
                if dataset is None:
                    data = make_glm_data(
                        shard, response, weights=train_weight(),
                    )
                    dataset = FixedEffectDataset(data=data, n_global_rows=n)
                    cache[key] = dataset
                coordinates.append(
                    FixedEffectCoordinate(
                        name,
                        dataset,
                        self.task,
                        cfg.optimization,
                        cfg.reg_weight,
                        feature_shard=cfg.feature_shard,
                    )
                )
            else:
                factored = isinstance(cfg, FactoredRandomEffectCoordinateConfig)
                if cfg.device_budget_bytes > 0:
                    # Host-resident dataset, cached separately from the
                    # device-resident one the resident path builds.
                    ooc_key = ("ooc_ds",) + key
                    dataset = cache.get(ooc_key)
                    if dataset is None:
                        dataset = build_random_effect_dataset(
                            ids[cfg.entity_key],
                            shard,
                            np.asarray(response, np.float32),
                            weight,
                            max_rows_per_entity=cfg.max_rows_per_entity,
                            bucket_growth=cfg.bucket_growth,
                            repack=cfg.repack,
                            program_budget=cfg.program_budget,
                            repack_seed=cfg.repack_seed,
                            device=False,
                        )
                        cache[ooc_key] = dataset
                    if factored:
                        from photon_ml_tpu.game.ooc_factored import (
                            OutOfCoreFactoredRandomEffectCoordinate,
                        )

                        coordinates.append(
                            OutOfCoreFactoredRandomEffectCoordinate(
                                name, dataset, self.task, cfg.optimization,
                                rank=cfg.rank, reg_weight=cfg.reg_weight,
                                projection_reg_weight=(
                                    cfg.projection_reg_weight
                                ),
                                alternations=cfg.alternations,
                                feature_shard=cfg.feature_shard,
                                entity_key=cfg.entity_key,
                                device_budget_bytes=cfg.device_budget_bytes,
                                mesh=self.mesh,
                                prefetch_depth=cfg.prefetch_depth,
                            )
                        )
                        continue
                    from photon_ml_tpu.game.ooc_random import (
                        OutOfCoreRandomEffectCoordinate,
                    )

                    coordinates.append(OutOfCoreRandomEffectCoordinate(
                        name, dataset, self.task, cfg.optimization,
                        cfg.reg_weight, feature_shard=cfg.feature_shard,
                        entity_key=cfg.entity_key,
                        device_budget_bytes=cfg.device_budget_bytes,
                        mesh=self.mesh,
                        prefetch_depth=cfg.prefetch_depth,
                        split_factor=cfg.split_factor,
                        hot_budget_bytes=int(cfg.hot_budget_mb * 1e6),
                    ))
                    continue
                if self.mesh is not None:
                    coordinates.append(
                        self._distributed_random(
                            name, cfg, shard, ids, response, weight,
                            cache, key, factored=factored,
                        )
                    )
                    continue
                dataset = cache.get(key)
                if dataset is None:
                    dataset = build_random_effect_dataset(
                        ids[cfg.entity_key],
                        shard,
                        np.asarray(response, np.float32),
                        weight,
                        max_rows_per_entity=cfg.max_rows_per_entity,
                        bucket_growth=cfg.bucket_growth,
                        repack=cfg.repack,
                        program_budget=cfg.program_budget,
                        repack_seed=cfg.repack_seed,
                    )
                    cache[key] = dataset
                if factored:
                    from photon_ml_tpu.game.factored import (
                        FactoredRandomEffectCoordinate,
                    )

                    coordinates.append(
                        FactoredRandomEffectCoordinate(
                            name,
                            dataset,
                            self.task,
                            cfg.optimization,
                            rank=cfg.rank,
                            reg_weight=cfg.reg_weight,
                            projection_reg_weight=cfg.projection_reg_weight,
                            alternations=cfg.alternations,
                            feature_shard=cfg.feature_shard,
                            entity_key=cfg.entity_key,
                        )
                    )
                    continue
                coordinates.append(
                    RandomEffectCoordinate(
                        name,
                        dataset,
                        self.task,
                        cfg.optimization,
                        cfg.reg_weight,
                        feature_shard=cfg.feature_shard,
                        entity_key=cfg.entity_key,
                    )
                )
        return coordinates

    def _distributed_fixed(
        self, name, cfg, shard, response, train_weight_fn, cache, key
    ):
        """Row-sharded fixed effect (mesh path).  Grid points sharing the
        dataset AND optimizer config reuse the sharded data and compiled
        shard_map programs via a shallow copy (reg_weight is traced)."""
        import copy

        from photon_ml_tpu.game.distributed import (
            DistributedFixedEffectCoordinate,
        )

        cache_key = ("dist",) + key
        cached = cache.get(cache_key)
        if cached is not None and cached[0] == cfg.optimization:
            coord = copy.copy(cached[1])
            coord.name = name
            coord.reg_weight = cfg.reg_weight
            return coord
        # The sharded dataset is cached independently of the optimizer
        # config (same pattern as _distributed_random): a config change
        # re-jits but never re-shards/re-uploads the matrix.
        ds_key = ("dist_ds",) + key
        dist = cache.get(ds_key)
        coord = DistributedFixedEffectCoordinate(
            name, shard, np.asarray(response, np.float32), self.mesh,
            self.task, cfg.optimization, cfg.reg_weight,
            feature_shard=cfg.feature_shard,
            # weights (incl. the O(n) down-sampling pass) only matter when
            # the sharded dataset is actually (re)built.
            weights=None if dist is not None else train_weight_fn(),
            dist=dist,
        )
        cache[ds_key] = coord.dist
        cache[cache_key] = (cfg.optimization, coord)
        return coord

    def _distributed_random(
        self, name, cfg, shard, ids, response, weight, cache, key,
        factored: bool = False,
    ):
        """Mesh-sharded random effect — plain or factored; same reuse
        rules as :meth:`_distributed_fixed`.  The plain path routes to
        the hierarchical bucket-ladder coordinate (game/hierarchical.py):
        big buckets split over the mesh, the long tail packs whole onto
        devices.  The factored path keeps the legacy everything-split
        layout (its projection accumulator cannot commit to devices)."""
        import copy

        from photon_ml_tpu.game.distributed import (
            entity_sharded_factored_coordinate,
        )
        from photon_ml_tpu.game.hierarchical import (
            ShardedBucketRandomEffectCoordinate,
        )

        cfg_sig = (
            (cfg.optimization, cfg.rank, cfg.alternations)
            if factored else (cfg.optimization, cfg.split_factor)
        )
        cache_key = ("dist", factored) + key
        cached = cache.get(cache_key)
        if cached is not None and cached[0] == cfg_sig:
            coord = copy.copy(cached[1])
            coord.name = name
            coord.reg_weight = cfg.reg_weight
            if factored:
                coord.projection_reg_weight = (
                    cfg.reg_weight
                    if cfg.projection_reg_weight is None
                    else cfg.projection_reg_weight
                )
            return coord
        # The expensive entity re-grouping is cached independently of the
        # optimizer config; a config change only re-places blocks on the
        # mesh.
        ds_key = ("dist_ds",) + key
        dataset = cache.get(ds_key)
        if dataset is None:
            dataset = build_random_effect_dataset(
                ids[cfg.entity_key],
                shard,
                np.asarray(response, np.float32),
                np.asarray(weight, np.float32),
                max_rows_per_entity=cfg.max_rows_per_entity,
                bucket_growth=cfg.bucket_growth,
                repack=cfg.repack,
                program_budget=cfg.program_budget,
                repack_seed=cfg.repack_seed,
                device=False,  # the coordinate places blocks on the mesh
            )
            cache[ds_key] = dataset
        if factored:
            coord = entity_sharded_factored_coordinate(
                name, dataset, self.mesh, self.task, cfg.optimization,
                rank=cfg.rank, reg_weight=cfg.reg_weight,
                projection_reg_weight=cfg.projection_reg_weight,
                alternations=cfg.alternations,
                feature_shard=cfg.feature_shard,
                entity_key=cfg.entity_key,
            )
        else:
            coord = ShardedBucketRandomEffectCoordinate(
                name, dataset, self.mesh, self.task, cfg.optimization,
                cfg.reg_weight, feature_shard=cfg.feature_shard,
                entity_key=cfg.entity_key,
                split_factor=cfg.split_factor,
            )
        cache[cache_key] = (cfg_sig, coord)
        return coord

    def fit(
        self,
        shards: dict,
        ids: dict,
        response: np.ndarray,
        weight: Optional[np.ndarray] = None,
        offset: Optional[np.ndarray] = None,
        evaluator: Optional[Evaluator] = None,
        validation=None,
        suite=None,
        initial_model: Optional[GameModel] = None,
        checkpointer=None,
        locked_coordinates: Sequence[str] = (),
    ) -> tuple[GameModel, list]:
        """Train; returns (model, per-coordinate-update history).

        ``validation`` is ``(shards, ids, response[, weight[, offset]])``;
        with it, every history entry carries the full validation
        ``EvaluationSuite`` after that coordinate update (the reference's
        per-iteration validation tracking — SURVEY.md §3.2).

        ``initial_model`` warm-starts coordinate descent from a previously
        trained GameModel (the reference's incremental training);
        ``locked_coordinates`` holds named coordinates at that model
        instead of retraining them (the reference's partial retraining);
        ``checkpointer`` enables per-iteration checkpoint + resume (see
        game/descent.py)."""
        coordinates = self._build_coordinates(
            self.coordinate_configs, shards, ids, response, weight, offset
        )
        train_groups = None
        if suite is not None and suite.group_column is not None:
            train_groups = np.asarray(ids[suite.group_column])
        return self.fit_coordinates(
            coordinates, response, weight, offset, evaluator,
            validation=validation, suite=suite,
            initial_model=initial_model, checkpointer=checkpointer,
            train_group_ids=train_groups,
            locked_coordinates=locked_coordinates,
        )

    @staticmethod
    def initial_states_from_model(
        coordinates, model: GameModel
    ) -> dict:
        """Project a saved GameModel onto pre-built coordinates' state
        layout: fixed effects take the coefficient vector directly; random
        effects materialize each bucket's (E, D) local-space matrix from the
        entity→sparse-coefficient table.  Coordinates absent from the model
        start from zero (state None).

        The datasets MUST have been built from data read with the saved
        model's index maps — stored coefficients are matched by global
        column id, so a different index map silently means different
        features.  Width mismatches are caught; same-width re-orderings
        cannot be (exactly as in the reference, where incremental training
        requires the prior run's feature index maps)."""
        states: dict = {}
        for c in coordinates:
            sub = model.models.get(c.name)
            if sub is None:
                continue
            if isinstance(sub, FixedEffectModel):
                w = np.asarray(sub.model.coefficients.means, np.float32)
                # Distributed fixed coordinates have no .dataset; both
                # expose the feature width.
                width = (
                    c.n_features
                    if hasattr(c, "n_features")
                    else c.dataset.data.n_features
                )
                if w.shape[0] != width:
                    raise ValueError(
                        f"initial model coordinate {c.name!r} has "
                        f"{w.shape[0]} features but the dataset has "
                        f"{width}; read the data with the initial model's "
                        "index maps"
                    )
                states[c.name] = jnp.asarray(w)
            elif isinstance(sub, RandomEffectModel):
                from photon_ml_tpu.game.factored import (
                    FactoredRandomEffectCoordinate,
                )

                if isinstance(c, FactoredRandomEffectCoordinate):
                    # A factored coordinate's state is (u_list, V); the
                    # saved model stores only the materialized w_e = V u_e,
                    # and the factorization is not recoverable from it.
                    # Start this coordinate cold (the reference's factored
                    # coordinates likewise don't warm-start from plain
                    # random-effect models).
                    continue
                if sub.n_features != c.dataset.n_features:
                    raise ValueError(
                        f"initial model coordinate {c.name!r} has "
                        f"{sub.n_features} features but the dataset has "
                        f"{c.dataset.n_features}; read the data with the "
                        "initial model's index maps"
                    )
                blocks_states = []
                for block, ids in zip(c.dataset.blocks, c.dataset.entity_ids):
                    cmap = np.asarray(block.col_map)
                    # Entity-sharded blocks are mesh-padded beyond the real
                    # lanes; padding lanes warm-start at zero.
                    mat = np.zeros(cmap.shape, np.float32)
                    mat[: len(ids)] = sub.coefficient_matrix_for(
                        cmap[: len(ids)], ids
                    )
                    blocks_states.append(jnp.asarray(mat))
                states[c.name] = blocks_states
        return states

    def fit_coordinates(
        self,
        coordinates,
        response,
        weight=None,
        offset=None,
        evaluator: Optional[Evaluator] = None,
        validation=None,
        suite=None,
        validation_scorers: Optional[dict] = None,
        initial_model: Optional[GameModel] = None,
        checkpointer=None,
        train_group_ids=None,
        locked_coordinates: Sequence[str] = (),
    ) -> tuple[GameModel, list]:
        """Run coordinate descent over pre-built coordinates (see
        :meth:`build_coordinates`) and finalize the GameModel.

        ``validation_scorers`` (name → scorer, see game/validation.py) lets
        grid/tuning loops reuse scorers built once per shared dataset.

        ``locked_coordinates`` (partial retraining, the reference's locked
        coordinate list): each named coordinate takes its coefficients from
        ``initial_model`` and is never retrained — its scores still enter
        every other coordinate's offsets, and its sub-model is carried into
        the returned GameModel unchanged."""
        from photon_ml_tpu.evaluation.suite import EvaluationSuite

        n = len(response)
        response = np.asarray(response, np.float32)
        base_offsets = (
            np.zeros(n, np.float32) if offset is None else np.asarray(offset, np.float32)
        )
        if suite is None:
            suite = (
                EvaluationSuite.from_specs([evaluator])
                if evaluator is not None
                else EvaluationSuite.for_task(self.task)
            )
        primary = suite.primary_evaluator
        w_host = None if weight is None else np.asarray(weight, np.float32)

        val_ctx = None
        if validation is not None:
            v_shards, v_ids, v_resp = validation[0], validation[1], validation[2]
            v_weight = validation[3] if len(validation) > 3 else None
            v_offset = validation[4] if len(validation) > 4 else None
            scorers = validation_scorers or {
                c.name: c.make_validation_scorer(v_shards, v_ids)
                for c in coordinates
            }
            n_val = len(v_resp)
            # Per-group evaluation (per-query AUC / precision@k): the
            # suite's group column names an id column of the validation set.
            v_groups = None
            if suite.group_column is not None:
                v_groups = np.asarray(v_ids[suite.group_column])
            val_ctx = {
                "scorers": scorers,
                "resp": np.asarray(v_resp, np.float32),
                "groups": v_groups,
                "weight": None if v_weight is None else np.asarray(v_weight, np.float32),
                "base": (
                    np.zeros(n_val, np.float32)
                    if v_offset is None
                    else np.asarray(v_offset, np.float32)
                ),
                # Per-coordinate validation scores, refreshed incrementally:
                # only the just-updated coordinate re-scores each step.
                "scores": {
                    c.name: np.zeros(n_val, np.float32) for c in coordinates
                },
            }

        primed = [False]  # becomes True once every live state has scored

        device_metrics = self.device_metrics
        if device_metrics and (
            suite.group_column is not None or train_group_ids is not None
        ):
            raise ValueError(
                "device_metrics computes GLOBAL metrics; grouped "
                "evaluation (suite group_column="
                f"{suite.group_column!r} / explicit train_group_ids) is "
                "host-side"
            )
        if device_metrics:
            from photon_ml_tpu.evaluation.device import device_evaluator_fn

            # Labels/weights/offsets go to device ONCE; every per-update
            # evaluation then stays device-side and pulls back scalars
            # only — no O(n_rows) transfer per coordinate update.
            resp_dev = jnp.asarray(response)
            w_dev = None if w_host is None else jnp.asarray(w_host)
            base_dev = jnp.asarray(base_offsets)
            primary_dev = device_evaluator_fn(primary)
            if val_ctx is not None:
                val_ctx["resp_dev"] = jnp.asarray(val_ctx["resp"])
                val_ctx["weight_dev"] = (
                    None if val_ctx["weight"] is None
                    else jnp.asarray(val_ctx["weight"])
                )
                val_ctx["base_dev"] = jnp.asarray(val_ctx["base"])
                val_ctx["scores"] = {
                    c.name: jnp.zeros(n_val, jnp.float32)
                    for c in coordinates
                }

        def eval_fn(it, cname, scores, states):
            if device_metrics:
                # CD scores are already device arrays — sum them there.
                # Device metrics stay 0-d DEVICE scalars in the entry:
                # the CD history flush materializes them in its one
                # batched readback (game/descent.py), so an evaluated
                # update costs no extra host round trip here.
                total = base_dev + sum(scores.values())
                train_metric = (
                    primary_dev(total, resp_dev, w_dev)
                    if primary_dev is not None
                    else primary.evaluate(
                        np.asarray(total), response, w_host
                    )
                )
            else:
                total = base_offsets + np.sum(
                    [np.asarray(s) for s in scores.values()], axis=0
                )
                # With a grouped suite, the train metric is grouped too
                # (else history entries would mix global and per-group
                # semantics); a per-group-only primary without train group
                # ids records None rather than crashing training.
                if suite.group_column is not None and train_group_ids is None:
                    train_metric = None
                else:
                    train_metric = primary.evaluate(
                        total, response, w_host, group_ids=train_group_ids
                    )
            entry = {
                "train_metric": train_metric,
                "evaluator": type(primary).__name__,
            }
            if val_ctx is not None:
                keep = (
                    (lambda a: jnp.asarray(a)) if device_metrics
                    else (lambda a: np.asarray(a))
                )
                if not primed[0]:
                    # First evaluation: warm starts / resumed runs carry
                    # live states for coordinates that haven't updated yet
                    # this run — score them all once.
                    for c in coordinates:
                        if states[c.name] is not None:
                            val_ctx["scores"][c.name] = keep(
                                val_ctx["scorers"][c.name].score(
                                    states[c.name]
                                )
                            )
                    primed[0] = True
                else:
                    val_ctx["scores"][cname] = keep(
                        val_ctx["scorers"][cname].score(states[cname])
                    )
                if device_metrics:
                    v_total = val_ctx["base_dev"] + sum(
                        val_ctx["scores"].values()
                    )
                    metrics = suite.evaluate_device(
                        v_total, val_ctx["resp_dev"], val_ctx["weight_dev"],
                        materialize=False,
                    )
                else:
                    v_total = val_ctx["base"] + np.sum(
                        list(val_ctx["scores"].values()), axis=0
                    )
                    metrics = suite.evaluate(
                        v_total, val_ctx["resp"], val_ctx["weight"],
                        group_ids=val_ctx["groups"],
                    )
                entry["validation"] = metrics
                entry["validation_metric"] = metrics[suite.primary]
            return entry

        locked = tuple(locked_coordinates)
        if locked and initial_model is None:
            raise ValueError(
                "locked_coordinates requires initial_model (partial "
                "retraining holds those coordinates at the prior model)"
            )
        if locked:
            missing = [
                n_ for n_ in locked if n_ not in (initial_model.models or {})
            ]
            if missing:
                raise ValueError(
                    f"locked coordinates {missing} are not in the initial "
                    "model"
                )
        initial_states = (
            self.initial_states_from_model(coordinates, initial_model)
            if initial_model is not None
            else None
        )
        unlockable = [
            n_ for n_ in locked
            if initial_states is None or initial_states.get(n_) is None
        ]
        if unlockable:
            # Accurate up-front rejection: a factored coordinate's saved
            # sub-model holds materialized w_e only, so its (u, V) device
            # state is not reconstructible — descent's generic "supply a
            # prior model" message would gaslight a user who already did.
            raise ValueError(
                f"coordinates {unlockable} cannot be locked: their prior "
                "state is not reconstructible from the initial model "
                "(factored coordinates save materialized coefficients "
                "only)"
            )
        cd = CoordinateDescent(coordinates, pipeline=self.pipeline)
        result = cd.run(
            jnp.asarray(base_offsets),
            n_iterations=self.n_iterations,
            eval_fn=eval_fn,
            logger=self.logger,
            checkpointer=checkpointer,
            initial_states=initial_states,
            locked=locked,
        )
        # Finalize with each coordinate's residual offsets (base + the
        # OTHER coordinates' scores) so coefficient variances — when a
        # coordinate's config asks for them — are evaluated at the full
        # final margins.  Skipped entirely (no device readbacks) when no
        # coordinate wants variances.
        def wants_variances(c):
            cfg = getattr(c, "config", None) or getattr(
                getattr(c, "problem", None), "config", None
            )
            return bool(cfg is not None and cfg.compute_variances)

        total_np = None
        if any(wants_variances(c) for c in coordinates):
            total_np = base_offsets + np.sum(
                [np.asarray(s) for s in result.scores.values()], axis=0
            )
        models = {}
        for c in coordinates:
            if c.name in locked:
                # Partial retraining: the locked sub-model passes through
                # VERBATIM (re-deriving it from the reconstructed device
                # state would drop variances and any stored detail).
                models[c.name] = initial_model.models[c.name]
                continue
            off_c = (
                total_np - np.asarray(result.scores[c.name])
                if total_np is not None
                else None
            )
            models[c.name] = c.finalize(result.states[c.name], offsets=off_c)
        return GameModel(models=models, task=self.task), result.history

    def fit_grid(
        self,
        grid_configs: Sequence[dict],
        shards: dict,
        ids: dict,
        response: np.ndarray,
        weight: Optional[np.ndarray] = None,
        offset: Optional[np.ndarray] = None,
        validation=None,
        suite=None,
        initial_model: Optional[GameModel] = None,
        grid_checkpointer=None,
    ) -> tuple[GameModel, list[dict]]:
        """Fit EVERY coordinate-config combination, select best (SURVEY.md
        §3.2: "for each coordinate-config combination ... select best model
        by validation metric").

        ``grid_configs`` is a list of name→config mappings (one grid point
        each, same coordinate names).  Datasets and validation scorers are
        built once per distinct :meth:`dataset_key` and shared across
        points.  Selection: final validation primary metric when
        ``validation`` is given, else final train metric.  Returns
        ``(best_model, point_results)`` where each point result dict carries
        ``configs / model / history / metric``.

        ``grid_checkpointer`` (io.checkpoint.GameGridCheckpointer):
        completed points persist as saved models and are SKIPPED on
        re-entry (retry / --resume), so an interrupted grid resumes at the
        completed-point boundary instead of restarting.
        """
        from photon_ml_tpu.evaluation.suite import EvaluationSuite

        if not grid_configs:
            raise ValueError("empty coordinate-config grid")
        if suite is None:
            suite = EvaluationSuite.for_task(self.task)
        dataset_cache: dict = {}
        scorer_cache: dict = {}
        results: list[dict] = []
        best_idx, best_metric = None, None
        metric_key = (
            "validation_metric" if validation is not None else "train_metric"
        )
        for gi, configs in enumerate(grid_configs):
            loaded = (
                grid_checkpointer.load_point(gi, configs, metric_key)
                if grid_checkpointer is not None else None
            )
            if loaded is not None:
                model, metric, history = loaded
                results.append({
                    "grid_index": gi,
                    "configs": configs,
                    "model": model,
                    "history": history,
                    "metric": metric,
                    "selected_by": metric_key,
                    "resumed": True,
                })
                if best_idx is None or suite.better_than(metric, best_metric):
                    best_idx, best_metric = gi, metric
                if self.logger is not None:
                    self.logger.info(
                        "grid point %d/%d resumed from checkpoint "
                        "(%s = %s)",
                        gi + 1, len(grid_configs), metric_key, metric,
                    )
                continue
            coordinates = self._build_coordinates(
                configs, shards, ids, response, weight, offset,
                dataset_cache=dataset_cache,
            )
            scorers = None
            if validation is not None:
                scorers = {}
                for name, cfg in configs.items():
                    # Fixed-effect scorers depend only on the feature shard
                    # (not on down-sampling, which is train-side only).
                    # Random-effect scorer keys carry the config TYPE:
                    # factored and plain share dataset_key (same dataset)
                    # but their scorers consume different state shapes.
                    key = (
                        ("fixed_scorer", cfg.feature_shard)
                        if isinstance(cfg, FixedEffectCoordinateConfig)
                        else (type(cfg).__name__,) + self.dataset_key(cfg)
                    )
                    if key not in scorer_cache:
                        coord = next(c for c in coordinates if c.name == name)
                        scorer_cache[key] = coord.make_validation_scorer(
                            validation[0], validation[1]
                        )
                    scorers[name] = scorer_cache[key]
            train_groups = None
            if suite.group_column is not None:
                train_groups = np.asarray(ids[suite.group_column])
            model, history = self.fit_coordinates(
                coordinates, response, weight, offset,
                validation=validation, suite=suite,
                validation_scorers=scorers, initial_model=initial_model,
                train_group_ids=train_groups,
            )
            metric = history[-1].get(metric_key) if history else None
            if grid_checkpointer is not None:
                grid_checkpointer.save_point(
                    gi, configs, model, metric, metric_key, history
                )
            results.append(
                {
                    "grid_index": gi,
                    "configs": configs,
                    "model": model,
                    "history": history,
                    "metric": metric,
                    "selected_by": metric_key,
                }
            )
            if best_idx is None or suite.better_than(metric, best_metric):
                best_idx, best_metric = gi, metric
            if self.logger is not None:
                self.logger.info(
                    "grid point %d/%d: %s = %s",
                    gi + 1, len(grid_configs), metric_key, metric,
                )
        for r in results:
            r["best"] = r["grid_index"] == best_idx
        return results[best_idx]["model"], results


@dataclasses.dataclass
class PreparedScoringSet:
    """Grouped block structures for scoring ONE dataset many times.

    Building the per-entity block grouping is the dominant host cost of
    random-effect scoring; ``GameTransformer.prepare`` pays it once and
    every subsequent ``transform`` over the same data reuses it (the
    reference persists its joined scoring RDDs the same way)."""

    n_rows: int
    re_datasets: dict  # coordinate name -> host-side RandomEffectDataset


class GameTransformer:
    """Reference: ``GameTransformer`` — batch scoring with a GameModel
    (SURVEY.md §3.3): fixed effect = one matvec; each random effect = block
    gather of per-entity coefficients; total = sum + offset.

    The scoring math itself lives in ``serving/kernels.py`` — ONE
    implementation shared with the online serving runtime, so batch jobs
    (``game_scoring_driver``) and the request path score through the same
    fixed-effect matvec + random-effect gather + offset sum.

    Scoring is pure host compute (scipy matvec + packed-table gathers):
    uploading scoring shards to the accelerator just to pull scores back
    would waste PCIe/HBM.  Repeated calls on the SAME (shards, ids) objects
    reuse the entity grouping automatically; for explicit control, call
    :meth:`prepare` once and pass ``prepared=`` to every transform."""

    def __init__(self, model: GameModel, logger=None):
        self.model = model
        self.logger = logger
        # (value-identity key, [weakrefs to source arrays], prepared); the
        # weakref callbacks clear the slot when any source array dies, so a
        # long-lived transformer never pins a dead scoring set's blocks.
        self._cache: Optional[tuple] = None

    def prepare(self, shards: dict, ids: dict) -> PreparedScoringSet:
        """Group scoring rows by entity for every random-effect coordinate
        (build once, score many times)."""
        n = next(iter(shards.values())).shape[0]
        re_datasets = {}
        for name, sub in self.model.models.items():
            if isinstance(sub, RandomEffectModel):
                # A file with NO rows carrying this id column yields no
                # ids entry at all — same join-miss semantics as rows
                # individually missing it: zero contribution, not a crash.
                entity_col = ids.get(sub.entity_key)
                if entity_col is None:
                    entity_col = np.full(n, None, object)
                re_datasets[name] = build_random_effect_dataset(
                    np.asarray(entity_col),
                    shards[sub.feature_shard],
                    np.zeros(n, np.float32),
                    np.ones(n, np.float32),
                    device=False,
                    # Scoring join semantics: rows without this entity id
                    # get zero contribution, they are not a data error.
                    allow_missing=True,
                )
        return PreparedScoringSet(n_rows=n, re_datasets=re_datasets)

    @staticmethod
    def _cache_key(shards: dict, ids: dict) -> tuple:
        """Identity of the VALUE objects (not the dicts): replacing a matrix
        or id column inside the same dict objects must miss the cache."""
        return (
            tuple(sorted((name, id(m)) for name, m in shards.items())),
            tuple(sorted((name, id(a)) for name, a in ids.items())),
        )

    def _prepared_for(self, shards: dict, ids: dict) -> PreparedScoringSet:
        import weakref

        key = self._cache_key(shards, ids)
        if self._cache is not None and self._cache[0] == key:
            return self._cache[2]
        prepared = self.prepare(shards, ids)

        def _clear(_ref, _self=weakref.ref(self)):
            t = _self()
            if t is not None:
                t._cache = None

        refs = []
        for obj in list(shards.values()) + list(ids.values()):
            try:
                refs.append(weakref.ref(obj, _clear))
            except TypeError:
                pass  # un-weakref-able value: fall back to identity check
        self._cache = (key, refs, prepared)
        return prepared

    def transform(
        self,
        shards: dict,
        ids: dict,
        offset: Optional[np.ndarray] = None,
        prepared: Optional[PreparedScoringSet] = None,
    ) -> np.ndarray:
        some_shard = next(iter(shards.values()))
        n = some_shard.shape[0]
        if prepared is not None and prepared.n_rows != n:
            raise ValueError(
                f"prepared scoring set covers {prepared.n_rows} rows but "
                f"the shards have {n}; prepare() must be called on the same "
                "data being transformed"
            )
        from photon_ml_tpu.serving import kernels as serving_kernels

        parts = []
        for name, sub in self.model.models.items():
            if isinstance(sub, FixedEffectModel):
                parts.append(serving_kernels.fixed_effect_matvec(
                    shards[sub.feature_shard], sub.model.coefficients.means
                ))
            else:
                if prepared is None:
                    prepared = self._prepared_for(shards, ids)
                parts.append(serving_kernels.random_effect_block_scores(
                    sub, prepared.re_datasets[name]
                ))
        return serving_kernels.sum_margins(n, offset, parts)

    @staticmethod
    def _score_random_effect(model: RandomEffectModel, dataset) -> np.ndarray:
        """Back-compat shim; the implementation moved to
        ``serving.kernels.random_effect_block_scores`` (shared with the
        online runtime)."""
        from photon_ml_tpu.serving import kernels as serving_kernels

        return serving_kernels.random_effect_block_scores(model, dataset)

    def transform_with_mean(self, shards, ids, offset=None) -> np.ndarray:
        """Scores passed through the task's inverse link (probabilities for
        logistic, rates for Poisson)."""
        from photon_ml_tpu.ops import losses as losses_lib

        margins = self.transform(shards, ids, offset)
        return np.asarray(losses_lib.get(self.model.task).mean_fn(jnp.asarray(margins)))
