from photon_ml_tpu.game.data import (  # noqa: F401
    EntityBlock,
    FixedEffectDataset,
    GameData,
    RandomEffectDataset,
    build_random_effect_dataset,
)
from photon_ml_tpu.game.model import (  # noqa: F401
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.game.coordinates import (  # noqa: F401
    Coordinate,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.factored import (  # noqa: F401
    FactoredRandomEffectCoordinate,
)
from photon_ml_tpu.game.descent import CoordinateDescent  # noqa: F401
from photon_ml_tpu.game.estimator import GameEstimator, GameTransformer  # noqa: F401
