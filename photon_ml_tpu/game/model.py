"""GAME model containers.

The analogue of the reference's ``...ml.model`` GAME classes (SURVEY.md §2):
``GameModel`` (container of per-coordinate models; scoring = sum of
coordinate scores), ``FixedEffectModel`` (one coefficient vector, broadcast
in the reference — replicated here), and ``RandomEffectModel`` (per-entity
coefficients, an RDD in the reference — a host-side entity→sparse-coefficient
table here, materialized into dense device blocks when scoring).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from photon_ml_tpu.models.glm import GeneralizedLinearModel


@dataclasses.dataclass
class FixedEffectModel:
    """Reference: ``FixedEffectModel(model, featureShardId)``."""

    model: GeneralizedLinearModel
    feature_shard: str


@dataclasses.dataclass
class RandomEffectModel:
    """Per-entity GLMs over one feature shard.

    ``coefficients`` maps entity key → (global_cols int32[], values float32[])
    with columns sorted ascending — the sparse original-space coefficient
    vector of that entity (the
    reference stores per-entity ``Coefficients`` in projected space and
    carries the projector; storing sparse global-space pairs is equivalent
    and projector-free).  Entities never seen at training time score 0, as
    in the reference.
    """

    coefficients: dict
    feature_shard: str
    entity_key: str
    task: str
    n_features: int
    #: optional per-entity coefficient variances (reference: Bayesian model
    #: output) — entity key → float32[] aligned with that entity's ``cols``.
    variances: Optional[dict] = None
    #: lazily-built packed view for vectorized lookup; the coefficient table
    #: is immutable after training/load, so this never needs invalidation.
    _packed: object = dataclasses.field(
        default=None, repr=False, compare=False
    )

    @property
    def n_entities(self) -> int:
        return len(self.coefficients)

    def _ensure_packed(self):
        """CSR-like packing of the entity→(cols, vals) table enabling ONE
        vectorized lookup across all lanes of a block: entity keys sorted,
        per-entity column segments concatenated, and a combined
        ``entity_rank * (n_features + 1) + col`` key that is GLOBALLY sorted
        (segments are rank-ordered, columns sorted within each segment), so
        a single ``searchsorted`` resolves every (lane, local column) pair."""
        if self._packed is not None:
            return self._packed
        keys = np.asarray(sorted(self.coefficients), dtype=object)
        sizes = np.array(
            [len(self.coefficients[k][0]) for k in keys], np.int64
        )
        starts = np.concatenate([[0], np.cumsum(sizes)])
        total = int(starts[-1])
        cols = np.empty(total, np.int64)
        vals = np.empty(total, np.float32)
        for i, k in enumerate(keys):
            c, v = self.coefficients[k]
            cols[starts[i] : starts[i + 1]] = c
            vals[starts[i] : starts[i + 1]] = v
        stride = self.n_features + 1
        ranks = np.repeat(np.arange(len(keys), dtype=np.int64), sizes)
        combined = ranks * stride + cols
        self._packed = (keys, combined, vals, stride)
        return self._packed

    def coefficient_matrix_for(
        self, col_map: np.ndarray, entity_ids: list
    ) -> np.ndarray:
        """Project stored coefficients into a block's local column layout:
        returns (E, D) with w_local[e, k] = w_e[col_map[e, k]].  Used when
        scoring new data through the block pipeline.  Fully vectorized: one
        ``searchsorted`` over the packed combined-key array covers every
        lane and column at once (no per-entity Python loop)."""
        keys, combined, vals, stride = self._ensure_packed()
        E, D = col_map.shape
        out = np.zeros((E, D), np.float32)
        if len(keys) == 0:
            return out
        lane_keys = np.asarray(entity_ids, dtype=object)
        rank = np.searchsorted(keys, lane_keys)
        rank_c = np.minimum(rank, len(keys) - 1)
        known = keys[rank_c] == lane_keys  # (E,) entity seen at training
        cm = np.asarray(col_map, np.int64)
        q = rank_c[:, None] * stride + cm  # (E, D) combined query keys
        pos = np.searchsorted(combined, q)
        pos_c = np.minimum(pos, len(combined) - 1)
        hit = (
            known[:, None]
            & (cm >= 0)
            & (pos < len(combined))
            & (combined[pos_c] == q)
        )
        out[hit] = vals[pos_c[hit]]
        return out


@dataclasses.dataclass
class GameModel:
    """Reference: ``GameModel`` — ordered per-coordinate models; the overall
    score of a row is the sum of its coordinate scores (plus offset)."""

    models: dict  # coordinate name -> FixedEffectModel | RandomEffectModel
    task: str

    def __getitem__(self, name: str):
        return self.models[name]

    @property
    def coordinate_names(self) -> list[str]:
        return list(self.models)
