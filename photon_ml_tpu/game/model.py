"""GAME model containers.

The analogue of the reference's ``...ml.model`` GAME classes (SURVEY.md §2):
``GameModel`` (container of per-coordinate models; scoring = sum of
coordinate scores), ``FixedEffectModel`` (one coefficient vector, broadcast
in the reference — replicated here), and ``RandomEffectModel`` (per-entity
coefficients, an RDD in the reference — a host-side entity→sparse-coefficient
table here, materialized into dense device blocks when scoring).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from photon_ml_tpu.models.glm import GeneralizedLinearModel


@dataclasses.dataclass
class FixedEffectModel:
    """Reference: ``FixedEffectModel(model, featureShardId)``."""

    model: GeneralizedLinearModel
    feature_shard: str


@dataclasses.dataclass
class RandomEffectModel:
    """Per-entity GLMs over one feature shard.

    ``coefficients`` maps entity key → (global_cols int32[], values float32[])
    with columns sorted ascending — the sparse original-space coefficient
    vector of that entity (the
    reference stores per-entity ``Coefficients`` in projected space and
    carries the projector; storing sparse global-space pairs is equivalent
    and projector-free).  Entities never seen at training time score 0, as
    in the reference.
    """

    coefficients: dict
    feature_shard: str
    entity_key: str
    task: str
    n_features: int

    @property
    def n_entities(self) -> int:
        return len(self.coefficients)

    def coefficient_matrix_for(
        self, col_map: np.ndarray, entity_ids: list
    ) -> np.ndarray:
        """Project stored coefficients into a block's local column layout:
        returns (E, D) with w_local[e, k] = w_e[col_map[e, k]].  Used when
        scoring new data through the block pipeline.  Vectorized per lane via
        searchsorted over the entity's (sorted) coefficient columns."""
        E, D = col_map.shape
        out = np.zeros((E, D), np.float32)
        for lane, key in enumerate(entity_ids):
            entry = self.coefficients.get(key)
            if entry is None or len(entry[0]) == 0:
                continue
            cols, vals = entry  # cols sorted ascending (store invariant)
            cm = col_map[lane]
            pos = np.searchsorted(cols, cm)
            pos_c = np.minimum(pos, len(cols) - 1)
            hit = (cm >= 0) & (pos < len(cols)) & (cols[pos_c] == cm)
            out[lane, hit] = vals[pos_c[hit]]
        return out


@dataclasses.dataclass
class GameModel:
    """Reference: ``GameModel`` — ordered per-coordinate models; the overall
    score of a row is the sum of its coordinate scores (plus offset)."""

    models: dict  # coordinate name -> FixedEffectModel | RandomEffectModel
    task: str

    def __getitem__(self, name: str):
        return self.models[name]

    @property
    def coordinate_names(self) -> list[str]:
        return list(self.models)
