"""Out-of-core GAME: a fixed-effect coordinate over a streamed dataset.

At BASELINE's north-star scale the GAME fixed-effect dataset alone
exceeds one chip's HBM, exactly like the legacy-GLM case
(SURVEY.md §7 "Host→device ingest bandwidth").  This coordinate plugs the
host-RAM chunk store (data/streaming.py) into the block coordinate
descent loop: training is the host-loop L-BFGS over double-buffered
chunk passes with the OTHER coordinates' scores entering as per-chunk
offset slices, and scoring streams ``X @ w`` back per chunk.  The rest
of the descent (random effects, factored effects, validation hooks,
checkpointing) is unchanged — coordinates compose through per-row score
arrays, which stay device-resident and small.

The streamed chunks must be built with ZERO data offsets: in GAME, the
base offsets ride the coordinate-descent total (the estimator seeds it),
so chunk-held offsets would double-count.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.streaming import StreamingGlmData
from photon_ml_tpu.game.coordinates import Coordinate
from photon_ml_tpu.game.model import FixedEffectModel
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.ops import losses as losses_lib
from photon_ml_tpu.optim.lbfgs import LBFGSConfig
from photon_ml_tpu.optim.owlqn import OWLQNConfig
from photon_ml_tpu.optim.problem import GlmOptimizationConfig, OptimizerType
from photon_ml_tpu.optim.streaming import (
    StreamingObjective,
    ensure_streamable,
    streaming_lbfgs_solve,
    streaming_owlqn_solve,
    streaming_tron_solve,
)

Array = jax.Array


class StreamingFixedEffectCoordinate(Coordinate):
    """FixedEffectCoordinate for datasets larger than HBM.

    Drop-in for the resident coordinate inside ``CoordinateDescent``:
    same ``train(offsets, warm) → w`` / ``score(w)`` / ``finalize``
    surface, with every objective evaluation a streamed pass.  All three
    optimizers stream: L-BFGS, OWL-QN (L1/elastic-net), and smooth TRON
    (each CG step one streamed HVP pass).
    """

    def __init__(
        self,
        name: str,
        stream: StreamingGlmData,
        task: str,
        config: GlmOptimizationConfig,
        reg_weight: float = 0.0,
        feature_shard: str = "global",
        accumulate: str = "f32",
        mesh=None,
        prefetch_depth: int = 2,
        chunk_fuse: int = 1,
        batch_linesearch: bool = True,
        compress: str = "off",
        hot_budget_bytes: int = 0,
    ):
        """``chunk_fuse``: chunks folded per device dispatch via
        ``lax.scan`` (single-device only) — amortizes per-dispatch
        overhead when chunks are small.  ``batch_linesearch``: evaluate
        a bracket of line-search candidates per streamed pass (identical
        trial sequence, ~half the passes per solve).

        ``compress`` / ``hot_budget_bytes``: the transfer-avoidance
        knobs — compressed chunk wire formats with on-device dequant,
        and the importance-aware HBM working-set cache (hot chunks skip
        pack + transfer across CD iterations; single-device only).
        Lossless compression and the cache leave every coordinate solve
        bitwise unchanged (see optim/streaming.py).

        ``mesh``: streams each chunk SHARDED over the mesh's first axis
        (chunks must be built with ``n_shards == mesh size``) — streamed
        data parallelism composed with GAME: the per-chunk reduction runs
        under shard_map with one fused psum, and the coordinate-descent
        offsets ride per-chunk as sharded row slices.

        On a multi-process POD, per-row CD state is PROCESS-LOCAL: this
        coordinate's ``train`` offsets and ``score`` output cover THIS
        process's rows (the rows its chunk store holds, built with
        ``n_shards == jax.local_device_count()``), the reference's layout
        of score RDDs partitioned next to the data.  The solve itself is
        global — every objective pass psums over the whole pod — so all
        processes converge on one identical model; compose only with
        coordinates whose per-row surface is also process-local (e.g.
        per-entity random effects whose entities are partitioned to the
        process holding their rows, the reference's hash-partitioner
        invariant), and reduce metrics with a psum or allgather."""
        ensure_streamable(config)
        if mesh is None and stream.n_shards != 1:
            raise ValueError(
                f"stream has n_shards={stream.n_shards}; pass the mesh it "
                "was built for"
            )
        if stream.has_nonzero_offsets():  # cached: free per grid point
            raise ValueError(
                "streamed GAME chunks must carry zero offsets — base "
                "offsets ride the coordinate-descent total"
            )
        self.name = name
        self.stream = stream
        self.task = losses_lib.get(task).name
        self.config = config
        self.reg_weight = reg_weight
        self.feature_shard = feature_shard
        self.batch_linesearch = bool(batch_linesearch)
        self._sobj = StreamingObjective(
            self.task, stream, accumulate=accumulate, mesh=mesh,
            prefetch_depth=prefetch_depth, chunk_fuse=chunk_fuse,
            compress=compress, hot_budget_bytes=hot_budget_bytes,
        )
        opt = config.optimizer
        self._lbfgs = LBFGSConfig(
            max_iters=opt.max_iters,
            tolerance=opt.tolerance,
            history=opt.history,
        )
        self._owlqn = OWLQNConfig(
            max_iters=opt.max_iters,
            tolerance=opt.tolerance,
            history=opt.history,
        )

    @property
    def transfer_stats(self):
        """The underlying stream's h2d observability (data/prefetch.py's
        TransferStats) — per-chunk timing, GB/s, stall counters."""
        return self._sobj.transfer_stats

    @property
    def _l1_frac(self) -> float:
        return self.config.regularization.l1_weight(1.0)

    @property
    def _l2(self) -> float:
        return self.config.regularization.l2_weight(1.0) * self.reg_weight

    def train(self, offsets: Array, warm_state: Optional[Array] = None):
        w0 = (
            jnp.zeros((self.stream.n_features,), jnp.float32)
            if warm_state is None else warm_state
        )
        # Offsets are fixed for the whole solve: slice them per chunk ONCE
        # (value_and_grad accepts the pre-sliced list), not per line-search
        # probe.
        slices = self._sobj.offset_slices(offsets)
        vg = lambda w: self._sobj.value_and_grad(w, self._l2, offsets=slices)
        # Batched line-search trials: one streamed pass evaluates the
        # whole candidate bracket (same trial sequence, fewer passes).
        vgb = (
            (lambda ws: self._sobj.value_and_grad_batch(
                ws, self._l2, offsets=slices
            ))
            if self.batch_linesearch else None
        )
        # Static routing as in problem.solve: any L1 component needs the
        # orthant machinery.
        if (
            self.config.optimizer.optimizer is OptimizerType.OWLQN
            or self._l1_frac > 0.0
        ):
            res = streaming_owlqn_solve(
                vg, w0, self._l1_frac * self.reg_weight, self._owlqn,
                value_and_grad_batch=vgb,
            )
        elif self.config.optimizer.optimizer is OptimizerType.TRON:
            from photon_ml_tpu.optim.tron import TRONConfig

            opt = self.config.optimizer
            res = streaming_tron_solve(
                vg,
                lambda w, v: self._sobj.hvp(
                    w, v, self._l2, offsets=slices
                ),
                w0,
                TRONConfig(
                    max_iters=opt.max_iters, tolerance=opt.tolerance
                ),
            )
        else:
            res = streaming_lbfgs_solve(
                vg, w0, self._lbfgs, value_and_grad_batch=vgb
            )
        return res.w

    def score(self, state: Array) -> Array:
        # Margin WITHOUT offsets: coordinate scores are additive pieces
        # (chunks carry zero offsets by the constructor's contract).
        return jnp.asarray(self._sobj.scores(state))

    def finalize(self, state: Array, offsets=None) -> FixedEffectModel:
        variances = None
        if self.config.compute_variances and offsets is None:
            # Same contract (and warning) as the distributed sibling: the
            # variance Hessian needs the FULL final margins.
            import logging

            logging.getLogger(__name__).warning(
                "compute_variances requested but finalize() got no "
                "offsets; variances omitted for coordinate %r", self.name,
            )
        if self.config.compute_variances and offsets is not None:
            diag = self._sobj.hessian_diagonal(state, offsets=offsets)
            variances = 1.0 / jnp.maximum(diag + self._l2, 1e-12)
        return FixedEffectModel(
            GeneralizedLinearModel(Coefficients(state, variances), self.task),
            self.feature_shard,
        )

    def make_validation_scorer(self, shards: dict, ids: dict):
        from photon_ml_tpu.game.validation import FixedEffectValidationScorer

        return FixedEffectValidationScorer(shards[self.feature_shard])
