"""Per-coordinate validation scoring for coordinate descent.

The reference's ``CoordinateDescent`` evaluates its validation
``EvaluationSuite`` after every coordinate update (SURVEY.md §2
CoordinateDescent, §3.2 loop).  Doing that cheaply requires scoring the
validation set against a coordinate's CURRENT device state without
finalizing a host-side model each step.  These scorers are built ONCE per
(training dataset, validation data) pair:

- ``FixedEffectValidationScorer`` — the validation shard as device
  ``GlmData``; one matvec per evaluation.
- ``RandomEffectValidationScorer`` — the validation rows grouped into entity
  blocks once, plus a host-precomputed STATIC gather map from every
  (validation lane, local column) into a flattened view of the training
  state (the per-bucket ``(E, D)`` coefficient arrays).  Each evaluation is
  then pure device work: flatten state → one ``take`` per validation block →
  batched einsum → scatter-add into the validation row space.  Entities
  unseen at training time (and column misses outside a training entity's
  active subspace) gather from a zero slot, so they score 0 exactly like the
  reference's projector-based scoring of unseen entities/features.

Both scorers are reused verbatim across a config grid when grid points share
the underlying training dataset (the gather map depends only on the training
dataset's entity layout, not on the coefficients).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.data import (
    RandomEffectDataset,
    build_random_effect_dataset,
)

Array = jax.Array


@jax.jit
def _fixed_matvec(features, w):
    return features.matvec(w)


@functools.lru_cache(maxsize=64)
def _re_val_score_jit(n_val: int, layout_sig: tuple):
    """Jitted static-gather validation scorer, memoized on the
    validation row count plus the (val blocks, train state) layout
    signature — the eviction granule (see coordinates._layout_sig) —
    where per-instance jits re-compiled identical programs for every
    scorer (one per coordinate per fit)."""

    def _score(state, blocks, gidxs):
        flat = jnp.concatenate(
            [s.ravel() for s in state] + [jnp.zeros((1,), jnp.float32)]
        )
        total_scores = jnp.zeros((n_val + 1,), jnp.float32)
        for vb, gidx in zip(blocks, gidxs):
            coefs = jnp.take(flat, gidx, axis=0)  # (E_v, D_v)
            s = jnp.einsum("erd,ed->er", vb.X, coefs)
            total_scores = total_scores.at[vb.row_index.ravel()].add(
                s.ravel()
            )
        return total_scores[:n_val]

    return jax.jit(_score)


class FixedEffectValidationScorer:
    """score(w) = X_val @ w on device; built once per validation shard.

    Holds ONLY the feature matrix (scoring never reads labels/weights, and
    only the matvec orientation is needed — no Pallas dual-orientation
    layout, no dummy row arrays)."""

    def __init__(self, val_shard):
        import scipy.sparse as sp

        from photon_ml_tpu.ops.sparse import DenseMatrix, from_scipy_csr

        self.n_rows = val_shard.shape[0]
        if sp.issparse(val_shard):
            self._features = from_scipy_csr(sp.csr_matrix(val_shard))
        else:
            self._features = DenseMatrix(
                jnp.asarray(np.asarray(val_shard), jnp.float32)
            )

    def score(self, state: Array) -> Array:
        return _fixed_matvec(self._features, state)


def _flat_layout(state_shapes: Sequence[tuple[int, int]]):
    """Bucket (E, D) shapes → per-bucket offsets into the flattened state."""
    sizes = [e * d for e, d in state_shapes]
    offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
    return offsets, int(offsets[-1])


class RandomEffectValidationScorer:
    """Static-gather scoring of validation rows against training RE state.

    ``train_dataset`` fixes the entity→(bucket, lane) layout and per-lane
    column maps; ``entity_col``/``val_shard`` are the validation rows.  The
    expensive grouping + gather-map construction happens here, once.
    """

    def __init__(
        self,
        train_dataset: RandomEffectDataset,
        entity_col,
        val_shard,
    ):
        n_val = val_shard.shape[0]
        self.n_rows = n_val
        # Group validation rows by entity (no active-set cap: scoring covers
        # every row).  Labels/weights are irrelevant for scoring.
        val_ds = build_random_effect_dataset(
            entity_col,
            val_shard,
            np.zeros(n_val, np.float32),
            np.ones(n_val, np.float32),
        )
        state_shapes = [
            (b.n_entities, b.block_dim) for b in train_dataset.blocks
        ]
        offsets, total = _flat_layout(state_shapes)
        self._miss = total  # index of the appended zero slot
        d = train_dataset.n_features

        # Flatten every training lane's active columns into ONE globally
        # sorted key table (global_lane_id * d + col — ascending because
        # lanes flatten in order and each lane's cmap holds its sorted
        # active cols first), so each validation block resolves with a
        # single searchsorted instead of a per-lane Python loop (the
        # loop was ~2 s per scorer at 100k entities).
        lane_gid0 = np.concatenate(
            [[0], np.cumsum([e for e, _d in state_shapes])]
        ).astype(np.int64)
        key_parts, pos_parts = [], []
        for tb, b in enumerate(train_dataset.blocks):
            tcmap = np.asarray(b.col_map)  # (E, D) active cols then -1 pad
            lanes, cols = np.nonzero(tcmap >= 0)
            key_parts.append(
                (lane_gid0[tb] + lanes).astype(np.int64) * d + tcmap[lanes, cols]
            )
            # cmap packs actives first, so the column position IS the
            # coefficient's rank in the lane's local space.
            pos_parts.append(
                offsets[tb] + lanes.astype(np.int64) * state_shapes[tb][1]
                + cols
            )
        train_keys = (
            np.concatenate(key_parts) if key_parts
            else np.empty(0, np.int64)
        )
        train_pos = (
            np.concatenate(pos_parts) if pos_parts
            else np.empty(0, np.int64)
        )

        gather_idxs = []
        for vb, vids in zip(val_ds.blocks, val_ds.entity_ids):
            vcmap = np.asarray(vb.col_map)  # (E_v, D_v) global cols, -1 pad
            gid = np.fromiter(
                (
                    -1 if (s := train_dataset.entity_to_slot.get(k)) is None
                    else lane_gid0[s[0]] + s[1]
                    for k in vids
                ),
                np.int64, count=len(vids),
            )
            gidx = np.full(vcmap.shape, self._miss, np.int64)
            valid = (vcmap >= 0) & (gid[:, None] >= 0)
            keys = gid[:, None] * d + vcmap
            if len(train_keys) and valid.any():
                kv = keys[valid]
                ss = np.searchsorted(train_keys, kv)
                hit = (ss < len(train_keys)) & (
                    train_keys[np.minimum(ss, len(train_keys) - 1)] == kv
                )
                flat = gidx[valid]
                flat[hit] = train_pos[ss[hit]]
                gidx[valid] = flat
            gather_idxs.append(jnp.asarray(gidx))

        self._val_blocks = val_ds.blocks
        self._gather_idxs = gather_idxs
        from photon_ml_tpu.game.coordinates import _layout_sig

        self._score_jit = _re_val_score_jit(
            n_val,
            _layout_sig((val_ds.blocks, gather_idxs))
            + tuple(state_shapes),
        )

    def score(self, state: list[Array]) -> Array:
        # A mesh-sharded coordinate leaves blocks committed to different
        # devices (packed vs split placements); jit rejects mixed committed
        # inputs, so stage to one device first.  Transfers preserve bits.
        shardings = {getattr(b, "sharding", None) for b in state}
        if len(shardings) > 1:
            dev = jax.devices()[0]
            state = [jax.device_put(b, dev) for b in state]
        return self._score_jit(state, self._val_blocks, self._gather_idxs)
