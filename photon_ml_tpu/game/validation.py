"""Per-coordinate validation scoring for coordinate descent.

The reference's ``CoordinateDescent`` evaluates its validation
``EvaluationSuite`` after every coordinate update (SURVEY.md §2
CoordinateDescent, §3.2 loop).  Doing that cheaply requires scoring the
validation set against a coordinate's CURRENT device state without
finalizing a host-side model each step.  These scorers are built ONCE per
(training dataset, validation data) pair:

- ``FixedEffectValidationScorer`` — the validation shard as device
  ``GlmData``; one matvec per evaluation.
- ``RandomEffectValidationScorer`` — the validation rows grouped into entity
  blocks once, plus a host-precomputed STATIC gather map from every
  (validation lane, local column) into a flattened view of the training
  state (the per-bucket ``(E, D)`` coefficient arrays).  Each evaluation is
  then pure device work: flatten state → one ``take`` per validation block →
  batched einsum → scatter-add into the validation row space.  Entities
  unseen at training time (and column misses outside a training entity's
  active subspace) gather from a zero slot, so they score 0 exactly like the
  reference's projector-based scoring of unseen entities/features.

Both scorers are reused verbatim across a config grid when grid points share
the underlying training dataset (the gather map depends only on the training
dataset's entity layout, not on the coefficients).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.data import (
    RandomEffectDataset,
    build_random_effect_dataset,
)

Array = jax.Array


@jax.jit
def _fixed_matvec(features, w):
    return features.matvec(w)


@functools.lru_cache(maxsize=32)  # size-keyed: bounded (see coordinates.py)
def _re_val_score_jit(n_val: int):
    """Jitted static-gather validation scorer, memoized on the
    validation row count (per-instance jits re-compiled identical
    programs for every scorer — one per coordinate per fit)."""

    def _score(state, blocks, gidxs):
        flat = jnp.concatenate(
            [s.ravel() for s in state] + [jnp.zeros((1,), jnp.float32)]
        )
        total_scores = jnp.zeros((n_val + 1,), jnp.float32)
        for vb, gidx in zip(blocks, gidxs):
            coefs = jnp.take(flat, gidx, axis=0)  # (E_v, D_v)
            s = jnp.einsum("erd,ed->er", vb.X, coefs)
            total_scores = total_scores.at[vb.row_index.ravel()].add(
                s.ravel()
            )
        return total_scores[:n_val]

    return jax.jit(_score)


class FixedEffectValidationScorer:
    """score(w) = X_val @ w on device; built once per validation shard.

    Holds ONLY the feature matrix (scoring never reads labels/weights, and
    only the matvec orientation is needed — no Pallas dual-orientation
    layout, no dummy row arrays)."""

    def __init__(self, val_shard):
        import scipy.sparse as sp

        from photon_ml_tpu.ops.sparse import DenseMatrix, from_scipy_csr

        self.n_rows = val_shard.shape[0]
        if sp.issparse(val_shard):
            self._features = from_scipy_csr(sp.csr_matrix(val_shard))
        else:
            self._features = DenseMatrix(
                jnp.asarray(np.asarray(val_shard), jnp.float32)
            )

    def score(self, state: Array) -> Array:
        return _fixed_matvec(self._features, state)


def _flat_layout(state_shapes: Sequence[tuple[int, int]]):
    """Bucket (E, D) shapes → per-bucket offsets into the flattened state."""
    sizes = [e * d for e, d in state_shapes]
    offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
    return offsets, int(offsets[-1])


class RandomEffectValidationScorer:
    """Static-gather scoring of validation rows against training RE state.

    ``train_dataset`` fixes the entity→(bucket, lane) layout and per-lane
    column maps; ``entity_col``/``val_shard`` are the validation rows.  The
    expensive grouping + gather-map construction happens here, once.
    """

    def __init__(
        self,
        train_dataset: RandomEffectDataset,
        entity_col,
        val_shard,
    ):
        n_val = val_shard.shape[0]
        self.n_rows = n_val
        # Group validation rows by entity (no active-set cap: scoring covers
        # every row).  Labels/weights are irrelevant for scoring.
        val_ds = build_random_effect_dataset(
            entity_col,
            val_shard,
            np.zeros(n_val, np.float32),
            np.ones(n_val, np.float32),
        )
        state_shapes = [
            (b.n_entities, b.block_dim) for b in train_dataset.blocks
        ]
        offsets, total = _flat_layout(state_shapes)
        self._miss = total  # index of the appended zero slot

        # Host copies of the training col maps (device→host once).
        train_cmaps = [np.asarray(b.col_map) for b in train_dataset.blocks]

        gather_idxs = []
        for vb, vids in zip(val_ds.blocks, val_ds.entity_ids):
            vcmap = np.asarray(vb.col_map)  # (E_v, D_v) global cols, -1 pad
            gidx = np.full(vcmap.shape, self._miss, np.int64)
            for lane, key in enumerate(vids):
                slot = train_dataset.entity_to_slot.get(key)
                if slot is None:
                    continue  # unseen entity → zero slot → score 0
                tb, tl = slot
                tcmap = train_cmaps[tb][tl]  # sorted active cols then -1 pad
                n_active = int(np.sum(tcmap >= 0))
                active = tcmap[:n_active]
                cm = vcmap[lane]
                pos = np.searchsorted(active, cm)
                pos_c = np.minimum(pos, max(n_active - 1, 0))
                hit = (
                    (cm >= 0)
                    & (pos < n_active)
                    & (n_active > 0)
                )
                hit &= np.where(hit, active[pos_c] == cm, False)
                D_t = state_shapes[tb][1]
                gidx[lane, hit] = (
                    offsets[tb] + tl * D_t + pos_c[hit]
                ).astype(np.int64)
            gather_idxs.append(jnp.asarray(gidx))

        self._val_blocks = val_ds.blocks
        self._gather_idxs = gather_idxs
        self._score_jit = _re_val_score_jit(n_val)

    def score(self, state: list[Array]) -> Array:
        return self._score_jit(state, self._val_blocks, self._gather_idxs)
