"""Block coordinate descent over GAME coordinates.

The analogue of the reference's ``CoordinateDescent`` ([CONFIRMED-BASELINE],
SURVEY.md §2, §3.2): iterate the (ordered) coordinate list; train each
coordinate against the *residual* scores of all the others (per-row offsets =
base offsets + sum of other coordinates' scores); refresh that coordinate's
scores; optionally evaluate validation metrics per iteration.

Device-side bookkeeping mirrors the reference's score RDD joins as pure
array updates: ``total`` holds base + Σ coordinate scores, and training
coordinate c uses ``total - scores[c]`` as its offsets — one subtract
instead of an (n-1)-way join.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.game.coordinates import Coordinate


def _optimizer_name(coord) -> Optional[str]:
    """Best-effort optimizer label for a coordinate's solver span (the
    config lives at different depths across coordinate flavors)."""
    cfg = getattr(coord, "config", None)
    if cfg is None:
        cfg = getattr(getattr(coord, "problem", None), "config", None)
    opt = getattr(getattr(cfg, "optimizer", None), "optimizer", None)
    return getattr(opt, "value", None)


def _state_to_device(st):
    """Recursively move a coordinate state (array, list of arrays, or
    nested — e.g. the factored (u_list, V)) onto the device."""
    if st is None:
        return None
    if isinstance(st, (list, tuple)):
        return [_state_to_device(s) for s in st]
    return jnp.asarray(st)

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class _Deferred:
    """Placeholder for a device scalar awaiting the batched flush."""

    index: int
    kind: str  # "f" float, "i" int, "b" bool — per-dtype readback stacks


def _walk_scalars(obj, pred, fn):
    """Map ``fn`` over every leaf matching ``pred`` in nested dicts/lists
    (history entries are plain JSON-ish data plus metric scalars; anything
    else passes through untouched)."""
    if pred(obj):
        return fn(obj)
    if isinstance(obj, dict):
        return {k: _walk_scalars(v, pred, fn) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        vals = [_walk_scalars(v, pred, fn) for v in obj]
        return tuple(vals) if isinstance(obj, tuple) else vals
    return obj


@dataclasses.dataclass
class CoordinateDescentResult:
    states: dict  # coordinate name -> device state
    scores: dict  # coordinate name -> (N,) device scores
    history: list  # per (iteration, coordinate) log entries


class CoordinateDescent:
    """Reference: ``CoordinateDescent.optimize(coordinates, iterations)``.

    ``pipeline=True`` enables the hierarchical-execution overlap
    schedule: before blocking on coordinate c's solve, the NEXT
    coordinate's ``prestage`` hint fires, so its offset-independent host
    work (out-of-core slice packing, warm-start staging) runs during
    c's streamed solve/all-reduce.  The Gauss-Seidel data flow is
    untouched — each coordinate still trains against the residual of
    everything before it, in the same order — so the trajectory is
    bitwise identical to the serial schedule (pinned by
    tests/test_game_hierarchical.py); the overlap achieved lands on the
    ``game_coordinate_overlap_seconds`` counter.
    """

    def __init__(
        self, coordinates: Sequence[Coordinate], pipeline: bool = False
    ):
        names = [c.name for c in coordinates]
        assert len(set(names)) == len(names), f"duplicate coordinate names: {names}"
        self.coordinates = list(coordinates)
        self.pipeline = bool(pipeline)

    def run(
        self,
        base_offsets: Array,
        n_iterations: int = 1,
        eval_fn: Optional[Callable[[int, str, dict, dict], dict]] = None,
        logger=None,
        checkpointer=None,
        initial_states: Optional[dict] = None,
        locked: Sequence[str] = (),
    ) -> CoordinateDescentResult:
        """``eval_fn(iteration, coordinate_name, scores_by_coordinate,
        states_by_coordinate)`` is called after each coordinate update (the
        reference evaluates its validation suite there — states let it score
        a validation set against the freshly-updated coordinate); its dict
        return is recorded in history.

        ``initial_states`` (coordinate name → state) warm-starts from a
        prior model — the reference's "incremental training" (SURVEY.md
        §5.4): each coordinate's scores are seeded from its initial state so
        the first update already trains against the prior model's residuals.

        ``locked`` names coordinates that are PARTIAL-RETRAIN locked (the
        reference's partial retraining: retrain some coordinates against a
        prior model's others): a locked coordinate contributes its initial
        state's scores to every offset but is never retrained — so it must
        appear in ``initial_states`` (or the resumed checkpoint).

        ``checkpointer`` (io/checkpoint.CoordinateDescentCheckpointer)
        persists the loop state after every iteration; when it holds a saved
        state, the run RESUMES from the last completed iteration and
        reproduces the uninterrupted result bit-for-bit (the accumulated
        ``total``/scores are restored, not recomputed)."""
        base_offsets = jnp.asarray(base_offsets, jnp.float32)
        locked = set(locked)
        names = {c.name for c in self.coordinates}
        if not locked <= names:
            raise ValueError(
                f"locked coordinates {sorted(locked - names)} are not in "
                f"this descent's coordinate list {sorted(names)}"
            )
        if names and locked >= names:
            raise ValueError(
                "every coordinate is locked — nothing to train (a fully "
                "locked run would just re-emit the initial model)"
            )
        scores: dict[str, Array] = {
            c.name: jnp.zeros_like(base_offsets) for c in self.coordinates
        }
        states: dict[str, object] = {c.name: None for c in self.coordinates}
        total = base_offsets
        history: list[dict] = []
        start_it = 0

        saved = checkpointer.load() if checkpointer is not None else None
        if saved is not None:
            saved_locked = set(saved.get("locked", []))
            if saved_locked != locked:
                # A resume must train the same coordinates the
                # checkpointed run did — otherwise the finalized model's
                # coordinates were never trained against each other.
                raise ValueError(
                    "checkpoint was written with locked coordinates "
                    f"{sorted(saved_locked)} but this run locks "
                    f"{sorted(locked)}; clear the checkpoint or match "
                    "the locked set"
                )
            # A checkpoint supersedes initial states entirely (it already
            # includes any warm start the original run began from), so don't
            # waste a full scoring pass on states about to be overwritten.
            start_it = saved["iteration"] + 1
            total = jnp.asarray(saved["total"])
            for coord in self.coordinates:
                scores[coord.name] = jnp.asarray(saved["scores"][coord.name])
                states[coord.name] = _state_to_device(
                    saved["states"][coord.name]
                )
            history = list(saved["history"])
            if logger is not None:
                logger.info(
                    "resuming coordinate descent from iteration %d", start_it
                )
        elif initial_states:
            for coord in self.coordinates:
                st = initial_states.get(coord.name)
                if st is None:
                    continue
                st = _state_to_device(st)
                states[coord.name] = st
                s = coord.score(st)
                scores[coord.name] = s
                total = total + s

        # score_norm — and any DEVICE scalar an eval_fn left in its entry
        # (the estimator's device-metrics path returns them unmaterialized
        # for exactly this reason) — stays on device as long as possible:
        # a host readback costs a full transport round trip (~0.1-0.4 s
        # on a tunneled chip — it dominated the CD iteration when taken
        # per update).  Entries and their scalars accumulate in
        # ``pending`` and are flushed in ONE batched readback — per
        # iteration when a logger/checkpointer needs values then (logs
        # must carry them; checkpoints persist history), otherwise once
        # at the END of the run, so the whole multi-iteration loop
        # pipelines on the device with a single host sync.
        pending: list[dict] = []

        def flush():
            if not pending:
                return
            # Floating scalars stack at f64 under x64 so fp64 device
            # metrics (device_auc computes in f64 there) keep full
            # precision — f32→f64 casts are exact.  Int/bool scalars (a
            # user eval_fn recording counts/flags) would corrupt through
            # a float stack; they materialize via HOST-side numpy
            # stacking instead: with x64 off, a device jnp.stack would
            # funnel them through int32 and silently wrap counts above
            # 2^31, while numpy preserves each scalar's own dtype
            # (uint32 counts to 4e9 included).  That costs one readback
            # per int/bool scalar — paid only when one exists; the big
            # float stack keeps the single batched readback.
            x64 = jax.config.jax_enable_x64
            fdt = jnp.float64 if x64 else jnp.float32
            stacks = {"f": [], "i": [], "b": []}

            def grab(a):
                kind = (
                    "f" if jnp.issubdtype(a.dtype, jnp.floating)
                    else "b" if a.dtype == jnp.bool_
                    else "i"
                )
                stack = stacks[kind]
                stack.append(a)
                return _Deferred(len(stack) - 1, kind)

            staged = [
                _walk_scalars(
                    entry,
                    lambda o: isinstance(o, jax.Array) and o.ndim == 0,
                    grab,
                )
                for entry in pending
            ]
            vals = {
                k: (
                    np.asarray(
                        jnp.stack([jnp.asarray(v, fdt) for v in stack])
                    )
                    if k == "f"
                    else np.stack([np.asarray(v) for v in stack])
                )
                for k, stack in stacks.items() if stack
            }
            cast = {"f": float, "i": int, "b": bool}
            for entry, filled in zip(pending, staged):
                done = _walk_scalars(
                    filled,
                    lambda o: isinstance(o, _Deferred),
                    lambda m: cast[m.kind](vals[m.kind][m.index]),
                )
                entry.clear()
                entry.update(done)
                history.append(entry)
                if logger is not None:
                    logger.info(
                        "CD iter %d coordinate %s: %s", entry["iteration"],
                        entry["coordinate"],
                        {k: v for k, v in entry.items()
                         if k not in ("iteration", "coordinate")},
                    )
            pending.clear()

        for name in locked:
            if states[name] is None:
                raise ValueError(
                    f"locked coordinate {name!r} has no state to hold: "
                    "supply it via initial_states (a prior model) or a "
                    "resumed checkpoint"
                )

        tel = telemetry_mod.current()
        flush_per_iteration = logger is not None or checkpointer is not None
        trainable = [
            c for c in self.coordinates if c.name not in locked
        ]
        for it in range(start_it, n_iterations):
            it_t0 = time.perf_counter()
            with tel.span("cd_iteration", iteration=it):
                for ci, coord in enumerate(trainable):
                    offsets = total - scores[coord.name]
                    if self.pipeline and ci + 1 < len(trainable):
                        # Overlap hint: the next coordinate's
                        # offset-independent host packing runs while
                        # this one's solve owns the device/foreground.
                        # Its warm state is untouched by this update
                        # (only states[coord.name] changes below), so
                        # the staged payloads stay valid.
                        nxt = trainable[ci + 1]
                        nxt.prestage(states[nxt.name])
                    upd_t0 = time.perf_counter()
                    # Coordinate/solver spans cover the HOST wall of the
                    # update: real wall for streamed/out-of-core
                    # coordinates (their train blocks per pass), dispatch
                    # wall for resident ones — the batched-flush design
                    # forbids a per-update device sync, so the true
                    # per-iteration wall rides the cd_iteration span /
                    # histogram measured across the flush below.
                    with tel.span(
                        "coordinate", coordinate=coord.name, iteration=it
                    ):
                        with tel.span(
                            "solver",
                            coordinate=coord.name,
                            optimizer=_optimizer_name(coord),
                        ):
                            state = coord.train(
                                offsets, warm_state=states[coord.name]
                            )
                        new_score = coord.score(state)
                    states[coord.name] = state
                    total = offsets + new_score
                    scores[coord.name] = new_score

                    entry = {"iteration": it, "coordinate": coord.name}
                    if eval_fn is not None:
                        entry.update(eval_fn(it, coord.name, scores, states))
                    # The norm is just another deferred floating scalar —
                    # the flush walk materializes it with the metrics.
                    entry["score_norm"] = jnp.linalg.norm(new_score)
                    entry["wall_seconds"] = time.perf_counter() - upd_t0
                    pending.append(entry)
                if flush_per_iteration:
                    flush()
                if checkpointer is not None:
                    checkpointer.save(
                        it, total, scores, states, history,
                        locked=sorted(locked),
                    )
                # The CD outer-iteration boundary (the distributed-CD
                # resume point): iteration ``it`` is complete AND
                # checkpointed; a kill here must resume at it+1
                # bit-identically (docs/robustness.md).
                chaos_mod.maybe_fail("cd.iteration", iteration=it)
            if flush_per_iteration and tel.enabled:
                # The flush materialized device scalars (a real sync), so
                # this iteration wall is achieved wall-clock, not
                # dispatch rate.
                tel.histogram("cd_iteration_seconds").observe(
                    time.perf_counter() - it_t0
                )
        flush()
        return CoordinateDescentResult(states=states, scores=scores, history=history)
