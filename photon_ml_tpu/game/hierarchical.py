"""Hierarchical random-effect execution: the bucket ladder sharded
across mesh devices.

The bucket ladder (game/data.py) turns one random effect into a list of
independent dense blocks; ``RandomEffectCoordinate`` runs them all on one
device, so per-coordinate seconds stay flat no matter how many devices
the mesh has (BENCH_r05: per_user 0.173 s vs fixed 0.119 s).  Per-entity
solves are embarrassingly parallel — Snap ML's nested node/accelerator
hierarchy (PAPERS.md) — so this module distributes the ladder itself:

- **Large buckets split** along the entity axis with the existing
  ``NamedSharding(mesh, P(DATA_AXIS))`` placement
  (game/distributed.py): the vmapped solver is elementwise across
  lanes, so GSPMD partitions it with zero communication.
- **Small buckets pack whole** onto single devices by greedy
  cost-balanced assignment (LPT over padded-FLOP costs): a 4-entity
  bucket sharded 8 ways would pad 2× and pay collective overhead for
  nothing — it runs where it lands, concurrently with its neighbours
  (per-device program dispatch is async, so devices overlap).

Bitwise contract: the plan only changes WHERE each block's program runs,
never the block shapes or the per-bucket math, and the score scatter
re-runs on one device in exactly ``_re_score_all_jit``'s block order —
so sharded results are bit-for-bit the single-device coordinate's (the
parity matrix in tests/test_game_hierarchical.py).  Contrast the
repacker (game/data.py), which changes realized shapes and is therefore
numerically-equivalent-not-bitwise vs the geometric ladder.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.game.coordinates import (
    RandomEffectCoordinate,
    _layout_sig,
    _re_train_all_jit,
)
from photon_ml_tpu.game.data import EntityBlock, RandomEffectDataset
from photon_ml_tpu.game.distributed import (
    DATA_AXIS,
    NamedSharding,
    P,
    _pad_block_entities,
)
from photon_ml_tpu.optim.problem import GlmOptimizationConfig

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class BucketShardPlan:
    """Where each bucket of one random-effect ladder executes.

    ``placements[b]`` is ``("split",)`` — block b's entity axis sharded
    over the whole mesh — or ``("pack", k)`` — block b resident whole on
    device k.  ``imbalance_ratio`` is max/mean padded-FLOP load across
    devices (1.0 = perfectly balanced; the ``game_shard_imbalance_ratio``
    gauge).
    """

    placements: tuple
    n_devices: int
    imbalance_ratio: float

    @property
    def n_split(self) -> int:
        return sum(1 for p in self.placements if p[0] == "split")

    @property
    def n_packed(self) -> int:
        return len(self.placements) - self.n_split


def plan_bucket_shards(
    blocks: list[EntityBlock],
    n_devices: int,
    split_factor: float = 0.5,
) -> BucketShardPlan:
    """Greedy cost-balanced placement of a bucket ladder on ``n_devices``.

    Cost model: padded FLOPs ``E·R·D`` per block (the same objective the
    repacker minimizes).  A block SPLITS across the mesh when its cost
    is at least ``split_factor`` of the ideal per-device share AND it
    has at least one entity lane per device (splitting smaller blocks
    pads more than it parallelizes).  Remaining blocks pack via longest
    processing time: sorted by descending cost (ascending index on
    ties), each onto the currently least-loaded device — deterministic,
    within 4/3 of optimal makespan.  Split blocks load every device
    with cost/n_devices.
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    costs = [
        b.n_entities * b.rows_per_entity * b.block_dim for b in blocks
    ]
    total = sum(costs)
    if not blocks or n_devices == 1 or total == 0:
        return BucketShardPlan(
            placements=tuple(("pack", 0) for _ in blocks),
            n_devices=n_devices,
            imbalance_ratio=1.0,
        )
    ideal = total / n_devices
    loads = np.zeros(n_devices)
    placements: list = [None] * len(blocks)
    packable = []
    for bi, (block, cost) in enumerate(zip(blocks, costs)):
        if cost >= split_factor * ideal and block.n_entities >= n_devices:
            placements[bi] = ("split",)
            loads += cost / n_devices
        else:
            packable.append((cost, bi))
    for cost, bi in sorted(packable, key=lambda t: (-t[0], t[1])):
        k = int(np.argmin(loads))
        placements[bi] = ("pack", k)
        loads[k] += cost
    mean = float(loads.mean())
    imbalance = float(loads.max() / mean) if mean > 0 else 1.0
    return BucketShardPlan(
        placements=tuple(placements),
        n_devices=n_devices,
        imbalance_ratio=imbalance,
    )


@functools.lru_cache(maxsize=64)
def _re_block_scores_jit(layout_sig: tuple):
    """Per-block raw score vectors ``(E, R)`` for a placement group —
    the einsum half of ``_re_score_all_jit``, dispatched on the group's
    home device; the scatter half runs later on ONE device in global
    block order so the accumulation order (and the f32 bits) match the
    single-device program.  Memoized on layout like every other block
    program cache (eviction granule, see ``_layout_sig``)."""

    def _scores(blocks, coefs_list):
        return [
            jnp.einsum("erd,ed->er", b.X, c)
            for b, c in zip(blocks, coefs_list)
        ]

    return jax.jit(_scores)


@functools.lru_cache(maxsize=64)
def _re_scatter_jit(n_rows: int, layout_sig: tuple):
    """The scatter half: per-block (row_index, scores) pairs accumulate
    into one row vector in block order — active then passive per block,
    exactly ``_re_score_all_jit``'s order, so the result is bitwise the
    single-device score."""

    def _scatter(row_indexes, scores):
        total = jnp.zeros((n_rows + 1,), jnp.float32)
        for ri, s in zip(row_indexes, scores):
            total = total.at[ri.ravel()].add(s.ravel())
        return total[:n_rows]

    return jax.jit(_scatter)


class ShardedBucketRandomEffectCoordinate(RandomEffectCoordinate):
    """Random-effect coordinate whose bucket ladder is distributed over a
    mesh by a :class:`BucketShardPlan`.

    Supersedes ``EntityShardedRandomEffectCoordinate`` (which shards
    EVERY block over the whole mesh): the hierarchical plan splits only
    the blocks big enough to amortize it and packs the long tail whole
    onto devices, so small buckets stop paying mesh-wide padding.  State
    layout, ``finalize`` and variances are inherited — the state is
    still one ``(E, D)`` array per block in global block order.
    """

    def __init__(
        self,
        name: str,
        dataset: RandomEffectDataset,
        mesh,
        task: str,
        config: GlmOptimizationConfig,
        reg_weight: float = 0.0,
        feature_shard: str = "global",
        entity_key: str = "",
        split_factor: float = 0.5,
    ):
        devices = list(mesh.devices.flat)
        self.plan = plan_bucket_shards(
            dataset.blocks, len(devices), split_factor=split_factor
        )
        telemetry_mod.current().gauge("game_shard_imbalance_ratio").set(
            self.plan.imbalance_ratio
        )
        sharding = NamedSharding(mesh, P(DATA_AXIS))
        sentinel = dataset.n_global_rows

        def place(block, placement):
            if block is None:
                return None
            if placement[0] == "split":
                padded = _pad_block_entities(
                    block, len(devices), sentinel
                )
                return jax.tree.map(
                    lambda x: jax.device_put(x, sharding), padded
                )
            return jax.tree.map(
                lambda x: jax.device_put(x, devices[placement[1]]), block
            )

        placed = dataclasses.replace(
            dataset,
            blocks=[
                place(b, p)
                for b, p in zip(dataset.blocks, self.plan.placements)
            ],
            passive_blocks=[
                place(b, p)
                for b, p in zip(
                    dataset.passive_blocks, self.plan.placements
                )
            ],
        )
        super().__init__(
            name, placed, task, config, reg_weight,
            feature_shard=feature_shard, entity_key=entity_key,
        )
        self.mesh = mesh
        # Dispatch groups: the split group (one SPMD program over the
        # mesh) plus one group per device holding packed blocks.  Group
        # order is deterministic (split first, then device index) but
        # does not affect results — only the score scatter's BLOCK
        # order matters, and that is global.
        groups: dict = {}
        for bi, p in enumerate(self.plan.placements):
            groups.setdefault(p, []).append(bi)
        self._groups = sorted(
            groups.items(), key=lambda kv: (kv[0][0] != "split", kv[0])
        )
        self._group_train_jits = {
            key: _re_train_all_jit(
                self.task, config,
                _layout_sig([placed.blocks[i] for i in idxs]),
            )
            for key, idxs in self._groups
        }
        # The score scatter is ONE program on a home device, so its
        # inputs must be colocated there.  Row indexes are static —
        # stage them once; per-call score vectors (small: (E, R) f32 vs
        # the (E, R, D) blocks) move at score time.
        self._devices = devices
        self._home = devices[0]

        def home(x):
            return jax.device_put(jnp.asarray(x), devices[0])

        self._home_row_index = [home(b.row_index) for b in placed.blocks]
        self._home_passive_row_index = [
            home(b.row_index) if b is not None else None
            for b in placed.passive_blocks
        ]

    def train(self, offsets: Array, warm_state=None) -> list[Array]:
        l1 = jnp.asarray(
            self.config.regularization.l1_weight(1.0) * self.reg_weight,
            jnp.float32,
        )
        l2 = jnp.asarray(
            self.config.regularization.l2_weight(1.0) * self.reg_weight,
            jnp.float32,
        )
        offsets = jnp.asarray(offsets, jnp.float32)
        # Each dispatch group needs offsets on ITS device set — a
        # committed input pinned elsewhere (the descent's running score
        # array) would clash inside the group jit.  Split groups take a
        # mesh-replicated copy, each packed device its own committed
        # copy; identical bits everywhere, so results never move.
        off_split = jax.device_put(
            offsets, NamedSharding(self.mesh, P())
        )
        off_for = {
            key: (
                off_split
                if key[0] == "split"
                else jax.device_put(offsets, self._devices[key[1]])
            )
            for key, _ in self._groups
        }
        state: list = [None] * len(self.dataset.blocks)
        for key, idxs in self._groups:
            # The per-device dispatch seam: a fault here aborts the
            # update with some groups already in flight; device programs
            # are pure functions of (blocks, offsets, w0), so the
            # retried update is bitwise the uninterrupted one.
            chaos_mod.maybe_fail(
                "game.bucket_shard", placement=key, blocks=len(idxs)
            )
            blocks = [self.dataset.blocks[i] for i in idxs]
            w0s = [
                (
                    warm_state[i]
                    if warm_state is not None
                    else jnp.zeros(
                        (b.n_entities, b.block_dim), jnp.float32
                    )
                )
                for i, b in zip(idxs, blocks)
            ]
            outs = self._group_train_jits[key](
                blocks, off_for[key], w0s, l1, l2
            )
            for i, out in zip(idxs, outs):
                state[i] = out
        return state

    def score(self, state: list[Array]) -> Array:
        # Einsums run on each block's home device (async, concurrent);
        # the scatter-accumulate runs as ONE program in global block
        # order — active then passive per block — matching the
        # single-device ``_re_score_all_jit`` bit for bit.
        per_block_scores: list = [None] * len(self.dataset.blocks)
        per_block_passive: list = [None] * len(self.dataset.blocks)
        for key, idxs in self._groups:
            blocks = [self.dataset.blocks[i] for i in idxs]
            coefs = [state[i] for i in idxs]
            outs = _re_block_scores_jit(_layout_sig(blocks))(
                blocks, coefs
            )
            for i, out in zip(idxs, outs):
                per_block_scores[i] = out
            passive = [
                (i, self.dataset.passive_blocks[i])
                for i in idxs
                if self.dataset.passive_blocks
                and self.dataset.passive_blocks[i] is not None
            ]
            if passive:
                pblocks = [b for _, b in passive]
                pouts = _re_block_scores_jit(_layout_sig(pblocks))(
                    pblocks, [state[i] for i, _ in passive]
                )
                for (i, _), out in zip(passive, pouts):
                    per_block_passive[i] = out
        row_indexes: list = []
        scores: list = []
        for bi in range(len(self.dataset.blocks)):
            row_indexes.append(self._home_row_index[bi])
            scores.append(jax.device_put(per_block_scores[bi], self._home))
            if per_block_passive[bi] is not None:
                row_indexes.append(self._home_passive_row_index[bi])
                scores.append(
                    jax.device_put(per_block_passive[bi], self._home)
                )
        out = _re_scatter_jit(
            self.dataset.n_global_rows,
            _layout_sig(row_indexes),
        )(row_indexes, scores)
        # Hand the score back mesh-replicated: the descent sums it with
        # mesh-placed fixed-effect scores, and a home-device-committed
        # array would clash there.  Pure transfer — bits unchanged.
        return jax.device_put(out, NamedSharding(self.mesh, P()))
