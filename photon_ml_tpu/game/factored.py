"""Factored random effects: per-entity coefficients through a shared
low-rank projection.

The reference's ``FactoredRandomEffectCoordinate`` (SURVEY.md §2, GAME
coordinates row — the older-photon-ml variant, tagged [LOW]; modeled here
from the GLMix matrix-factorization formulation since the reference mount
is unreadable): entity e's coefficient vector is constrained to

    w_e = V u_e        V: (n_features, rank) shared, u_e: (rank,) per entity

so sparse entities borrow statistical strength through V (classic
factorization regularization), and per-entity state is ``rank`` floats
instead of ``n_features``.

Training alternates two convex sub-problems (block coordinate descent
INSIDE this coordinate, mirroring the reference's alternation between the
per-entity problems and the projection fit):

1. **latent step** (V fixed): per-entity GLMs over the projected features
   ``Z = X V`` — exactly the batched bucketed solver used by
   ``RandomEffectCoordinate``, at dimension ``rank``;
2. **projection step** (all u_e fixed): one global GLM over vec(V) with
   margin ``x_rᵀ V u_e`` — value/gradient assembled per bucket with
   einsums (no (n_rows × d·rank) design matrix is ever materialized),
   solved by the on-device L-BFGS.

Both steps run inside ONE jitted program per call (static alternation
count), so a factored coordinate costs one device dispatch per CD update,
like the other coordinates.

``finalize`` materializes ``w_e = V u_e`` into the standard
``RandomEffectModel`` table, so model storage, scoring drivers, and the
transformer treat factored and plain random effects identically.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.coordinates import (
    Coordinate,
    _gather_block_offsets,
    _make_block_solver,
    pack_entity_tables,
)
from photon_ml_tpu.game.data import EntityBlock, RandomEffectDataset
from photon_ml_tpu.game.model import RandomEffectModel
from photon_ml_tpu.ops import losses as losses_lib
from photon_ml_tpu.optim.lbfgs import LBFGSConfig, lbfgs_solve
from photon_ml_tpu.optim.problem import GlmOptimizationConfig

Array = jax.Array


def _gather_v(V: Array, cmap: Array) -> Array:
    """Per-lane rows of V in the block's LOCAL column space: (E, D, rank).
    Padding columns (cmap == -1) read as zero rows."""
    safe = jnp.maximum(cmap, 0)
    vsub = jnp.take(V, safe, axis=0)
    return jnp.where((cmap >= 0)[:, :, None], vsub, 0.0)


def _project_block(block: EntityBlock, V: Array, rank: int) -> EntityBlock:
    """The block with features projected through V: X (E,R,D) → Z (E,R,k)."""
    vsub = _gather_v(V, block.col_map)
    z = jnp.einsum("erd,edk->erk", block.X, vsub)
    # col_map is meaningless in latent space; the solver never reads it.
    return dataclasses.replace(
        block,
        X=z,
        col_map=jnp.zeros((block.n_entities, rank), jnp.int32),
        block_dim=rank,
    )


class FactoredRandomEffectCoordinate(Coordinate):
    """Reference: ``FactoredRandomEffectCoordinate`` — see module docstring.

    State is ``(u_list, V)``: per-bucket latent arrays ``(E, rank)`` plus
    the shared projection ``(n_features, rank)``.
    """

    def __init__(
        self,
        name: str,
        dataset: RandomEffectDataset,
        task: str,
        config: GlmOptimizationConfig,
        rank: int,
        reg_weight: float = 0.0,
        projection_reg_weight: Optional[float] = None,
        alternations: int = 2,
        feature_shard: str = "global",
        entity_key: str = "",
        seed: int = 0,
    ):
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        self.name = name
        self.dataset = dataset
        self.task = losses_lib.get(task).name
        self.config = config
        self.rank = int(rank)
        self.reg_weight = reg_weight
        self.projection_reg_weight = (
            reg_weight if projection_reg_weight is None
            else projection_reg_weight
        )
        self.alternations = int(alternations)
        self.feature_shard = feature_shard
        self.entity_key = entity_key or name
        self._solver = _make_block_solver(task, config)
        loss = losses_lib.get(task)
        n_rows = dataset.n_global_rows
        n_features = dataset.n_features
        rank = self.rank
        opt = config.optimizer
        solver = self._solver
        alternations_n = self.alternations

        # Deterministic non-zero init for V: with U = 0 the projection
        # gradient vanishes (dm ⊗ u = 0), so V must start non-degenerate;
        # the first latent step then populates U against this basis.
        self._v0 = jnp.asarray(
            (
                np.random.default_rng(seed).normal(size=(n_features, rank))
                / np.sqrt(max(rank, 1))
            ).astype(np.float32)
        )

        def projection_value_grad(vflat, blocks, u_list, offsets, l2v):
            """Objective in V with all latents fixed (margins via einsum —
            the (n_rows, d·rank) design matrix is never materialized)."""
            V = vflat.reshape(n_features, rank)
            val = 0.5 * l2v * jnp.vdot(vflat, vflat)
            g = jnp.zeros((n_features + 1, rank), jnp.float32)
            for block, u in zip(blocks, u_list):
                vsub = _gather_v(V, block.col_map)
                off = _gather_block_offsets(offsets, block)
                m = (
                    jnp.einsum("erd,edk,ek->er", block.X, vsub, u)
                    + off.astype(jnp.float32)
                )
                val = val + jnp.sum(
                    block.weights * loss.value(m, block.labels)
                )
                dm = block.weights * loss.d1(m, block.labels)  # (E, R)
                g_local = jnp.einsum(
                    "er,erd,ek->edk", dm, block.X, u
                )  # (E, D, rank)
                idx = jnp.where(
                    block.col_map >= 0, block.col_map, n_features
                )
                g = g.at[idx.reshape(-1)].add(
                    g_local.reshape(-1, rank)
                )
            g = g[:n_features] + l2v * V
            return val, g.reshape(-1)

        def _train_impl(blocks, offsets, u_list, V, l1, l2, l2v):
            offsets = offsets.astype(jnp.float32)
            for _ in range(alternations_n):
                # (1) latent step: bucketed per-entity solves at dim=rank.
                u_list = [
                    solver(
                        _project_block(b, V, rank),
                        _gather_block_offsets(offsets, b),
                        u, l1, l2,
                    )
                    for b, u in zip(blocks, u_list)
                ]
                # (2) projection step: global L-BFGS over vec(V).
                def vg(vflat, u_list=u_list):
                    return projection_value_grad(
                        vflat, blocks, u_list, offsets, l2v
                    )

                V = lbfgs_solve(
                    vg,
                    V.reshape(-1),
                    LBFGSConfig(
                        max_iters=opt.max_iters,
                        tolerance=opt.tolerance,
                        history=opt.history,
                    ),
                ).w.reshape(n_features, rank)
            return u_list, V

        def _score_impl(blocks, passive_blocks, u_list, V):
            total = jnp.zeros((n_rows + 1,), jnp.float32)
            passive = passive_blocks or [None] * len(blocks)
            for block, pblock, u in zip(blocks, passive, u_list):
                s = jnp.einsum(
                    "erd,edk,ek->er",
                    block.X, _gather_v(V, block.col_map), u,
                )
                total = total.at[block.row_index.ravel()].add(s.ravel())
                if pblock is not None:
                    sp_ = jnp.einsum(
                        "erd,edk,ek->er",
                        pblock.X, _gather_v(V, pblock.col_map), u,
                    )
                    total = total.at[pblock.row_index.ravel()].add(
                        sp_.ravel()
                    )
            return total[:n_rows]

        def _materialize_impl(blocks, u_list, V):
            """Dense per-bucket local coefficients w_e = V_sub u_e: the
            shape RandomEffectCoordinate state has, for shared scorers."""
            return [
                jnp.einsum("edk,ek->ed", _gather_v(V, b.col_map), u)
                for b, u in zip(blocks, u_list)
            ]

        self._train_jit = jax.jit(_train_impl)
        self._score_jit = jax.jit(_score_impl)
        self._materialize_jit = jax.jit(_materialize_impl)

    # -- Coordinate protocol ------------------------------------------------
    def train(self, offsets: Array, warm_state=None):
        l1 = jnp.asarray(
            self.config.regularization.l1_weight(1.0) * self.reg_weight,
            jnp.float32,
        )
        l2 = jnp.asarray(
            self.config.regularization.l2_weight(1.0) * self.reg_weight,
            jnp.float32,
        )
        l2v = jnp.asarray(self.projection_reg_weight, jnp.float32)
        if warm_state is None:
            u_list = [
                jnp.zeros((b.n_entities, self.rank), jnp.float32)
                for b in self.dataset.blocks
            ]
            V = self._v0
        else:
            u_list, V = warm_state
        return self._train_jit(
            self.dataset.blocks, jnp.asarray(offsets), u_list, V,
            l1, l2, l2v,
        )

    def score(self, state) -> Array:
        u_list, V = state
        return self._score_jit(
            self.dataset.blocks, self.dataset.passive_blocks, u_list, V
        )

    def materialize(self, state) -> list[Array]:
        """Per-bucket dense local coefficients (RandomEffectCoordinate's
        state shape) — used by validation scorers and finalize."""
        u_list, V = state
        return self._materialize_jit(self.dataset.blocks, u_list, V)

    def finalize(self, state, offsets=None) -> RandomEffectModel:
        return finalize_factored_model(self, state)

    def make_validation_scorer(self, shards: dict, ids: dict):
        from photon_ml_tpu.game.validation import RandomEffectValidationScorer

        inner = RandomEffectValidationScorer(
            self.dataset, ids[self.entity_key], shards[self.feature_shard]
        )
        return _FactoredValidationScorer(self, inner)


def finalize_factored_model(coord, state) -> RandomEffectModel:
    """The one materialized-table builder both the resident and the
    out-of-core factored coordinates share.  Identical storage shape to a
    plain random effect: scoring driver, transformer, and Avro store need
    no factored-specific handling.  Coefficient variances are not defined
    through the factorization (w_e is a deterministic function of the
    joint (U, V) fit), so none are produced — matching the reference,
    which computes variances only for unfactored coordinates."""
    table: dict = {}
    for block, ids, coefs in zip(
        coord.dataset.blocks, coord.dataset.entity_ids,
        coord.materialize(state),
    ):
        col_parts, val_parts, _ = pack_entity_tables(
            np.asarray(block.col_map), np.asarray(coefs)
        )
        for lane, key in enumerate(ids):
            table[key] = (col_parts[lane], val_parts[lane])
    return RandomEffectModel(
        coefficients=table,
        feature_shard=coord.feature_shard,
        entity_key=coord.entity_key,
        task=coord.task,
        n_features=coord.dataset.n_features,
        variances=None,
    )


class _FactoredValidationScorer:
    """Adapts factored (u_list, V) state to the dense-coefficient scorer."""

    def __init__(self, coord: FactoredRandomEffectCoordinate, inner):
        self._coord = coord
        self._inner = inner

    @property
    def n_rows(self) -> int:
        return self._inner.n_rows

    def score(self, state) -> Array:
        return self._inner.score(self._coord.materialize(state))
