"""Out-of-core FACTORED random effects: w_e = V u_e at beyond-HBM scale.

Completes the out-of-core coordinate matrix (game/ooc_random.py covers
plain random effects): the factored coordinate's entity blocks stream
through HBM in the same budget-bounded pass groups, while the two
alternation sub-problems restructure exactly the way the fixed-effect
solvers did when their data went out of core (optim/streaming.py):

1. **latent step** — per-entity solves are independent, so each pass
   group projects its slices through the (device-resident, replicated)
   ``V`` and runs the memoized batched solver at dimension ``rank``;
   latent vectors live in host numpy between passes.
2. **projection step** — the shared-``V`` fit becomes a HOST-LOOP
   L-BFGS (``streaming_lbfgs_solve``, the same outer loop the streamed
   GLM uses) whose every value/gradient evaluation is one streamed pass
   over the groups, accumulating the ``(n_features+1, rank)`` gradient
   on device.

``V`` and its gradient are the only whole-pass-resident device state;
their bytes are carved out of the budget before groups are sized
(``_budget_overhead_bytes``).  State is ``(u_list, V)`` with ``u_list``
host numpy — the factored analogue of the plain OOC coordinate's
host-resident coefficients.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.coordinates import _gather_block_offsets
from photon_ml_tpu.game.data import EntityBlock, RandomEffectDataset
from photon_ml_tpu.game.factored import _gather_v, _project_block
from photon_ml_tpu.game.model import RandomEffectModel
from photon_ml_tpu.game.ooc_random import (
    OutOfCoreRandomEffectCoordinate,
    _cut,
    _slice_block,
)
from photon_ml_tpu.ops import losses as losses_lib
from photon_ml_tpu.optim.lbfgs import LBFGSConfig
from photon_ml_tpu.optim.problem import GlmOptimizationConfig

Array = jax.Array


class OutOfCoreFactoredRandomEffectCoordinate(OutOfCoreRandomEffectCoordinate):
    """FactoredRandomEffectCoordinate for datasets larger than HBM.

    Same ``train(offsets, warm) → (u_list, V)`` / ``score(state)``
    surface as the resident factored coordinate; the same pass-plan,
    double-buffer, and budget machinery as the plain OOC coordinate.
    """

    # The projection step threads ONE device-resident (V, gradient)
    # accumulator through every slice's program — a slice committed to
    # device k would drag that accumulator across devices mid-pass, so
    # this coordinate keeps the legacy everything-split mesh layout.
    _supports_packed = False
    # train/score here stream PROJECTED payloads with their own pack
    # functions — the base class's cached raw-block trees would never
    # be consumed, so the hot working-set cache stays off.
    _supports_hot_cache = False

    def prestage(self, warm_state=None) -> None:
        # The factored train packs PROJECTED latent payloads, not the
        # base class's (block, w0) slices — inherited prestage buffers
        # would never be consumed, so opt out of the hint entirely.
        return None

    def __init__(
        self,
        name: str,
        dataset: RandomEffectDataset,
        task: str,
        config: GlmOptimizationConfig,
        rank: int,
        reg_weight: float = 0.0,
        projection_reg_weight: Optional[float] = None,
        alternations: int = 2,
        feature_shard: str = "global",
        entity_key: str = "",
        device_budget_bytes: int = 256 * 2**20,
        mesh=None,
        seed: int = 0,
        prefetch_depth: int = 2,
    ):
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        # The plan hooks below read these during super().__init__.
        self.rank = int(rank)
        self._n_features = dataset.n_features
        super().__init__(
            name, dataset, task, config, reg_weight=reg_weight,
            feature_shard=feature_shard, entity_key=entity_key,
            device_budget_bytes=device_budget_bytes, mesh=mesh,
            prefetch_depth=prefetch_depth,
        )
        self.projection_reg_weight = (
            reg_weight if projection_reg_weight is None
            else projection_reg_weight
        )
        self.alternations = int(alternations)
        loss = losses_lib.get(self.task)
        rank = self.rank
        n_features = dataset.n_features
        solver = self._solver

        # Same deterministic non-zero V init as the resident coordinate.
        self._v0 = jnp.asarray(
            (
                np.random.default_rng(seed).normal(size=(n_features, rank))
                / np.sqrt(max(rank, 1))
            ).astype(np.float32)
        )

        def _latent_slice(block, V, offsets, u0, l1, l2):
            return solver(
                _project_block(block, V, rank),
                _gather_block_offsets(offsets, block),
                u0, l1, l2,
            )

        def _proj_slice(acc_val, acc_g, block, u, offsets, vflat):
            """One slice's (value, gradient-scatter) contribution to the
            projection objective — accumulated on device."""
            V = vflat.reshape(n_features, rank)
            vsub = _gather_v(V, block.col_map)
            off = _gather_block_offsets(offsets, block)
            m = (
                jnp.einsum("erd,edk,ek->er", block.X, vsub, u)
                + off.astype(jnp.float32)
            )
            acc_val = acc_val + jnp.sum(
                block.weights * loss.value(m, block.labels)
            )
            dm = block.weights * loss.d1(m, block.labels)
            g_local = jnp.einsum("er,erd,ek->edk", dm, block.X, u)
            idx = jnp.where(block.col_map >= 0, block.col_map, n_features)
            acc_g = acc_g.at[idx.reshape(-1)].add(g_local.reshape(-1, rank))
            return acc_val, acc_g

        def _proj_finish(val, g, vflat, l2v):
            V = vflat.reshape(n_features, rank)
            return (
                val + 0.5 * l2v * jnp.vdot(vflat, vflat),
                (g[:n_features] + l2v * V).reshape(-1),
            )

        def _score_slice_f(total, X, col_map, row_index, u, V):
            s = jnp.einsum(
                "erd,edk,ek->er", X, _gather_v(V, col_map), u
            )
            return total.at[row_index.ravel()].add(s.ravel())

        def _materialize_slice(block_cmap, u, V):
            return jnp.einsum("edk,ek->ed", _gather_v(V, block_cmap), u)

        self._latent_jit = jax.jit(_latent_slice)
        self._proj_jit = jax.jit(_proj_slice, donate_argnums=(0, 1))
        self._proj_finish_jit = jax.jit(_proj_finish)
        self._score_f_jit = jax.jit(_score_slice_f, donate_argnums=0)
        self._materialize_jit = jax.jit(_materialize_slice)
        self._lbfgs_cfg = LBFGSConfig(
            max_iters=config.optimizer.max_iters,
            tolerance=config.optimizer.tolerance,
            history=config.optimizer.history,
        )

    # -- plan hooks ---------------------------------------------------------

    def _extra_lane_bytes(self, block: EntityBlock) -> int:
        # Projected features Z (E, R, rank) live next to X during the
        # latent step; latent vectors ride in and out.
        return 4 * (block.rows_per_entity * self.rank + 2 * self.rank)

    def _budget_overhead_bytes(self) -> int:
        # V + its gradient accumulator, replicated and whole-pass-resident.
        return 2 * 4 * (self._n_features + 1) * self.rank

    # -- coordinate surface -------------------------------------------------

    def train(self, offsets: Array, warm_state=None):
        l1 = jnp.asarray(
            self.config.regularization.l1_weight(1.0) * self.reg_weight,
            jnp.float32,
        )
        l2 = jnp.asarray(
            self.config.regularization.l2_weight(1.0) * self.reg_weight,
            jnp.float32,
        )
        l2v = jnp.asarray(self.projection_reg_weight, jnp.float32)
        offsets = jnp.asarray(offsets, jnp.float32)
        sentinel = self.dataset.n_global_rows
        if warm_state is None:
            u_list = [
                np.zeros((b.n_entities, self.rank), np.float32)
                for b in self.dataset.blocks
            ]
            V = self._v0
        else:
            u_warm, V = warm_state
            u_list = [np.array(u, np.float32) for u in u_warm]
            V = jnp.asarray(V, jnp.float32)

        def host_group(group):
            # One slicer for BOTH passes: the latent step reads u as its
            # warm start, the projection step as the fixed latents.
            out = []
            for s in group:
                out.append((
                    _slice_block(
                        self.dataset.blocks[s.block_idx],
                        s.lane_lo, s.lane_hi, s.padded_e, sentinel,
                    ),
                    _cut(
                        u_list[s.block_idx], s.lane_lo, s.lane_hi,
                        s.padded_e, 0,
                    ),
                ))
            return out

        from photon_ml_tpu.optim.streaming import streaming_lbfgs_solve

        for _ in range(self.alternations):
            # (1) latent step: one streamed pass, u host-resident between.
            V_dev = V

            def consume_latent(group, dev):
                results = [
                    self._latent_jit(blk, V_dev, offsets, u0, l1, l2)
                    for blk, u0 in dev
                ]
                for s, res in zip(group, results):
                    u_list[s.block_idx][s.lane_lo:s.lane_hi] = np.asarray(
                        res
                    )[: s.lane_hi - s.lane_lo]

            self._run_groups(host_group, consume_latent)

            # (2) projection step: host-loop L-BFGS; every evaluation is
            # one streamed pass accumulating (val, grad) on device.
            def vg(vflat):
                import collections

                acc = [
                    jnp.zeros((), jnp.float32),
                    jnp.zeros(
                        (self._n_features + 1, self.rank), jnp.float32
                    ),
                ]
                # Windowed carry sync (optim/streaming.py's discipline):
                # run up to prefetch_depth dispatched-but-unexecuted
                # group programs ahead, then block on the value scalar a
                # window behind — keeps the device fed through each
                # group's Python dispatch while bounding live group
                # buffers (the device_budget contract) instead of
                # letting the dispatch queue pin arbitrarily many.
                window = 0 if self.prefetch_depth == 1 else (
                    self.prefetch_depth
                )
                ring: collections.deque = collections.deque()

                def consume(group, dev):
                    for blk, u in dev:
                        acc[0], acc[1] = self._proj_jit(
                            acc[0], acc[1], blk, u, offsets, vflat
                        )
                    ring.append(acc[0])
                    if len(ring) > window:
                        jax.block_until_ready(ring.popleft())

                self._run_groups(host_group, consume)
                ring.clear()
                return self._proj_finish_jit(acc[0], acc[1], vflat, l2v)

            V = streaming_lbfgs_solve(
                vg, V.reshape(-1), self._lbfgs_cfg
            ).w.reshape(self._n_features, self.rank)
        return u_list, V

    def score(self, state) -> Array:
        u_list, V = state
        V = jnp.asarray(V, jnp.float32)
        sentinel = self.dataset.n_global_rows
        total = self._zeros_jit()

        def host_group(group):
            out = []
            for s in group:
                u = _cut(
                    np.asarray(u_list[s.block_idx], np.float32),
                    s.lane_lo, s.lane_hi, s.padded_e, 0,
                )
                block = self.dataset.blocks[s.block_idx]
                active = (
                    _cut(block.X, s.lane_lo, s.lane_hi, s.padded_e, 0),
                    _cut(block.col_map, s.lane_lo, s.lane_hi,
                         s.padded_e, -1),
                    _cut(block.row_index, s.lane_lo, s.lane_hi,
                         s.padded_e, sentinel),
                )
                passive = None
                if self.dataset.passive_blocks:
                    pb = self.dataset.passive_blocks[s.block_idx]
                    if pb is not None:
                        passive = (
                            _cut(pb.X, s.lane_lo, s.lane_hi, s.padded_e, 0),
                            _cut(pb.col_map, s.lane_lo, s.lane_hi,
                                 s.padded_e, -1),
                            _cut(pb.row_index, s.lane_lo, s.lane_hi,
                                 s.padded_e, sentinel),
                        )
                out.append((active, passive, u))
            return out

        def consume(_group, dev):
            nonlocal total
            for active, passive, u in dev:
                total = self._score_f_jit(total, *active, u, V)
                if passive is not None:
                    total = self._score_f_jit(total, *passive, u, V)

        self._run_groups(host_group, consume)
        return total[: self.dataset.n_global_rows]

    def materialize(self, state) -> list[np.ndarray]:
        """Per-bucket dense local coefficients, computed slice-wise so
        no whole block rides to the device (validation scorers and
        finalize share this)."""
        u_list, V = state
        V = jnp.asarray(V, jnp.float32)
        out = [
            np.zeros((b.n_entities, b.block_dim), np.float32)
            for b in self.dataset.blocks
        ]
        for group in self.pass_plan:
            for s in group:
                block = self.dataset.blocks[s.block_idx]
                cmap = self._put(_cut(
                    block.col_map, s.lane_lo, s.lane_hi, s.padded_e, -1
                ))
                u = self._put(_cut(
                    np.asarray(u_list[s.block_idx], np.float32),
                    s.lane_lo, s.lane_hi, s.padded_e, 0,
                ))
                w = self._materialize_jit(cmap, u, V)
                out[s.block_idx][s.lane_lo:s.lane_hi] = np.asarray(
                    w
                )[: s.lane_hi - s.lane_lo]
        return out

    def finalize(self, state, offsets=None) -> RandomEffectModel:
        from photon_ml_tpu.game.factored import finalize_factored_model

        return finalize_factored_model(self, state)

    def make_validation_scorer(self, shards: dict, ids: dict):
        from photon_ml_tpu.game.factored import _FactoredValidationScorer
        from photon_ml_tpu.game.validation import RandomEffectValidationScorer

        inner = RandomEffectValidationScorer(
            self.dataset, ids[self.entity_key], shards[self.feature_shard]
        )
        # The resident adapter only needs coord.materialize(state).
        return _FactoredValidationScorer(self, inner)
