"""GAME coordinates: per-effect training and scoring units.

The analogue of the reference's ``...ml.algorithm`` coordinates
([CONFIRMED-BASELINE], SURVEY.md §2, §3.2):

- ``FixedEffectCoordinate`` — one distributed GLM fit over all rows (the
  stage-3.1 solver with per-row offsets from the other coordinates);
- ``RandomEffectCoordinate`` — millions of independent per-entity GLM fits.
  The reference runs them inside Spark ``mapPartitions`` (executor-local
  L-BFGS per entity, zero communication — SURVEY.md §3.2); here each
  size-bucket block solves as ONE ``vmap``'d L-BFGS/OWL-QN ``while_loop``
  over its entity lanes, one jitted program per block shape.  Converged
  lanes freeze (lax batching selects old carries), so ragged per-entity
  convergence inside a batch is handled by construction.

Coordinates hold their (device-resident) datasets — the analogue of the
reference persisting per-coordinate RDDs — and expose
``train(offsets, warm) → state`` / ``score(state) → per-row scores``,
mirroring the reference's ``Coordinate.trainModel`` / ``score``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.dataset import GlmData
from photon_ml_tpu.game.data import (
    EntityBlock,
    FixedEffectDataset,
    RandomEffectDataset,
)
from photon_ml_tpu.game.model import FixedEffectModel, RandomEffectModel
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.ops import losses as losses_lib
from photon_ml_tpu.optim.lbfgs import LBFGSConfig, lbfgs_solve
from photon_ml_tpu.optim.owlqn import OWLQNConfig, owlqn_solve
from photon_ml_tpu.optim.problem import GlmOptimizationConfig, OptimizerType

Array = jax.Array


class Coordinate:
    """Protocol: train against offsets, score into the global row space."""

    name: str

    def train(self, offsets: Array, warm_state=None):
        raise NotImplementedError

    def prestage(self, warm_state=None) -> None:
        """Hint that ``train(..., warm_state)`` is about to be called.

        The pipelined descent schedule (game/descent.py) issues this for
        the NEXT coordinate before blocking on the current one's solve:
        work that does not depend on the offsets — host-side slice
        packing, warm-start staging — may start in the background.  The
        contract is strictly a latency hint: results must stay bitwise
        identical whether or not prestage ran, so the default is a
        no-op and implementations must key any staged buffers to the
        exact ``warm_state`` they were built from."""
        return None

    def score(self, state) -> Array:
        raise NotImplementedError

    def finalize(self, state, offsets=None):
        """Turn device state into the host-side model object.

        ``offsets`` are this coordinate's final residual offsets (base +
        the other coordinates' scores) — required for coefficient-variance
        computation, whose Hessian must be evaluated at the full final
        margins, not this coordinate's margins alone."""
        raise NotImplementedError

    def make_validation_scorer(self, shards: dict, ids: dict):
        """Build a reusable validation scorer for this coordinate (see
        game/validation.py) from raw validation columns."""
        raise NotImplementedError


def _layout_sig(tree) -> tuple:
    """Hashable shape/dtype signature of a pytree of arrays.  Program
    caches key on it purely as an EVICTION GRANULE: ``jax.jit`` retraces
    per shape signature anyway, but without the sig in the lru key one
    shared wrapper would accumulate an executable per distinct dataset
    layout for process lifetime — keying (and bounding) on the layout
    lets old layouts' compiled programs be dropped with their entry."""
    return tuple(
        (tuple(leaf.shape), str(leaf.dtype))
        for leaf in jax.tree.leaves(tree)
    )


@functools.lru_cache(maxsize=64)
def _fixed_effect_jits(
    task: str, config: GlmOptimizationConfig, axis_name: Optional[str],
    data_sig: tuple,
):
    """Jitted (train, score) programs for a fixed-effect coordinate,
    memoized PROCESS-WIDE on (task, config, axis_name) plus the
    dataset's layout signature, like ``_make_block_solver``: per-instance
    ``jax.jit`` closures meant every new coordinate object — a second
    ``fit``, every ``fit_grid`` point, a fresh estimator in the same
    process — re-traced and re-COMPILED identical programs (~3 s each on
    the chip, 41 of 72 s of a repeat flagship fit)."""
    from photon_ml_tpu.optim.problem import GlmOptimizationProblem

    problem = GlmOptimizationProblem(task, config)

    # Dataset AND reg_weight are jit ARGUMENTS (not closure constants):
    # closures bake them into the HLO, forcing recompiles per dataset /
    # per tuning point and oversized programs.  Hyperparameter tuning
    # mutates reg_weight between runs at zero recompile cost.
    def _train(data: GlmData, offsets: Array, w0: Array, reg_weight: Array):
        data = dataclasses.replace(data, offsets=offsets)
        return problem.solve(data, reg_weight, w0, axis_name=axis_name).w

    def _score(data: GlmData, w: Array) -> Array:
        # Margin WITHOUT offsets: coordinate scores are additive pieces.
        return data.features.matvec(w)

    return jax.jit(_train), jax.jit(_score)


class FixedEffectCoordinate(Coordinate):
    """Reference: ``FixedEffectCoordinate`` — DistributedOptimizationProblem
    over the full dataset (SURVEY.md §3.2)."""

    def __init__(
        self,
        name: str,
        dataset: FixedEffectDataset,
        task: str,
        config: GlmOptimizationConfig,
        reg_weight: float = 0.0,
        feature_shard: str = "global",
        axis_name: Optional[str] = None,
    ):
        from photon_ml_tpu.optim.problem import GlmOptimizationProblem

        self.name = name
        self.dataset = dataset
        self.task = losses_lib.get(task).name
        self.problem = GlmOptimizationProblem(task, config)
        self.reg_weight = reg_weight
        self.feature_shard = feature_shard
        self.axis_name = axis_name
        self._sharded_trainer = None
        solver_name = getattr(config.optimizer, "solver", None)
        if solver_name is not None:
            from photon_ml_tpu.solvers import registry as solver_registry

            if solver_registry.get(solver_name).kind == "host":
                # Host-kind solvers (ADMM, block CD) distribute this
                # coordinate's solve over logical row shards; per-GAME-
                # iteration offsets re-slot into one shard template so
                # the compiled step program is reused across iterations.
                from photon_ml_tpu.solvers import sharded as solvers_sharded

                if axis_name is not None:
                    raise ValueError(
                        f"solver {solver_name!r} manages its own mesh "
                        "collectives; it cannot nest inside an existing "
                        f"axis {axis_name!r} (drop data-parallel GAME or "
                        "the solver override)"
                    )
                self._sharded_trainer = solvers_sharded.make_fixed_effect_trainer(
                    self.problem,
                    dataset.data,
                    solvers_sharded.resolve_shard_count(config.optimizer),
                )
        self._train_jit, self._score_jit = _fixed_effect_jits(
            self.task, config, axis_name, _layout_sig(dataset.data)
        )

    def train(self, offsets: Array, warm_state: Optional[Array] = None) -> Array:
        w0 = (
            jnp.zeros((self.dataset.data.n_features,), jnp.float32)
            if warm_state is None
            else warm_state
        )
        if self._sharded_trainer is not None:
            return self._sharded_trainer(offsets, w0, self.reg_weight)
        return self._train_jit(
            self.dataset.data, offsets, w0,
            jnp.asarray(self.reg_weight, jnp.float32),
        )

    def score(self, state: Array) -> Array:
        return self._score_jit(self.dataset.data, state)

    def finalize(self, state: Array, offsets=None) -> FixedEffectModel:
        variances = None
        if self.problem.config.compute_variances and offsets is not None:
            data = dataclasses.replace(
                self.dataset.data, offsets=jnp.asarray(offsets, jnp.float32)
            )
            variances = self.problem.coefficient_variances(
                state, data, self.reg_weight
            )
        return FixedEffectModel(
            GeneralizedLinearModel(Coefficients(state, variances), self.task),
            self.feature_shard,
        )

    def make_validation_scorer(self, shards: dict, ids: dict):
        from photon_ml_tpu.game.validation import FixedEffectValidationScorer

        return FixedEffectValidationScorer(shards[self.feature_shard])


def _make_block_solver(task: str, config: GlmOptimizationConfig):
    """Canonicalize the task name before the cache lookup: raw aliases
    ("logistic_regression") and the canonical name ("logistic") must hit
    ONE cache entry, or every bucket shape compiles twice."""
    return _make_block_solver_cached(losses_lib.get(task).name, config)


@functools.lru_cache(maxsize=None)
def _make_block_solver_cached(task: str, config: GlmOptimizationConfig):
    """Build a jitted (block, offsets, w0, l1, l2) → (E, D) batched solver.

    Optimizer dispatch: any L1 component (static on the regularization
    TYPE) routes to OWL-QN.  SMOOTH problems prefer an exact fast path
    when one exists for the block shape — rank-1 Newton (R == 1), scalar
    Newton (D == 1), or batched damped Newton (D <= 32) — regardless of
    whether the config names L-BFGS or TRON: these solve the identical
    regularized objective to the identical stationary point, the config's
    optimizer choice only governs HOW, and the fast paths are 2-13x
    cheaper on TPU (per-entity problems this small are sequential-step-
    bound).  Only blocks with no fast path (D > 32) run the configured
    L-BFGS/TRON machinery.  l1/l2 are traced scalars so tuning sweeps
    don't recompile.  Memoized on (task, config) —
    both hashable — so every coordinate/grid point with the same optimizer
    setup shares ONE jit cache (one compile per block shape process-wide).
    """
    from photon_ml_tpu.optim.tron import TRONConfig, tron_solve

    loss = losses_lib.get(task)
    opt = config.optimizer
    has_l1 = config.regularization.l1_weight(1.0) > 0.0
    if getattr(opt, "solver", None) is not None:
        # Registry dispatch for an explicit solver name.  Random-effect
        # blocks are batched per-entity traced solves, so only jit-kind
        # solvers apply here (host-kind ADMM/block-CD distribute the
        # FIXED-effect coordinate — see FixedEffectCoordinate).
        from photon_ml_tpu.solvers import registry as solver_registry

        defn = solver_registry.resolve(
            opt, l1_frac=config.regularization.l1_weight(1.0)
        )
        if defn.kind != "jit":
            raise ValueError(
                f"solver {defn.name!r} is host-kind and cannot run the "
                "per-entity random-effect blocks; set it on the "
                "fixed-effect coordinate's spec instead"
            )
        use_owlqn = defn.name == "owlqn" or has_l1
        use_tron = defn.name == "tron"
    else:
        use_owlqn = opt.optimizer is OptimizerType.OWLQN or has_l1
        use_tron = opt.optimizer is OptimizerType.TRON

    def rank1_newton(block, offsets_block, w0, l2):
        """Single-row entities (R == 1 — the LARGEST bucket class in
        long-tailed data) have a closed structure: the stationarity
        condition ℓ'(m)·x + λw = 0 forces w ∝ x, so the whole per-entity
        GLM collapses to a 1-D problem in α (w = α·x).  A few damped Newton
        steps replace the full vmapped L-BFGS machinery — ~30 sequential
        device ops instead of hundreds (the while_loop step count, not
        FLOPs, dominates these buckets).  Smooth objectives only (L1 breaks
        the proportionality)."""
        X = block.X[:, 0, :]                       # (E, D)
        y = block.labels[:, 0]
        wt = block.weights[:, 0]
        off = offsets_block[:, 0].astype(X.dtype)  # robust under x64 callers
        s = jnp.sum(X * X, axis=1)                 # (E,) = ‖x‖²
        safe_s = jnp.maximum(s, 1e-12)
        alpha = jnp.sum(w0 * X, axis=1) / safe_s   # warm start projection
        # Margin-change clamp: Δmargin = Δα·s, so |Δα| ≤ 20/s bounds each
        # step's margin movement at 20 — keeps the undamped Newton step sane
        # when the curvature flattens (λ = 0, saturated logistic / large
        # Poisson counts) without capping total movement (12 × 20 margins).
        clip = 20.0 / safe_s

        def grad_at(alpha):
            m = alpha * s + off
            return m, s * (wt * loss.d1(m, y) + l2 * alpha)

        _, g0 = grad_at(alpha)
        gtol = opt.tolerance * jnp.maximum(1.0, jnp.abs(g0))
        done0 = (jnp.abs(g0) <= gtol) | (s <= 0)

        def cond(carry):
            i, _alpha, done = carry
            return (i < 30) & ~jnp.all(done)

        def body(carry):
            i, alpha, done = carry
            m, g1 = grad_at(alpha)
            done = done | (jnp.abs(g1) <= gtol)
            g2 = wt * loss.d2(m, y) * s * s + l2 * s
            step = g1 / jnp.maximum(g2, 1e-12)
            step = jnp.clip(step, -clip, clip)
            alpha = alpha - jnp.where(done, 0.0, step)
            return i + 1, alpha, done

        # Up to 30 damped steps with a per-lane relative-gradient exit
        # (newton_block's test, seeded so lanes converged at entry run
        # zero bodies): exp-family losses can overshoot to the clamp
        # ceiling then crawl back ~1 margin-unit per Newton step (a huge
        # Poisson count), so the cap must stay high — but warm-started CD
        # iterations converge every lane in 1-3 steps, and sequential
        # step count is what these buckets are bound by.
        _, alpha, _ = jax.lax.while_loop(
            cond, body, (jnp.zeros((), jnp.int32), alpha, done0)
        )
        return alpha[:, None] * X

    def dim1_newton(block, offsets_block, w0, l2):
        """Single-FEATURE entities (D == 1 — the reference's flagship
        GAME shape: a per-entity bias/intercept random effect, e.g.
        MovieLens per-user) are a 1-D problem in w regardless of row
        count: damped scalar Newton replaces the vmapped L-BFGS
        machinery, as rank1_newton does for R == 1.  Smooth objectives
        only."""
        X = block.X[:, :, 0]                       # (E, R)
        y = block.labels
        wt = block.weights
        off = offsets_block.astype(X.dtype)
        w = w0[:, 0]                               # (E,)
        # Margin-change clamp: |Δw|·max|x| ≤ 20 per step (same damping
        # rationale as rank1_newton's).
        xmax = jnp.max(jnp.abs(X), axis=1)
        clip = 20.0 / jnp.maximum(xmax, 1e-12)

        def grad_at(w):
            m = w[:, None] * X + off
            return m, jnp.sum(wt * loss.d1(m, y) * X, axis=1) + l2 * w

        _, g0 = grad_at(w)
        gtol = opt.tolerance * jnp.maximum(1.0, jnp.abs(g0))

        def cond(carry):
            i, _w, done = carry
            return (i < 30) & ~jnp.all(done)

        def body(carry):
            i, w, done = carry
            m, g = grad_at(w)
            done = done | (jnp.abs(g) <= gtol)
            h = jnp.sum(wt * loss.d2(m, y) * X * X, axis=1) + l2
            # All-zero-feature lanes (padding, degenerate entities) need
            # no special case: g = l2·w, h = l2 → one exact step to the
            # regularized solution w = 0 (and with l2 = 0 the step is 0/ε
            # = 0, leaving w unchanged — same stationary point the
            # generic solver reports).
            step = jnp.clip(g / jnp.maximum(h, 1e-12), -clip, clip)
            w = w - jnp.where(done, 0.0, step)
            return i + 1, w, done

        # Same per-lane relative-gradient exit + 30-step cap as
        # rank1_newton, seeded from the entry gradient.
        _, w, _ = jax.lax.while_loop(
            cond, body,
            (jnp.zeros((), jnp.int32), w, jnp.abs(g0) <= gtol),
        )
        return w[:, None]

    _HI = jax.lax.Precision.HIGHEST

    def spd_solve_cg(H, g, n_steps):
        """Batched (E, D, D) SPD solve by ``n_steps`` unrolled CG
        iterations (exact at n_steps = D in exact arithmetic) — NO
        lax.linalg: batched ``jnp.linalg.solve`` lowers to scalar-heavy
        LU loops on TPU (measured 4.5x slower than the vmapped L-BFGS it
        was meant to replace), and a Gauss-Jordan inverse's (E, D, 2D)
        row ops are bandwidth-heavy at large E; CG touches only
        (E, D)-vectors plus one (E, D, D) matvec per step.  Zero lanes
        (H = 0, g = 0 — bucket padding) stay exactly zero."""
        x = jnp.zeros_like(g)
        r = g
        p = r
        rs = jnp.sum(r * r, axis=1)
        for _ in range(n_steps):
            Hp = jnp.einsum("edk,ek->ed", H, p, precision=_HI)
            alpha = rs / jnp.maximum(
                jnp.sum(p * Hp, axis=1), 1e-30
            )
            x = x + alpha[:, None] * p
            r = r - alpha[:, None] * Hp
            rs_new = jnp.sum(r * r, axis=1)
            beta = rs_new / jnp.maximum(rs, 1e-30)
            rs = rs_new
            p = r + beta[:, None] * p
        return x

    def newton_block(block, offsets_block, w0, l2, max_iters, tol):
        """Batched damped Newton for smooth objectives on small-D blocks:
        an exact (E, D, D) Hessian CG solve replaces the vmapped L-BFGS
        machinery.  The win is SEQUENTIAL structure — the chip profile
        showed the (E=27k, R=4) bucket costing 2x the (E=13k, R=16) one
        despite HALF the lane-rows, i.e. these buckets are bound by the
        while-loop body's launch/overhead count, not FLOPs.  One Newton
        body is a single fusable chain (grad, one batched-matmul Hessian
        build, D unrolled CG steps, damp) vs L-BFGS's nested scan + zoom
        while_loop per iteration, and quadratic convergence needs fewer
        outer trips — warm-started CD iterations exit in 1-2.  Per-lane
        freezing + the Breeze-style relative gradient test match the
        L-BFGS convergence semantics.  Small einsums run at HIGHEST
        precision: default MXU bf16 puts a noise floor above the 1e-6
        gradient tolerance, which silently disables the early exit."""
        X, yb, wt = block.X, block.labels, block.weights
        off = offsets_block.astype(X.dtype)
        d = block.block_dim
        eye = jnp.eye(d, dtype=X.dtype)

        def grad_at(w):
            m = jnp.einsum("erd,ed->er", X, w, precision=_HI) + off
            g = jnp.einsum(
                "er,erd->ed", wt * loss.d1(m, yb), X, precision=_HI
            ) + l2 * w
            return m, g

        _, g0 = grad_at(w0)
        gtol = tol * jnp.maximum(1.0, jnp.linalg.norm(g0, axis=1))

        def cond(carry):
            i, _w, done = carry
            return (i < max_iters) & ~jnp.all(done)

        def body(carry):
            i, w, done = carry
            m, g = grad_at(w)
            newly = jnp.linalg.norm(g, axis=1) <= gtol
            d2 = wt * loss.d2(m, yb)
            H = jnp.einsum(
                "erd,erk->edk", X * d2[:, :, None], X, precision=_HI
            ) + l2 * eye
            step = spd_solve_cg(H, g, d)
            # Margin-change damp (the rank1/dim1 clamp, per lane): one
            # step moves no row's margin by more than 20.
            dm = jnp.einsum("erd,ed->er", X, step, precision=_HI)
            scale = jnp.minimum(
                1.0,
                20.0 / jnp.maximum(jnp.max(jnp.abs(dm), axis=1), 1e-12),
            )
            keep = done | newly
            w = jnp.where(keep[:, None], w, w - scale[:, None] * step)
            return i + 1, w, keep

        _, w, _ = jax.lax.while_loop(
            cond, body, (jnp.zeros((), jnp.int32), w0,
                         jnp.zeros((X.shape[0],), bool))
        )
        return w

    def make_solve_one(history: int):
        def solve_one(X, y, wts, off, w0, l1, l2):
            def vg(w):
                m = X @ w + off
                val = jnp.sum(wts * loss.value(m, y)) + 0.5 * l2 * jnp.vdot(w, w)
                g = X.T @ (wts * loss.d1(m, y)) + l2 * w
                return val, g

            if use_owlqn:
                return owlqn_solve(
                    vg,
                    w0,
                    l1,
                    OWLQNConfig(
                        max_iters=opt.max_iters,
                        tolerance=opt.tolerance,
                        history=history,
                    ),
                ).w
            if use_tron:
                def hvp(w, v, aux):
                    return X.T @ (aux * (X @ v)) + l2 * v

                def d2f(w):
                    return wts * loss.d2(X @ w + off, y)

                return tron_solve(
                    vg, hvp, w0,
                    TRONConfig(
                        max_iters=opt.max_iters, tolerance=opt.tolerance
                    ),
                    d2_fn=d2f,
                ).w
            return lbfgs_solve(
                vg,
                w0,
                LBFGSConfig(
                    max_iters=opt.max_iters,
                    tolerance=opt.tolerance,
                    history=history,
                ),
            ).w

        return solve_one

    @jax.jit
    def solve_block(
        block: EntityBlock, offsets_block: Array, w0: Array, l1: Array, l2: Array
    ) -> Array:
        # Static shape dispatch (trace-time): single-row buckets take the
        # rank-1 Newton path for smooth objectives.  (A gram-space dual
        # Newton for 2 <= R <= 16 was tried and measured 4.5x SLOWER than
        # the vmapped L-BFGS: batched small jnp.linalg.solve lowers to
        # scalar-heavy LU loops on TPU.)
        if block.rows_per_entity == 1 and not use_owlqn:
            return rank1_newton(block, offsets_block, w0, l2)
        if block.block_dim == 1 and not use_owlqn:
            return dim1_newton(block, offsets_block, w0, l2)
        if block.block_dim <= 32 and not use_owlqn:
            # Small-D smooth blocks: exact batched Newton (D unrolled CG
            # steps per Hessian solve stay cheap; the Hessian build is
            # one MXU-friendly (E, D, R) x (E, R, D) batched matmul).
            return newton_block(
                block, offsets_block, w0, l2,
                opt.max_iters, opt.tolerance,
            )
        # History beyond the LOCAL problem dimension buys nothing (L-BFGS
        # with m >= d already behaves Newton-like) but every extra pair
    # adds two scan steps per iteration — sequential step count is what
        # dominates these small batched solves.
        solve_one = make_solve_one(min(opt.history, block.block_dim))
        return jax.vmap(
            solve_one, in_axes=(0, 0, 0, 0, 0, None, None)
        )(block.X, block.labels, block.weights, offsets_block, w0, l1, l2)

    return solve_block


def pack_entity_tables(cmap: np.ndarray, w: np.ndarray, var=None):
    """Per-lane (cols, vals[, variances]) lists for the host model table:
    one bulk mask + ``np.split`` instead of several numpy calls per lane
    (which cost ~4 s at 100k entities, once per coordinate per fit).
    Keeps real columns whose coefficient is nonzero — the same
    keep-then-nonzero filter the per-lane loop applied."""
    valid = (cmap >= 0) & (w != 0)
    bounds = np.cumsum(valid.sum(axis=1))[:-1]
    col_parts = np.split(cmap[valid].astype(np.int32), bounds)
    val_parts = np.split(w[valid].astype(np.float32), bounds)
    var_parts = (
        np.split(np.asarray(var)[valid].astype(np.float32), bounds)
        if var is not None else None
    )
    return col_parts, val_parts, var_parts


def _gather_block_offsets(offsets: Array, block: EntityBlock) -> Array:
    """Per-row offsets for one entity block; padding rows (sentinel index)
    read the appended zero slot."""
    padded = jnp.concatenate([offsets, jnp.zeros((1,), offsets.dtype)])
    return jnp.take(padded, block.row_index, axis=0)


@functools.lru_cache(maxsize=64)
def _re_train_all_jit(
    task: str, config: GlmOptimizationConfig, layout_sig: tuple
):
    """ONE jitted program for ALL buckets: per-bucket dispatches each pay
    a host→device round trip, which on a tunneled chip (~0.1-0.2 s each)
    dominated the whole coordinate update for long-tailed datasets with
    many buckets.  Bucket shapes differ but are static, so a single trace
    inlines every bucket's solver into one HLO.  Memoized PROCESS-WIDE on
    (task, config, dataset layout) like ``_make_block_solver`` —
    per-instance jits meant every new coordinate object (a second fit, a
    grid point, a fresh estimator) re-traced and re-compiled identical
    programs.  ``layout_sig`` is unused inside: it is the eviction
    granule (see ``_layout_sig``)."""
    solver = _make_block_solver(task, config)

    def _train_all(blocks, offsets, w0s, l1, l2):
        return [
            solver(b, _gather_block_offsets(offsets, b), w0, l1, l2)
            for b, w0 in zip(blocks, w0s)
        ]

    return jax.jit(_train_all)


@functools.lru_cache(maxsize=64)
def _re_score_all_jit(n_rows: int, layout_sig: tuple):
    """One jitted scoring scatter over all buckets (active + passive),
    memoized on (global row count, dataset layout).  BOUNDED: layouts
    vary per dataset/fold, and an unbounded cache would pin one compiled
    program per distinct layout for process lifetime."""

    def _score_all(blocks, passive_blocks, coefs_list):
        total = jnp.zeros((n_rows + 1,), jnp.float32)
        passive = passive_blocks or [None] * len(blocks)
        for block, passive_block, coefs in zip(blocks, passive, coefs_list):
            s = jnp.einsum("erd,ed->er", block.X, coefs)
            # Padding rows (sentinel index) scatter into the trailing slot.
            total = total.at[block.row_index.ravel()].add(s.ravel())
            if passive_block is not None:
                # Active/passive split: capped-out rows are never trained
                # on but MUST be scored, or other coordinates would see
                # offsets missing this coordinate's contribution there.
                sp_ = jnp.einsum("erd,ed->er", passive_block.X, coefs)
                total = total.at[passive_block.row_index.ravel()].add(
                    sp_.ravel()
                )
        return total[:n_rows]

    return jax.jit(_score_all)


class RandomEffectCoordinate(Coordinate):
    """Reference: ``RandomEffectCoordinate`` — per-entity solves, batched.

    State is a list of per-bucket coefficient arrays ``(E, D)`` in each
    block's LOCAL (projected) column space.
    """

    def __init__(
        self,
        name: str,
        dataset: RandomEffectDataset,
        task: str,
        config: GlmOptimizationConfig,
        reg_weight: float = 0.0,
        feature_shard: str = "global",
        entity_key: str = "",
    ):
        self.name = name
        self.dataset = dataset
        self.task = losses_lib.get(task).name
        self.config = config
        self.reg_weight = reg_weight
        self.feature_shard = feature_shard
        self.entity_key = entity_key or name
        self._solver = _make_block_solver(task, config)
        sig = _layout_sig((dataset.blocks, dataset.passive_blocks))
        self._train_all_jit = _re_train_all_jit(self.task, config, sig)
        self._score_all_jit = _re_score_all_jit(dataset.n_global_rows, sig)

    def train(self, offsets: Array, warm_state=None) -> list[Array]:
        l1 = jnp.asarray(
            self.config.regularization.l1_weight(1.0) * self.reg_weight,
            jnp.float32,
        )
        l2 = jnp.asarray(
            self.config.regularization.l2_weight(1.0) * self.reg_weight,
            jnp.float32,
        )
        w0s = [
            (
                warm_state[bi]
                if warm_state is not None
                else jnp.zeros(
                    (block.n_entities, block.block_dim), jnp.float32
                )
            )
            for bi, block in enumerate(self.dataset.blocks)
        ]
        return self._train_all_jit(
            self.dataset.blocks, jnp.asarray(offsets, jnp.float32), w0s,
            l1, l2,
        )

    def score(self, state: list[Array]) -> Array:
        return self._score_all_jit(
            self.dataset.blocks, self.dataset.passive_blocks, state
        )

    def _block_variances(self, block: EntityBlock, coefs: Array,
                         offsets: Array) -> np.ndarray:
        """Per-entity diagonal-inverse-Hessian variances (the reference's
        SIMPLE variance type, per entity): 1 / (Σ_r w·d2(m)·X² + λ₂),
        evaluated at the FULL final margins (residual offsets included)."""
        loss = losses_lib.get(self.task)
        l2 = jnp.asarray(
            self.config.regularization.l2_weight(1.0) * self.reg_weight,
            jnp.float32,
        )
        off_b = _gather_block_offsets(jnp.asarray(offsets, jnp.float32), block)
        m = jnp.einsum("erd,ed->er", block.X, coefs) + off_b
        d2w = block.weights * loss.d2(m, block.labels)
        diag = jnp.einsum("er,erd->ed", d2w, block.X * block.X) + l2
        return np.asarray(1.0 / jnp.maximum(diag, 1e-12))

    def finalize(self, state: list[Array], offsets=None) -> RandomEffectModel:
        compute_var = (
            self.config.compute_variances and offsets is not None
        )
        table: dict = {}
        var_table: dict = {} if compute_var else None
        for block, ids, coefs in zip(
            self.dataset.blocks, self.dataset.entity_ids, state
        ):
            cmap = np.asarray(block.col_map)
            w = np.asarray(coefs)
            var = (
                self._block_variances(block, coefs, offsets)
                if compute_var
                else None
            )
            col_parts, val_parts, var_parts = pack_entity_tables(
                cmap, w, var
            )
            for lane, key in enumerate(ids):
                table[key] = (col_parts[lane], val_parts[lane])
                if var_parts is not None:
                    var_table[key] = var_parts[lane]
        return RandomEffectModel(
            coefficients=table,
            feature_shard=self.feature_shard,
            entity_key=self.entity_key,
            task=self.task,
            n_features=self.dataset.n_features,
            variances=var_table,
        )

    def make_validation_scorer(self, shards: dict, ids: dict):
        from photon_ml_tpu.game.validation import RandomEffectValidationScorer

        return RandomEffectValidationScorer(
            self.dataset, ids[self.entity_key], shards[self.feature_shard]
        )
