"""Out-of-core random effects: entity blocks larger than device memory.

The reference's ``RandomEffectDataset`` is an RDD — *cluster-memory*-scaled:
entities hash-partitioned across executors, each executor training its
partition's per-entity GLMs locally (SURVEY.md §2 RandomEffectDataset row,
§3.2).  At BASELINE config 5's scale (1B rows, user+item+context random
effects) the per-entity datasets collectively dwarf one chip's HBM, and
entity-sharding only divides by ``n_devices`` — it never bounds the
PER-DEVICE footprint.

This module bounds it.  The per-entity solves are embarrassingly
independent (no cross-block state beyond the shared per-row offsets), so
the blocks stream the way the row-chunk store streams fixed-effect data:

1. the dataset is built HOST-resident (``device=False``);
2. oversized blocks are split along the ENTITY axis into uniform-shape
   sub-slices (one compiled program per original block shape — the last
   slice pads with zero-weight lanes, which solve to w=0 under any L2);
3. slices are packed into PASS GROUPS whose device footprint fits half the
   budget — half, because the next group's transfer is enqueued while the
   current group solves (double buffering, the chunk-store discipline);
4. per-entity coefficients live in host numpy between passes; only the
   global offset/score row arrays stay device-resident.

With a mesh, each slice's entity axis is additionally sharded over the
mesh (the ``EntityShardedRandomEffectCoordinate`` layout) — the budget
then bounds the PER-DEVICE bytes, and the vmap'd solver still partitions
with zero communication.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_ml_tpu.data.prefetch import TransferStats, run_prefetched
from photon_ml_tpu.game.coordinates import (
    RandomEffectCoordinate,
    _gather_block_offsets,
    _make_block_solver,
)
from photon_ml_tpu.game.data import EntityBlock, RandomEffectDataset
from photon_ml_tpu.ops import losses as losses_lib
from photon_ml_tpu.optim.problem import GlmOptimizationConfig
from photon_ml_tpu.parallel.distributed import DATA_AXIS

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class _Slice:
    """One schedulable unit: lanes [lane_lo, lane_hi) of block ``block_idx``,
    padded to ``padded_e`` entities (uniform across the block's slices so
    every slice of a block shares ONE compiled program)."""

    block_idx: int
    lane_lo: int
    lane_hi: int
    padded_e: int
    bytes: int


def _lane_bytes(block: EntityBlock, passive: Optional[EntityBlock]) -> int:
    """Device bytes one entity lane costs in a pass: active leaves + the
    gathered offsets + coefficients in and out, plus the lane's score-only
    passive companion (score passes carry both; one conservative number
    keeps train and score on a single plan)."""
    r, d = block.rows_per_entity, block.block_dim
    active = 4 * (r * d + 4 * r + 2 * d)  # X, labels/weights/row_index/off, cmap+w
    out = 4 * d
    psv = 0
    if passive is not None:
        rp = passive.rows_per_entity
        psv = 4 * (rp * d + 3 * rp)  # Xp, labels/weights/row_index
    return active + out + psv


@functools.lru_cache(maxsize=64)
def _ooc_slice_jits(
    task: str, config: GlmOptimizationConfig, slice_sig: tuple
):
    # slice_sig is unused inside — it is the cache's eviction granule
    # (see coordinates._layout_sig): slice shapes vary per dataset/plan,
    # and one shared wrapper would otherwise pin an executable per
    # distinct layout for process lifetime.
    solver = _make_block_solver(task, config)
    loss = losses_lib.get(task)

    def _solve_slice(block, offsets, w0, l1, l2):
        return solver(
            block, _gather_block_offsets(offsets, block), w0, l1, l2
        )

    def _var_slice(block, coefs, offsets, l2):
        off_b = _gather_block_offsets(offsets, block)
        m = jnp.einsum("erd,ed->er", block.X, coefs) + off_b
        d2w = block.weights * loss.d2(m, block.labels)
        diag = jnp.einsum("er,erd->ed", d2w, block.X * block.X) + l2
        return 1.0 / jnp.maximum(diag, 1e-12)

    return jax.jit(_solve_slice), jax.jit(_var_slice)


@functools.lru_cache(maxsize=None)
def _ooc_score_jit():
    def _score_slice(total, X, row_index, coefs):
        s = jnp.einsum("erd,ed->er", X, coefs)
        return total.at[row_index.ravel()].add(s.ravel())

    # total is donated: each pass group's scatter reuses the buffer
    # instead of allocating a second (n_rows+1) array per step.
    return jax.jit(_score_slice, donate_argnums=0)


@functools.lru_cache(maxsize=32)  # size-keyed: bounded (see coordinates.py)
def _ooc_zeros_jit(n_rows: int):
    return jax.jit(lambda: jnp.zeros((n_rows + 1,), jnp.float32))


def _host_leaf(x) -> np.ndarray:
    if isinstance(x, jax.Array):
        raise ValueError(
            "out-of-core random effects need a HOST-resident dataset — "
            "build it with build_random_effect_dataset(..., device=False)"
        )
    return np.asarray(x)


def _cut(x, lo: int, hi: int, padded_e: int, fill):
    """Entity-axis slice [lo, hi) padded to ``padded_e`` lanes with
    ``fill`` — the one pad-and-slice implementation for both the full
    block slicer and the score path's slimmed (X, row_index) slices."""
    x = x[lo:hi]
    pad = padded_e - x.shape[0]
    if pad == 0:
        return x
    width = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, width, constant_values=fill)


def _slice_block(
    block: EntityBlock, lo: int, hi: int, padded_e: int, sentinel: int
) -> EntityBlock:
    """Host-side entity-axis slice [lo, hi), padded to ``padded_e`` lanes.
    Padding lanes carry zero weights (solve to 0), col_map -1, and sentinel
    row indices (scatter into the discarded trailing slot)."""
    return EntityBlock(
        X=_cut(block.X, lo, hi, padded_e, 0),
        labels=_cut(block.labels, lo, hi, padded_e, 0),
        weights=_cut(block.weights, lo, hi, padded_e, 0),
        col_map=_cut(block.col_map, lo, hi, padded_e, -1),
        row_index=_cut(block.row_index, lo, hi, padded_e, sentinel),
        n_entities=padded_e,
        rows_per_entity=block.rows_per_entity,
        block_dim=block.block_dim,
    )


class OutOfCoreRandomEffectCoordinate(RandomEffectCoordinate):
    """RandomEffectCoordinate whose dataset exceeds device memory.

    Same ``train(offsets, warm) → state`` / ``score(state)`` surface as the
    resident coordinate; identical numerics (the very same memoized block
    solver runs on each slice, and entity-axis slicing/padding never changes
    a lane's math).  State is a list of HOST (E, D) numpy arrays.
    """

    def __init__(
        self,
        name: str,
        dataset: RandomEffectDataset,
        task: str,
        config: GlmOptimizationConfig,
        reg_weight: float = 0.0,
        feature_shard: str = "global",
        entity_key: str = "",
        device_budget_bytes: int = 256 * 2**20,
        mesh=None,
        prefetch_depth: int = 2,
    ):
        # Deliberately NOT calling super().__init__: the resident
        # constructor jits one whole-dataset program, which is exactly what
        # a larger-than-HBM dataset cannot do.
        self.name = name
        self.dataset = dataset
        self.task = losses_lib.get(task).name
        self.config = config
        self.reg_weight = reg_weight
        self.feature_shard = feature_shard
        self.entity_key = entity_key or name
        self.device_budget_bytes = int(device_budget_bytes)
        if prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {prefetch_depth}"
            )
        self.prefetch_depth = int(prefetch_depth)
        #: h2d observability for this coordinate's group transfers — the
        #: same TransferStats the streamed fixed effect exposes.
        self.transfer_stats = TransferStats()
        if mesh is not None and jax.process_count() > 1:
            # Same early rejection as StreamingFixedEffectCoordinate:
            # _put would device_put per-process host numpy onto a
            # pod-spanning sharding — unsupported/undefined — and only
            # deep inside the first train pass.
            raise NotImplementedError(
                "out-of-core random effects are single-host for now: "
                "entity blocks live in one process's RAM, and slicing "
                "them onto a multi-process pod mesh is not wired up"
            )
        self.mesh = mesh
        self._solver = _make_block_solver(task, config)
        self._sharding = (
            None if mesh is None else NamedSharding(mesh, P(DATA_AXIS))
        )
        self._quantum = 1 if mesh is None else int(mesh.devices.size)

        for b in dataset.blocks:
            jax.tree.map(_host_leaf, b)
        for b in dataset.passive_blocks:
            if b is not None:
                jax.tree.map(_host_leaf, b)

        self.pass_plan = self._build_plan()
        #: high-water mark of pass groups with live device buffers —
        #: the structural "bounded memory" witness the tests pin
        #: (≤ prefetch_depth; 2 by default: the solving group plus the
        #: prefetched next one).
        self.live_groups_high_water = 0

        # Process-wide memoized programs (per-instance jits re-compiled
        # identical HLO for every new coordinate — each fit, grid point,
        # or fresh estimator).
        slice_sig = tuple(sorted({
            (s.padded_e,
             dataset.blocks[s.block_idx].rows_per_entity,
             dataset.blocks[s.block_idx].block_dim)
            for group in self.pass_plan for s in group
        }))
        self._solve_jit, self._var_jit = _ooc_slice_jits(
            self.task, config, slice_sig
        )
        self._score_jit = _ooc_score_jit()
        self._zeros_jit = _ooc_zeros_jit(dataset.n_global_rows)

    # -- pass planning -----------------------------------------------------

    def _build_plan(self) -> list[list[_Slice]]:
        """Split blocks along the entity axis and pack slices into groups.

        Each original block is cut into ``n_parts`` uniform sub-slices
        (ceil division, padded to the mesh quantum) so the whole block
        contributes ONE compiled shape; groups then fill greedily to the
        per-pass budget (= budget/prefetch_depth — the pipeline keeps up
        to that many groups live on the device; depth 2 is the classic
        double-buffering reserve).
        """
        budget = (
            self.device_budget_bytes - self._budget_overhead_bytes()
        ) // self.prefetch_depth
        if budget <= 0:
            raise ValueError(
                f"random-effect coordinate {self.name!r}: "
                f"device_budget_bytes={self.device_budget_bytes} does not "
                f"cover the {self._budget_overhead_bytes()}-byte "
                "whole-pass-resident overhead"
            )
        q = self._quantum
        plan: list[list[_Slice]] = []
        group: list[_Slice] = []
        group_bytes = 0
        for bi, block in enumerate(self.dataset.blocks):
            passive = (
                self.dataset.passive_blocks[bi]
                if self.dataset.passive_blocks else None
            )
            per_lane = _lane_bytes(block, passive) + self._extra_lane_bytes(
                block
            )
            e = block.n_entities
            if per_lane * q > budget:
                raise ValueError(
                    f"random-effect coordinate {self.name!r}: one "
                    f"{q}-entity slice of block {bi} "
                    f"(R={block.rows_per_entity}, D={block.block_dim}) "
                    f"needs {per_lane * q} bytes, over the "
                    f"per-pass budget {budget} (= (device_budget_bytes "
                    f"- {self._budget_overhead_bytes()} overhead) / "
                    f"prefetch_depth={self.prefetch_depth}). "
                    "Raise device_budget_bytes or lower "
                    "max_rows_per_entity / bucket_growth"
                )
            # Quantum-multiple lane cap, so the final round-up below can
            # never push a slice past the budget.
            lanes_per_pass = max(q, (budget // per_lane) // q * q)
            n_parts = max(1, -(-e // lanes_per_pass))  # ceil
            sub_e = -(-e // n_parts)
            sub_e = ((sub_e + q - 1) // q) * q  # quantum-aligned
            for lo in range(0, e, sub_e):
                hi = min(lo + sub_e, e)
                s = _Slice(bi, lo, hi, sub_e, per_lane * sub_e)
                if group and group_bytes + s.bytes > budget:
                    plan.append(group)
                    group, group_bytes = [], 0
                group.append(s)
                group_bytes += s.bytes
        if group:
            plan.append(group)
        return plan

    def _extra_lane_bytes(self, block: EntityBlock) -> int:
        """Subclass hook: additional device bytes one lane costs beyond
        the raw block leaves (e.g. the factored variant's projected
        features and latent vectors)."""
        return 0

    def _budget_overhead_bytes(self) -> int:
        """Subclass hook: device bytes resident for the WHOLE pass
        (shared state like the factored projection + its gradient),
        carved out of the budget before groups are sized."""
        return 0

    def _put(self, tree):
        if self._sharding is None:
            return jax.device_put(tree)
        return jax.tree.map(
            lambda x: jax.device_put(x, self._sharding), tree
        )

    def _run_groups(self, make_host_group, consume):
        """Prefetch-pipelined group runner (the chunk store's ingest
        pipeline, data/prefetch.py): a PACK thread slices the next
        groups on the host, a TRANSFER thread dispatches them and waits
        out their h2d completion, and the caller thread consumes the
        current one — host slicing, the link, and device compute all
        overlap, with at most ``prefetch_depth`` groups admitted by the
        permit accounting (which replaced the old hand-rolled double
        buffer — and its reference-lifetime subtleties — outright).
        ``make_host_group(group) → host pytree list``; per-stage wall
        attribution lands in ``self.transfer_stats``."""
        plan = self.pass_plan
        self.live_groups_high_water = 0
        if not plan:
            return
        self.live_groups_high_water = run_prefetched(
            len(plan),
            lambda gi: make_host_group(plan[gi]),
            self._put,
            lambda gi, dev: consume(plan[gi], dev),
            depth=self.prefetch_depth,
            stats=self.transfer_stats,
        )

    # -- coordinate surface ------------------------------------------------

    def train(self, offsets: Array, warm_state=None) -> list[np.ndarray]:
        l1 = jnp.asarray(
            self.config.regularization.l1_weight(1.0) * self.reg_weight,
            jnp.float32,
        )
        l2 = jnp.asarray(
            self.config.regularization.l2_weight(1.0) * self.reg_weight,
            jnp.float32,
        )
        offsets = jnp.asarray(offsets, jnp.float32)
        sentinel = self.dataset.n_global_rows
        state = [
            (
                np.zeros((b.n_entities, b.block_dim), np.float32)
                if warm_state is None
                # copy: np.asarray of a jax array (checkpoint resume) is a
                # read-only zero-copy view, and this buffer is written into.
                else np.array(warm_state[bi], np.float32)
            )
            for bi, b in enumerate(self.dataset.blocks)
        ]

        def host_group(group):
            out = []
            for s in group:
                block = self.dataset.blocks[s.block_idx]
                w0 = state[s.block_idx][s.lane_lo:s.lane_hi]
                pad = s.padded_e - w0.shape[0]
                if pad:
                    w0 = np.pad(w0, ((0, pad), (0, 0)))
                out.append((
                    _slice_block(
                        block, s.lane_lo, s.lane_hi, s.padded_e, sentinel
                    ),
                    w0,
                ))
            return out

        def consume(group, dev):
            # Dispatch every solve in the group first (async), then pull —
            # the pulls overlap the NEXT group's host slicing + transfer.
            results = [
                self._solve_jit(blk, offsets, w0, l1, l2)
                for blk, w0 in dev
            ]
            for s, res in zip(group, results):
                state[s.block_idx][s.lane_lo:s.lane_hi] = np.asarray(
                    res
                )[: s.lane_hi - s.lane_lo]

        self._run_groups(host_group, consume)
        return state

    def score(self, state) -> Array:
        sentinel = self.dataset.n_global_rows
        total = self._zeros_jit()

        def host_group(group):
            # Score-only slices: just X + row_index (+ coefs) cross the
            # wire — labels/weights/col_map are ~30% of the lane bytes
            # and the score einsum/scatter never reads them (h2d is the
            # scarce resource on the tunneled chip).
            out = []
            for s in group:
                coefs = _cut(
                    np.asarray(state[s.block_idx], np.float32),
                    s.lane_lo, s.lane_hi, s.padded_e, 0,
                )
                block = self.dataset.blocks[s.block_idx]
                active = (
                    _cut(block.X, s.lane_lo, s.lane_hi, s.padded_e, 0),
                    _cut(block.row_index, s.lane_lo, s.lane_hi,
                        s.padded_e, sentinel),
                )
                passive = None
                if self.dataset.passive_blocks:
                    pb = self.dataset.passive_blocks[s.block_idx]
                    if pb is not None:
                        passive = (
                            _cut(pb.X, s.lane_lo, s.lane_hi, s.padded_e, 0),
                            _cut(pb.row_index, s.lane_lo, s.lane_hi,
                                s.padded_e, sentinel),
                        )
                out.append((active, passive, coefs))
            return out

        def consume(_group, dev):
            nonlocal total
            for active, passive, coefs in dev:
                total = self._score_jit(total, *active, coefs)
                if passive is not None:
                    # Active/passive split: capped-out rows are never
                    # trained on but MUST be scored (coordinates train
                    # against each other's full contributions).
                    total = self._score_jit(total, *passive, coefs)

        self._run_groups(host_group, consume)
        return total[: self.dataset.n_global_rows]

    def _block_variances(self, block: EntityBlock, coefs, offsets):
        """Budget-bounded override: the inherited version moves the WHOLE
        block to device for the variance Hessian — exactly the transfer
        this coordinate exists to avoid.  Reuse the pass plan's slice
        shape for this block instead."""
        bi = next(
            i for i, b in enumerate(self.dataset.blocks) if b is block
        )
        sub_e = next(
            s.padded_e
            for group in self.pass_plan
            for s in group
            if s.block_idx == bi
        )
        sentinel = self.dataset.n_global_rows
        offsets = jnp.asarray(offsets, jnp.float32)
        l2 = jnp.asarray(
            self.config.regularization.l2_weight(1.0) * self.reg_weight,
            jnp.float32,
        )
        coefs = np.asarray(coefs, np.float32)
        out = np.empty((block.n_entities, block.block_dim), np.float32)
        for lo in range(0, block.n_entities, sub_e):
            hi = min(lo + sub_e, block.n_entities)
            c = coefs[lo:hi]
            pad = sub_e - c.shape[0]
            if pad:
                c = np.pad(c, ((0, pad), (0, 0)))
            v = self._var_jit(
                self._put(_slice_block(block, lo, hi, sub_e, sentinel)),
                self._put(c), offsets, l2,
            )
            out[lo:hi] = np.asarray(v)[: hi - lo]
        return out
