"""Out-of-core random effects: entity blocks larger than device memory.

The reference's ``RandomEffectDataset`` is an RDD — *cluster-memory*-scaled:
entities hash-partitioned across executors, each executor training its
partition's per-entity GLMs locally (SURVEY.md §2 RandomEffectDataset row,
§3.2).  At BASELINE config 5's scale (1B rows, user+item+context random
effects) the per-entity datasets collectively dwarf one chip's HBM, and
entity-sharding only divides by ``n_devices`` — it never bounds the
PER-DEVICE footprint.

This module bounds it.  The per-entity solves are embarrassingly
independent (no cross-block state beyond the shared per-row offsets), so
the blocks stream the way the row-chunk store streams fixed-effect data:

1. the dataset is built HOST-resident (``device=False``);
2. oversized blocks are split along the ENTITY axis into uniform-shape
   sub-slices (one compiled program per original block shape — the last
   slice pads with zero-weight lanes, which solve to w=0 under any L2);
3. slices are packed into PASS GROUPS whose device footprint fits half the
   budget — half, because the next group's transfer is enqueued while the
   current group solves (double buffering, the chunk-store discipline);
4. per-entity coefficients live in host numpy between passes; only the
   global offset/score row arrays stay device-resident.

With a mesh, each slice's entity axis is additionally sharded over the
mesh (the ``EntityShardedRandomEffectCoordinate`` layout) — the budget
then bounds the PER-DEVICE bytes, and the vmap'd solver still partitions
with zero communication.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.data.prefetch import TransferStats, run_prefetched
from photon_ml_tpu.game.coordinates import (
    RandomEffectCoordinate,
    _gather_block_offsets,
    _make_block_solver,
)
from photon_ml_tpu.game.data import EntityBlock, RandomEffectDataset
from photon_ml_tpu.game.hierarchical import plan_bucket_shards
from photon_ml_tpu.ops import losses as losses_lib
from photon_ml_tpu.optim.problem import GlmOptimizationConfig
from photon_ml_tpu.optim.streaming import HotChunkCache
from photon_ml_tpu.parallel.distributed import DATA_AXIS

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class _Slice:
    """One schedulable unit: lanes [lane_lo, lane_hi) of block ``block_idx``,
    padded to ``padded_e`` entities (uniform across the block's slices so
    every slice of a block shares ONE compiled program).  ``placement``
    follows the block's :class:`~photon_ml_tpu.game.hierarchical
    .BucketShardPlan` entry — ``("split",)`` shards the slice's entity
    axis over the whole mesh, ``("pack", k)`` lands it whole on device k
    (ignored when there is no mesh)."""

    block_idx: int
    lane_lo: int
    lane_hi: int
    padded_e: int
    bytes: int
    placement: tuple = ("split",)


def _lane_bytes(block: EntityBlock, passive: Optional[EntityBlock]) -> int:
    """Device bytes one entity lane costs in a pass: active leaves + the
    gathered offsets + coefficients in and out, plus the lane's score-only
    passive companion (score passes carry both; one conservative number
    keeps train and score on a single plan)."""
    r, d = block.rows_per_entity, block.block_dim
    active = 4 * (r * d + 4 * r + 2 * d)  # X, labels/weights/row_index/off, cmap+w
    out = 4 * d
    psv = 0
    if passive is not None:
        rp = passive.rows_per_entity
        psv = 4 * (rp * d + 3 * rp)  # Xp, labels/weights/row_index
    return active + out + psv


@functools.lru_cache(maxsize=64)
def _ooc_slice_jits(
    task: str, config: GlmOptimizationConfig, slice_sig: tuple
):
    # slice_sig is unused inside — it is the cache's eviction granule
    # (see coordinates._layout_sig): slice shapes vary per dataset/plan,
    # and one shared wrapper would otherwise pin an executable per
    # distinct layout for process lifetime.
    solver = _make_block_solver(task, config)
    loss = losses_lib.get(task)

    def _solve_slice(block, offsets, w0, l1, l2):
        return solver(
            block, _gather_block_offsets(offsets, block), w0, l1, l2
        )

    def _var_slice(block, coefs, offsets, l2):
        off_b = _gather_block_offsets(offsets, block)
        m = jnp.einsum("erd,ed->er", block.X, coefs) + off_b
        d2w = block.weights * loss.d2(m, block.labels)
        diag = jnp.einsum("er,erd->ed", d2w, block.X * block.X) + l2
        return 1.0 / jnp.maximum(diag, 1e-12)

    return jax.jit(_solve_slice), jax.jit(_var_slice)


@functools.lru_cache(maxsize=None)
def _ooc_score_jit():
    def _score_slice(total, X, row_index, coefs):
        s = jnp.einsum("erd,ed->er", X, coefs)
        return total.at[row_index.ravel()].add(s.ravel())

    # total is donated: each pass group's scatter reuses the buffer
    # instead of allocating a second (n_rows+1) array per step.
    return jax.jit(_score_slice, donate_argnums=0)


@functools.lru_cache(maxsize=32)  # size-keyed: bounded (see coordinates.py)
def _ooc_zeros_jit(n_rows: int):
    return jax.jit(lambda: jnp.zeros((n_rows + 1,), jnp.float32))


def _host_leaf(x) -> np.ndarray:
    if isinstance(x, jax.Array):
        raise ValueError(
            "out-of-core random effects need a HOST-resident dataset — "
            "build it with build_random_effect_dataset(..., device=False)"
        )
    return np.asarray(x)


def _cut(x, lo: int, hi: int, padded_e: int, fill):
    """Entity-axis slice [lo, hi) padded to ``padded_e`` lanes with
    ``fill`` — the one pad-and-slice implementation for both the full
    block slicer and the score path's slimmed (X, row_index) slices."""
    x = x[lo:hi]
    pad = padded_e - x.shape[0]
    if pad == 0:
        return x
    width = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, width, constant_values=fill)


def _slice_block(
    block: EntityBlock, lo: int, hi: int, padded_e: int, sentinel: int
) -> EntityBlock:
    """Host-side entity-axis slice [lo, hi), padded to ``padded_e`` lanes.
    Padding lanes carry zero weights (solve to 0), col_map -1, and sentinel
    row indices (scatter into the discarded trailing slot)."""
    return EntityBlock(
        X=_cut(block.X, lo, hi, padded_e, 0),
        labels=_cut(block.labels, lo, hi, padded_e, 0),
        weights=_cut(block.weights, lo, hi, padded_e, 0),
        col_map=_cut(block.col_map, lo, hi, padded_e, -1),
        row_index=_cut(block.row_index, lo, hi, padded_e, sentinel),
        n_entities=padded_e,
        rows_per_entity=block.rows_per_entity,
        block_dim=block.block_dim,
    )


class OutOfCoreRandomEffectCoordinate(RandomEffectCoordinate):
    """RandomEffectCoordinate whose dataset exceeds device memory.

    Same ``train(offsets, warm) → state`` / ``score(state)`` surface as the
    resident coordinate; identical numerics (the very same memoized block
    solver runs on each slice, and entity-axis slicing/padding never changes
    a lane's math).  State is a list of HOST (E, D) numpy arrays.
    """

    #: Subclasses whose jitted programs mix slice payloads with
    #: whole-pass device state (the factored projection accumulator)
    #: cannot commit slices to individual devices — they disable the
    #: hierarchical plan and keep the legacy everything-split layout.
    _supports_packed = True
    #: Subclasses with their own payload formats (the factored variant
    #: streams projected features, not raw blocks) opt out of the hot
    #: working-set cache — the base-class train/score are the only
    #: consumers of the cached slice trees.
    _supports_hot_cache = True

    def __init__(
        self,
        name: str,
        dataset: RandomEffectDataset,
        task: str,
        config: GlmOptimizationConfig,
        reg_weight: float = 0.0,
        feature_shard: str = "global",
        entity_key: str = "",
        device_budget_bytes: int = 256 * 2**20,
        mesh=None,
        prefetch_depth: int = 2,
        split_factor: float = 0.5,
        hot_budget_bytes: int = 0,
    ):
        # Deliberately NOT calling super().__init__: the resident
        # constructor jits one whole-dataset program, which is exactly what
        # a larger-than-HBM dataset cannot do.
        self.name = name
        self.dataset = dataset
        self.task = losses_lib.get(task).name
        self.config = config
        self.reg_weight = reg_weight
        self.feature_shard = feature_shard
        self.entity_key = entity_key or name
        self.device_budget_bytes = int(device_budget_bytes)
        if prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {prefetch_depth}"
            )
        self.prefetch_depth = int(prefetch_depth)
        #: h2d observability for this coordinate's group transfers — the
        #: same TransferStats the streamed fixed effect exposes.
        self.transfer_stats = TransferStats()
        if mesh is not None and jax.process_count() > 1:
            # Same early rejection as StreamingFixedEffectCoordinate:
            # _put would device_put per-process host numpy onto a
            # pod-spanning sharding — unsupported/undefined — and only
            # deep inside the first train pass.
            raise NotImplementedError(
                "out-of-core random effects are single-host for now: "
                "entity blocks live in one process's RAM, and slicing "
                "them onto a multi-process pod mesh is not wired up"
            )
        self.mesh = mesh
        self._solver = _make_block_solver(task, config)
        self._sharding = (
            None if mesh is None else NamedSharding(mesh, P(DATA_AXIS))
        )
        self._devices = (
            None if mesh is None else list(mesh.devices.flat)
        )
        # Hierarchical placement (game/hierarchical.py): big blocks split
        # over the mesh, the long tail packs whole onto devices — the
        # slices inherit their block's placement, so small buckets stop
        # paying mesh-quantum padding and the devices' async dispatch
        # overlaps their solves.
        self.bucket_plan = (
            None
            if mesh is None or not self._supports_packed
            else plan_bucket_shards(
                dataset.blocks, len(self._devices),
                split_factor=split_factor,
            )
        )
        if self.bucket_plan is not None:
            telemetry_mod.current().gauge(
                "game_shard_imbalance_ratio"
            ).set(self.bucket_plan.imbalance_ratio)

        for b in dataset.blocks:
            jax.tree.map(_host_leaf, b)
        for b in dataset.passive_blocks:
            if b is not None:
                jax.tree.map(_host_leaf, b)

        self.pass_plan = self._build_plan()
        #: high-water mark of pass groups with live device buffers —
        #: the structural "bounded memory" witness the tests pin
        #: (≤ prefetch_depth; 2 by default: the solving group plus the
        #: prefetched next one).
        self.live_groups_high_water = 0

        # Process-wide memoized programs (per-instance jits re-compiled
        # identical HLO for every new coordinate — each fit, grid point,
        # or fresh estimator).
        slice_sig = tuple(sorted({
            (s.padded_e,
             dataset.blocks[s.block_idx].rows_per_entity,
             dataset.blocks[s.block_idx].block_dim)
            for group in self.pass_plan for s in group
        }))
        self._solve_jit, self._var_jit = _ooc_slice_jits(
            self.task, config, slice_sig
        )
        self._score_jit = _ooc_score_jit()
        self._zeros_jit = _ooc_zeros_jit(dataset.n_global_rows)
        # Pipelined-descent prestage state: one background packer at a
        # time, single-producer/single-consumer handed off via an Event
        # (no shared mutable state beyond the record, so no lock).
        self._plan_index = {
            id(g): gi for gi, g in enumerate(self.pass_plan)
        }
        self._prestage_rec = None
        # Hot working-set cache (optim/streaming.py HotChunkCache,
        # generalized to per-device hot sets): a hot pass group's STATIC
        # slice payloads — the already-placed block/score trees, sharded
        # or device-committed per the bucket plan — stay resident, so
        # repeat passes skip their host pack AND h2d transfer and stream
        # only the dynamic part (warm starts / coefficients).  The same
        # compiled programs serve hot and cold groups in the same order,
        # so results are bitwise identical either way.  Blocks are
        # immutable for the coordinate's lifetime, so entries never go
        # stale; the wanted set is picked ONCE here, biggest transfers
        # first (the importance of a static payload IS its wire bytes).
        if hot_budget_bytes < 0:
            raise ValueError(
                f"hot_budget_bytes must be >= 0, got {hot_budget_bytes}"
            )
        self.hot_budget_bytes = int(hot_budget_bytes)
        self._hot_cache = None
        self._hot_bytes: dict = {}
        if self.hot_budget_bytes and self._supports_hot_cache:
            self._hot_cache = HotChunkCache(self.hot_budget_bytes)
            for gi, group in enumerate(self.pass_plan):
                for kind in ("train", "score"):
                    self._hot_bytes[(kind, gi)] = (
                        self._group_static_bytes(kind, group)
                    )
            self._hot_cache.replan(
                self._hot_bytes, self._hot_bytes.__getitem__
            )

    # -- pass planning -----------------------------------------------------

    def _build_plan(self) -> list[list[_Slice]]:
        """Split blocks along the entity axis and pack slices into groups.

        Each original block is cut into ``n_parts`` uniform sub-slices
        (ceil division, padded to the mesh quantum) so the whole block
        contributes ONE compiled shape; groups then fill greedily to the
        per-pass budget (= budget/prefetch_depth — the pipeline keeps up
        to that many groups live on the device; depth 2 is the classic
        double-buffering reserve).
        """
        budget = (
            self.device_budget_bytes - self._budget_overhead_bytes()
        ) // self.prefetch_depth
        if budget <= 0:
            raise ValueError(
                f"random-effect coordinate {self.name!r}: "
                f"device_budget_bytes={self.device_budget_bytes} does not "
                f"cover the {self._budget_overhead_bytes()}-byte "
                "whole-pass-resident overhead"
            )
        plan: list[list[_Slice]] = []
        group: list[_Slice] = []
        group_bytes = 0
        for bi, block in enumerate(self.dataset.blocks):
            passive = (
                self.dataset.passive_blocks[bi]
                if self.dataset.passive_blocks else None
            )
            # Placement sets the lane quantum: split slices need one
            # shardable lane per mesh device, packed (and unmeshed)
            # slices run whole on one device and pad nothing extra.
            placement = (
                ("split",)
                if self.bucket_plan is None
                else self.bucket_plan.placements[bi]
            )
            q = (
                len(self._devices)
                if self.mesh is not None and placement[0] == "split"
                else 1
            )
            per_lane = _lane_bytes(block, passive) + self._extra_lane_bytes(
                block
            )
            e = block.n_entities
            if per_lane * q > budget:
                raise ValueError(
                    f"random-effect coordinate {self.name!r}: one "
                    f"{q}-entity slice of block {bi} "
                    f"(R={block.rows_per_entity}, D={block.block_dim}) "
                    f"needs {per_lane * q} bytes, over the "
                    f"per-pass budget {budget} (= (device_budget_bytes "
                    f"- {self._budget_overhead_bytes()} overhead) / "
                    f"prefetch_depth={self.prefetch_depth}). "
                    "Raise device_budget_bytes or lower "
                    "max_rows_per_entity / bucket_growth"
                )
            # Quantum-multiple lane cap, so the final round-up below can
            # never push a slice past the budget.
            lanes_per_pass = max(q, (budget // per_lane) // q * q)
            n_parts = max(1, -(-e // lanes_per_pass))  # ceil
            sub_e = -(-e // n_parts)
            sub_e = ((sub_e + q - 1) // q) * q  # quantum-aligned
            for lo in range(0, e, sub_e):
                hi = min(lo + sub_e, e)
                s = _Slice(
                    bi, lo, hi, sub_e, per_lane * sub_e, placement
                )
                if group and group_bytes + s.bytes > budget:
                    plan.append(group)
                    group, group_bytes = [], 0
                group.append(s)
                group_bytes += s.bytes
        if group:
            plan.append(group)
        return plan

    def _group_static_bytes(self, kind: str, group) -> int:
        """Wire bytes of one pass group's pass-invariant payloads — the
        train path's sliced blocks, or the score path's (X, row_index)
        active/passive pairs.  Budget arithmetic for the hot cache; the
        dynamic leaves (w0, coefs) stream every pass and don't count."""
        total = 0
        for s in group:
            b = self.dataset.blocks[s.block_idx]
            r, d = b.rows_per_entity, b.block_dim
            if kind == "train":
                # X, labels, weights, row_index (E,R) + col_map (E,D)
                per = 4 * (r * d + 3 * r + d)
            else:
                per = 4 * (r * d + r)  # X + row_index
                if self.dataset.passive_blocks:
                    pb = self.dataset.passive_blocks[s.block_idx]
                    if pb is not None:
                        rp = pb.rows_per_entity
                        per += 4 * (rp * d + rp)
            total += per * s.padded_e
        return total

    def _probe_hot(self, kind: str) -> dict:
        """Resident static trees by group index for this pass — one
        locked cache probe per group, before any pipeline thread
        starts (the streaming objective's hot/cold-split discipline)."""
        hot: dict = {}
        if self._hot_cache is not None:
            for gi in range(len(self.pass_plan)):
                d = self._hot_cache.get((kind, gi))
                if d is not None:
                    hot[gi] = d
        return hot

    def _extra_lane_bytes(self, block: EntityBlock) -> int:
        """Subclass hook: additional device bytes one lane costs beyond
        the raw block leaves (e.g. the factored variant's projected
        features and latent vectors)."""
        return 0

    def _budget_overhead_bytes(self) -> int:
        """Subclass hook: device bytes resident for the WHOLE pass
        (shared state like the factored projection + its gradient),
        carved out of the budget before groups are sized."""
        return 0

    def _put(self, tree):
        if self._sharding is None:
            return jax.device_put(tree)
        return jax.tree.map(
            lambda x: jax.device_put(x, self._sharding), tree
        )

    def _put_group(self, group, payloads, pack_to_default=False):
        """One pass group's transfer — one call per group on the
        transfer thread (the bounded-memory tests hook this to count
        dispatched-but-unconsumed groups)."""
        return [
            self._put_one(s.placement, p, pack_to_default)
            for s, p in zip(group, payloads)
        ]

    def _put_one(self, placement, tree, pack_to_default=False):
        """Placement-aware transfer for one slice payload.  Split slices
        shard over the mesh; packed slices land whole on their assigned
        device — except when ``pack_to_default`` (the score path: every
        scatter folds into ONE accumulator, and a packed slice committed
        to device k would force that accumulator to bounce devices)."""
        if self._sharding is None:
            return jax.device_put(tree)
        if placement[0] == "pack":
            if pack_to_default:
                return jax.device_put(tree)
            dev = self._devices[placement[1]]
            return jax.tree.map(
                lambda x: jax.device_put(x, dev), tree
            )
        return jax.tree.map(
            lambda x: jax.device_put(x, self._sharding), tree
        )

    def _run_groups(self, make_host_group, consume, pack_to_default=False):
        """Prefetch-pipelined group runner (the chunk store's ingest
        pipeline, data/prefetch.py): a PACK thread slices the next
        groups on the host, a TRANSFER thread dispatches them and waits
        out their h2d completion, and the caller thread consumes the
        current one — host slicing, the link, and device compute all
        overlap, with at most ``prefetch_depth`` groups admitted by the
        permit accounting (which replaced the old hand-rolled double
        buffer — and its reference-lifetime subtleties — outright).
        ``make_host_group(group) → host pytree list``; per-stage wall
        attribution lands in ``self.transfer_stats``."""
        plan = self.pass_plan
        self.live_groups_high_water = 0
        if not plan:
            return

        self.live_groups_high_water = run_prefetched(
            len(plan),
            lambda gi: (plan[gi], make_host_group(plan[gi])),
            lambda item: self._put_group(*item, pack_to_default),
            lambda gi, dev: consume(plan[gi], dev),
            depth=self.prefetch_depth,
            stats=self.transfer_stats,
        )

    # -- pipelined-descent prestage ----------------------------------------

    def _train_state_init(self, warm_state) -> list[np.ndarray]:
        return [
            (
                np.zeros((b.n_entities, b.block_dim), np.float32)
                if warm_state is None
                # copy: np.asarray of a jax array (checkpoint resume) is
                # a read-only zero-copy view, and this buffer is written
                # into.
                else np.array(warm_state[bi], np.float32)
            )
            for bi, b in enumerate(self.dataset.blocks)
        ]

    def _train_host_group(self, group, state, with_blocks=True) -> list:
        # with_blocks=False builds only the dynamic half (warm-start
        # lanes) — the hot-cache path, where the sliced block already
        # sits on device and packing it again would waste the savings.
        sentinel = self.dataset.n_global_rows
        out = []
        for s in group:
            block = self.dataset.blocks[s.block_idx]
            w0 = state[s.block_idx][s.lane_lo:s.lane_hi]
            pad = s.padded_e - w0.shape[0]
            if pad:
                w0 = np.pad(w0, ((0, pad), (0, 0)))
            out.append((
                _slice_block(
                    block, s.lane_lo, s.lane_hi, s.padded_e, sentinel
                ) if with_blocks else None,
                w0,
            ))
        return out

    def prestage(self, warm_state=None) -> None:
        """Background-pack the first ``prefetch_depth`` pass groups' host
        payloads while ANOTHER coordinate's solve owns the foreground
        (the pipelined descent schedule, game/descent.py).

        Packing is offset-independent — slices and warm-start lanes are
        pure functions of (dataset, plan, warm_state) — so the staged
        payloads are byte-identical to what ``train``'s pack thread
        would build, and results stay bitwise the unpipelined run's.
        The buffers are keyed to this exact ``warm_state`` object; a
        train call with any other warm state discards them.  Host RAM
        held is at most one pass budget (depth groups of budget/depth
        bytes).  The overlap actually achieved lands on the
        ``game_coordinate_overlap_seconds`` counter at take time."""
        self._drop_prestage()
        if not self.pass_plan:
            return
        n = min(self.prefetch_depth, len(self.pass_plan))
        rec = {
            "warm": warm_state,
            "buf": {},
            "t0": time.perf_counter(),
            "t_end": None,
        }

        def work():
            try:
                state = self._train_state_init(warm_state)
                for gi in range(n):
                    rec["buf"][gi] = self._train_host_group(
                        self.pass_plan[gi], state
                    )
            finally:
                rec["t_end"] = time.perf_counter()

        rec["thread"] = threading.Thread(
            target=work, name="game-ooc-prestage", daemon=True
        )
        self._prestage_rec = rec
        rec["thread"].start()

    def _drop_prestage(self) -> None:
        rec, self._prestage_rec = self._prestage_rec, None
        if rec is not None:
            rec["thread"].join()

    def _take_prestage(self, warm_state) -> dict:
        rec, self._prestage_rec = self._prestage_rec, None
        if rec is None:
            return {}
        t_take = time.perf_counter()
        rec["thread"].join()
        if rec["warm"] is not warm_state:
            # Stale hint (different warm start than announced): the
            # payloads would carry the WRONG w0 lanes — drop them.
            return {}
        overlap = max(0.0, min(rec["t_end"], t_take) - rec["t0"])
        telemetry_mod.current().counter(
            "game_coordinate_overlap_seconds"
        ).inc(overlap)
        return rec["buf"]

    # -- coordinate surface ------------------------------------------------

    def train(self, offsets: Array, warm_state=None) -> list[np.ndarray]:
        l1 = jnp.asarray(
            self.config.regularization.l1_weight(1.0) * self.reg_weight,
            jnp.float32,
        )
        l2 = jnp.asarray(
            self.config.regularization.l2_weight(1.0) * self.reg_weight,
            jnp.float32,
        )
        offsets = jnp.asarray(offsets, jnp.float32)
        # Each placement needs offsets on ITS device set — a committed
        # input pinned elsewhere (e.g. the caller's score array on
        # device 0) would clash inside the jit.  Split slices take a
        # mesh-replicated copy; each packed device gets its own
        # committed copy.  Staged once per train pass; identical bits
        # everywhere, so this never perturbs results.
        off_split = offsets
        off_by_dev = {}
        if self.mesh is not None:
            off_split = jax.device_put(
                offsets, NamedSharding(self.mesh, P())
            )
        if self.bucket_plan is not None:
            packed_devs = {
                s.placement[1]
                for group in self.pass_plan
                for s in group
                if s.placement[0] == "pack"
            }
            off_by_dev = {
                k: jax.device_put(offsets, self._devices[k])
                for k in sorted(packed_devs)
            }
        prestaged = self._take_prestage(warm_state)
        state = self._train_state_init(warm_state)
        hot = self._probe_hot("train")

        def host_group(group):
            gi = self._plan_index[id(group)]
            if gi in prestaged:
                payload = prestaged.pop(gi)
                if gi in hot:
                    # Prestage packed full payloads before this pass
                    # knew its hot set — keep just the dynamic half.
                    payload = [(None, w0) for _blk, w0 in payload]
                return payload
            return self._train_host_group(
                group, state, with_blocks=gi not in hot
            )

        def consume(group, dev):
            gi = self._plan_index[id(group)]
            # The per-device dispatch seam (mirrors the resident
            # hierarchical coordinate): a fault here aborts the update
            # mid-pass; per-bucket solves are pure functions of
            # (block, offsets, w0), so the retried update is bitwise
            # the uninterrupted one.
            chaos_mod.maybe_fail(
                "game.bucket_shard",
                coordinate=self.name,
                slices=len(group),
            )
            resident = hot.get(gi)
            blks = [
                blk if blk is not None else resident[si]
                for si, (blk, _w0) in enumerate(dev)
            ]
            # Dispatch every solve in the group first (async), then pull —
            # the pulls overlap the NEXT group's host slicing + transfer,
            # and packed slices' programs run concurrently on their
            # assigned devices.
            results = [
                self._solve_jit(
                    blk,
                    (
                        off_by_dev[s.placement[1]]
                        if s.placement[0] == "pack" and off_by_dev
                        else off_split
                    ),
                    w0, l1, l2,
                )
                for s, blk, (_b, w0) in zip(group, blks, dev)
            ]
            for s, res in zip(group, results):
                state[s.block_idx][s.lane_lo:s.lane_hi] = np.asarray(
                    res
                )[: s.lane_hi - s.lane_lo]
            if self._hot_cache is not None and resident is None:
                self._hot_cache.maybe_admit(
                    ("train", gi), blks, self._hot_bytes[("train", gi)]
                )

        self._run_groups(host_group, consume)
        return state

    def score(self, state) -> Array:
        sentinel = self.dataset.n_global_rows
        total = self._zeros_jit()
        hot = self._probe_hot("score")

        def host_group(group):
            # Score-only slices: just X + row_index (+ coefs) cross the
            # wire — labels/weights/col_map are ~30% of the lane bytes
            # and the score einsum/scatter never reads them (h2d is the
            # scarce resource on the tunneled chip).  A hot group's
            # static pair is already resident; only coefs cross.
            gi = self._plan_index[id(group)]
            resident = gi in hot
            out = []
            for s in group:
                coefs = _cut(
                    np.asarray(state[s.block_idx], np.float32),
                    s.lane_lo, s.lane_hi, s.padded_e, 0,
                )
                if resident:
                    out.append((None, None, coefs))
                    continue
                block = self.dataset.blocks[s.block_idx]
                active = (
                    _cut(block.X, s.lane_lo, s.lane_hi, s.padded_e, 0),
                    _cut(block.row_index, s.lane_lo, s.lane_hi,
                        s.padded_e, sentinel),
                )
                passive = None
                if self.dataset.passive_blocks:
                    pb = self.dataset.passive_blocks[s.block_idx]
                    if pb is not None:
                        passive = (
                            _cut(pb.X, s.lane_lo, s.lane_hi, s.padded_e, 0),
                            _cut(pb.row_index, s.lane_lo, s.lane_hi,
                                s.padded_e, sentinel),
                        )
                out.append((active, passive, coefs))
            return out

        def consume(group, dev):
            nonlocal total
            gi = self._plan_index[id(group)]
            resident = hot.get(gi)
            statics = []
            for si, (active, passive, coefs) in enumerate(dev):
                if active is None and resident is not None:
                    active, passive = resident[si]
                statics.append((active, passive))
                total = self._score_jit(total, *active, coefs)
                if passive is not None:
                    # Active/passive split: capped-out rows are never
                    # trained on but MUST be scored (coordinates train
                    # against each other's full contributions).
                    total = self._score_jit(total, *passive, coefs)
            if self._hot_cache is not None and resident is None:
                self._hot_cache.maybe_admit(
                    ("score", gi), statics, self._hot_bytes[("score", gi)]
                )

        # pack_to_default: the donated ``total`` accumulator lives on the
        # default device; a payload committed to device k would drag it
        # there and clash with the next slice.  Scatter order (slice
        # order, active then passive) is placement-independent, so the
        # score stays bitwise the unpacked one.
        self._run_groups(host_group, consume, pack_to_default=True)
        return total[: self.dataset.n_global_rows]

    def _block_variances(self, block: EntityBlock, coefs, offsets):
        """Budget-bounded override: the inherited version moves the WHOLE
        block to device for the variance Hessian — exactly the transfer
        this coordinate exists to avoid.  Reuse the pass plan's slice
        shape for this block instead."""
        bi = next(
            i for i, b in enumerate(self.dataset.blocks) if b is block
        )
        sub_e = next(
            s.padded_e
            for group in self.pass_plan
            for s in group
            if s.block_idx == bi
        )
        sentinel = self.dataset.n_global_rows
        placement = (
            ("split",)
            if self.bucket_plan is None
            else self.bucket_plan.placements[bi]
        )
        offsets = jnp.asarray(offsets, jnp.float32)
        if self.mesh is not None and placement[0] == "split":
            # Same device-set normalization as train: sharded slice
            # inputs need mesh-replicated offsets.
            offsets = jax.device_put(
                offsets, NamedSharding(self.mesh, P())
            )
        l2 = jnp.asarray(
            self.config.regularization.l2_weight(1.0) * self.reg_weight,
            jnp.float32,
        )
        coefs = np.asarray(coefs, np.float32)
        out = np.empty((block.n_entities, block.block_dim), np.float32)
        for lo in range(0, block.n_entities, sub_e):
            hi = min(lo + sub_e, block.n_entities)
            c = coefs[lo:hi]
            pad = sub_e - c.shape[0]
            if pad:
                c = np.pad(c, ((0, pad), (0, 0)))
            v = self._var_jit(
                self._put_one(
                    placement,
                    _slice_block(block, lo, hi, sub_e, sentinel),
                    pack_to_default=True,
                ),
                self._put_one(placement, c, pack_to_default=True),
                offsets, l2,
            )
            out[lo:hi] = np.asarray(v)[: hi - lo]
        return out
