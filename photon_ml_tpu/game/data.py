"""GAME data layer: fixed-effect and random-effect datasets.

The analogue of the reference's ``...ml.data`` GAME layer (SURVEY.md §2):
``GameDatum`` (per-row response/weight/offset + features-by-shard + entity
ids), ``FixedEffectDataset`` (all rows, one feature shard), and
``RandomEffectDataset`` — in the reference an RDD keyed by entity id with a
custom partitioner colocating each entity's rows, so per-entity GLMs solve
locally inside ``mapPartitions``.

TPU-first reshape: instead of per-entity JVM objects, entities are

1. **grouped** (all rows of an entity gathered together),
2. **projected** — each entity's rows only reference the feature columns that
   entity actually observes, so tiny per-entity problems don't carry the
   global dimensionality (the reference's ``LinearSubspaceProjector``), and
3. **bucketed by size** — entities with similar row counts / active-feature
   counts share one dense padded block ``(E, R, D)`` that a ``vmap``'d
   solver minimizes in one jitted program (SURVEY.md §7 step 6).

Padding discipline matches the rest of the framework: padding rows carry
weight 0; padding columns map to global column -1 and carry value 0; padding
*entities* (to fill a bucket) have all-zero weights and solve to w=0 under
any L2.

Row bookkeeping: each block row remembers its global row index so coordinate
descent can gather per-row offsets in and scatter per-row scores out
(the analogue of the reference's score joins on unique id).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.dataset import GlmData

Array = jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["X", "labels", "weights", "col_map", "row_index"],
    meta_fields=["n_entities", "rows_per_entity", "block_dim"],
)
@dataclasses.dataclass
class EntityBlock:
    """One size-bucket of entities as a dense padded batch.

    ``X[e, r, k]`` is the value of local feature k in row r of entity e;
    ``col_map[e, k]`` maps local feature k to its global column (or -1).
    ``row_index[e, r]`` is the row's index in the global dataset (or the
    sentinel ``n_global_rows`` for padding — callers gather from arrays
    padded with one trailing zero slot).
    """

    X: Array  # (E, R, D) float
    labels: Array  # (E, R)
    weights: Array  # (E, R) — 0 for padding rows / entities
    col_map: Array  # (E, D) int32 — global column ids, -1 pad
    row_index: Array  # (E, R) int32 — global row ids, sentinel pad
    n_entities: int
    rows_per_entity: int
    block_dim: int


@dataclasses.dataclass
class RandomEffectDataset:
    """All buckets for one random-effect coordinate + host-side id maps.

    ``entity_ids[b][e]`` is the entity key of lane e in bucket b;
    ``entity_to_slot`` maps entity key → (bucket, lane).

    ``passive_blocks[b]`` (None when no entity in bucket b exceeds the
    active-set cap) holds the rows beyond ``max_rows_per_entity`` — the
    reference's active/passive split: passive rows are never TRAINED on, but
    they must still be SCORED during coordinate descent or the other
    coordinates would train against offsets missing this coordinate's
    contribution for those rows.  Lanes align with the active block (same
    entity order, same col_map), so the trained (E, D) coefficients apply
    directly; passive-row features outside the entity's active subspace drop,
    as the reference's projector-based scoring does.
    """

    blocks: list[EntityBlock]
    entity_ids: list[list]
    entity_to_slot: dict
    n_global_rows: int
    n_features: int  # global feature-space width of this coordinate's shard
    passive_blocks: list[Optional[EntityBlock]] = dataclasses.field(
        default_factory=list
    )

    @property
    def n_entities(self) -> int:
        return len(self.entity_to_slot)


@dataclasses.dataclass
class FixedEffectDataset:
    """All rows against one feature shard (reference: FixedEffectDataset)."""

    data: GlmData
    n_global_rows: int


@dataclasses.dataclass
class GameData:
    """Per-coordinate datasets over one global row space (the analogue of the
    reference's per-coordinate dataset map inside GameEstimator).

    labels/weights are global row arrays shared by every coordinate;
    ``base_offsets`` are the user-supplied per-row offsets (GameDatum.offset).
    """

    coordinates: dict  # name -> FixedEffectDataset | RandomEffectDataset
    labels: np.ndarray
    weights: np.ndarray
    base_offsets: np.ndarray

    @property
    def n_rows(self) -> int:
        return len(self.labels)


def _round_up_geometric(n: int, growth: float, floor: int = 1) -> int:
    """Smallest bucket size >= n on the geometric grid floor·growth^k.

    growth=2.0 reproduces the pow2 grid; larger growth consolidates the
    long tail into fewer buckets — fewer compiled block programs and fewer
    per-pass dispatches, at the cost of more padding FLOPs (the
    shape-consolidation policy knob; VERDICT round 1, weak #6)."""
    if growth <= 1.0:
        raise ValueError(f"bucket growth must be > 1, got {growth}")
    n = max(n, floor)
    v = floor
    while v < n:
        v = max(v + 1, int(math.ceil(v * growth)))
    return v


def build_random_effect_dataset(
    entity_keys: Sequence,
    rows_csr,  # scipy CSR (n_rows, d) — this coordinate's feature shard
    labels: np.ndarray,
    weights: np.ndarray,
    max_rows_per_entity: Optional[int] = None,
    dtype=jnp.float32,
    device: bool = True,
    bucket_growth: float = 2.0,
    allow_missing: bool = False,
) -> RandomEffectDataset:
    """Group rows by entity, project to per-entity subspaces, bucket by size.

    ``max_rows_per_entity`` is the reference's active-set cap: entities with
    more rows train on a uniformly-spaced subset; the remaining (passive)
    rows land in score-only ``passive_blocks``.

    ``bucket_growth`` sets the geometric bucket grid (2.0 = pow2; larger
    values consolidate long-tailed size distributions into fewer buckets —
    fewer compiled programs / dispatches per CD pass, more padding).

    Entity keys are canonicalized to STRINGS — the on-disk model format
    (Avro entityId) is string-keyed, so training with int keys and scoring
    after reload must agree.  ``device=False`` keeps blocks as host numpy
    arrays (pure-host scoring paths avoid the device round trip).
    """
    import scipy.sparse as sp

    rows_csr = sp.csr_matrix(rows_csr)
    rows_csr.sum_duplicates()
    n_rows, d = rows_csr.shape
    entity_keys = np.asarray(entity_keys)
    assert entity_keys.shape[0] == n_rows
    if entity_keys.dtype == object:
        missing = sum(1 for k in entity_keys if k is None)
        if missing and not allow_missing:
            # TRAINING: a row with no entity id is a data error (it would
            # silently train some entity on foreign rows).
            raise ValueError(
                f"{missing} of {n_rows} rows have no entity id for this "
                "random effect (records missing the id column?)"
            )
        if missing:
            # SCORING (allow_missing): id-less rows simply get no
            # contribution from this coordinate — the reference's
            # join-miss semantics.  Drop them from the grouping; the
            # score scatter covers only grouped rows, everything else
            # stays 0.
            keep = np.array([k is not None for k in entity_keys])
            rows_kept = np.flatnonzero(keep)
            if rows_kept.size == 0:
                # Every row id-less (e.g. one streamed scoring block):
                # this coordinate contributes nothing to any row.
                return RandomEffectDataset(
                    blocks=[],
                    entity_ids=[],
                    entity_to_slot={},
                    n_global_rows=n_rows,
                    n_features=d,
                    passive_blocks=[],
                )
            ds = build_random_effect_dataset(
                entity_keys[keep], rows_csr[rows_kept], labels[keep],
                weights[keep], max_rows_per_entity=max_rows_per_entity,
                dtype=dtype, device=device, bucket_growth=bucket_growth,
            )
            # Re-point every block's row indices at the ORIGINAL row
            # space (scatter targets), keeping the sentinel padding slot.
            remap = np.concatenate([rows_kept, [n_rows]]).astype(np.int64)
            kept_n = int(keep.sum())

            def _repoint(block):
                if block is None:  # bucket with no passive rows
                    return None
                ri = np.asarray(block.row_index)
                ri = np.where(ri >= kept_n, kept_n, ri)  # sentinel slot
                new_ri = (
                    jnp.asarray(remap[ri])
                    if isinstance(block.row_index, jax.Array)
                    else remap[ri]
                )
                return dataclasses.replace(block, row_index=new_ri)

            return dataclasses.replace(
                ds,
                blocks=[_repoint(b) for b in ds.blocks],
                passive_blocks=(
                    [_repoint(b) for b in ds.passive_blocks]
                    if ds.passive_blocks else ds.passive_blocks
                ),
                n_global_rows=n_rows,
            )
    entity_keys = entity_keys.astype(str)
    _asarray = (lambda x, dt=None: jnp.asarray(x, dt)) if device else (
        lambda x, dt=None: np.asarray(x, dt) if dt else np.asarray(x)
    )

    # Group row indices by entity.
    order = np.argsort(entity_keys, kind="stable")
    sorted_keys = entity_keys[order]
    boundaries = np.flatnonzero(
        np.concatenate([[True], sorted_keys[1:] != sorted_keys[:-1]])
    )
    # (key, active_rows, passive_rows, active_cols, active_row_slice)
    groups: list[tuple] = []
    for gi, start in enumerate(boundaries):
        end = boundaries[gi + 1] if gi + 1 < len(boundaries) else len(order)
        ridx = order[start:end]
        passive = np.empty(0, ridx.dtype)
        if max_rows_per_entity is not None and len(ridx) > max_rows_per_entity:
            keep = np.linspace(0, len(ridx) - 1, max_rows_per_entity).astype(int)
            mask = np.zeros(len(ridx), bool)
            mask[keep] = True
            passive = ridx[~mask]
            ridx = ridx[mask]
        # The CSR row slice is the dominant host cost at millions of
        # entities; slice once and reuse it in the bucket-fill loop.
        sub = rows_csr[ridx]
        active = np.unique(sub.indices)
        groups.append((sorted_keys[start], ridx, passive, active, sub))

    # GROUP by the geometric (row count, active-feature count) grid, but
    # PAD each block only to its members' actual maxima: the geometric
    # key bounds the bucket COUNT (compile count per dataset), while the
    # per-bucket entity count E already makes every block shape unique —
    # so tight padding costs no extra compiles and cuts the padded bytes
    # every objective evaluation touches (the zipf cap at 128 rows used
    # to pad to the 256 grid point: 2x pure waste on the biggest block).
    buckets: dict[tuple[int, int], list[int]] = {}
    for i, (_, ridx, _passive, active, _sub) in enumerate(groups):
        key = (
            _round_up_geometric(len(ridx), bucket_growth),
            _round_up_geometric(len(active), bucket_growth),
        )
        buckets.setdefault(key, []).append(i)

    blocks: list[EntityBlock] = []
    passive_blocks: list[Optional[EntityBlock]] = []
    ids_per_block: list[list] = []
    entity_to_slot: dict = {}
    for _key, members in sorted(buckets.items()):
        E = len(members)
        R = max(len(groups[gi][1]) for gi in members)
        D = max(1, max(len(groups[gi][3]) for gi in members))
        X = np.zeros((E, R, D), np.float32)
        lab = np.zeros((E, R), np.float32)
        wts = np.zeros((E, R), np.float32)
        cmap = np.full((E, D), -1, np.int32)
        rindex = np.full((E, R), n_rows, np.int32)  # sentinel
        ids: list = []
        for lane, gi in enumerate(members):
            key, ridx, _passive, active, sub = groups[gi]
            ids.append(key)
            entity_to_slot[key] = (len(blocks), lane)
            cmap[lane, : len(active)] = active
            # Project this entity's rows into its active subspace.
            X[lane, : len(ridx), : len(active)] = sub[:, active].toarray()
            lab[lane, : len(ridx)] = labels[ridx]
            wts[lane, : len(ridx)] = weights[ridx]
            rindex[lane, : len(ridx)] = ridx
        blocks.append(
            EntityBlock(
                X=_asarray(X, dtype),
                labels=_asarray(lab),
                weights=_asarray(wts),
                col_map=_asarray(cmap),
                row_index=_asarray(rindex),
                n_entities=E,
                rows_per_entity=R,
                block_dim=D,
            )
        )
        ids_per_block.append(ids)

        # Score-only passive companion block, lane-aligned with the active
        # block (same entity order and col_map).
        max_passive = max(
            (len(groups[gi][2]) for gi in members), default=0
        )
        if max_passive == 0:
            passive_blocks.append(None)
            continue
        Rp = max_passive  # tight, like the active block's R
        Xp = np.zeros((E, Rp, D), np.float32)
        labp = np.zeros((E, Rp), np.float32)
        wtsp = np.zeros((E, Rp), np.float32)
        rindexp = np.full((E, Rp), n_rows, np.int32)
        for lane, gi in enumerate(members):
            _key, _ridx, passive, active, _sub = groups[gi]
            if len(passive) == 0:
                continue
            # Features outside the entity's ACTIVE subspace drop here, as in
            # the reference's projected scoring.
            Xp[lane, : len(passive), : len(active)] = (
                rows_csr[passive][:, active].toarray()
            )
            labp[lane, : len(passive)] = labels[passive]
            wtsp[lane, : len(passive)] = weights[passive]
            rindexp[lane, : len(passive)] = passive
        passive_blocks.append(
            EntityBlock(
                X=_asarray(Xp, dtype),
                labels=_asarray(labp),
                weights=_asarray(wtsp),
                col_map=blocks[-1].col_map,
                row_index=_asarray(rindexp),
                n_entities=E,
                rows_per_entity=Rp,
                block_dim=D,
            )
        )

    return RandomEffectDataset(
        blocks=blocks,
        entity_ids=ids_per_block,
        entity_to_slot=entity_to_slot,
        n_global_rows=n_rows,
        n_features=d,
        passive_blocks=passive_blocks,
    )
