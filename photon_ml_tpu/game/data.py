"""GAME data layer: fixed-effect and random-effect datasets.

The analogue of the reference's ``...ml.data`` GAME layer (SURVEY.md §2):
``GameDatum`` (per-row response/weight/offset + features-by-shard + entity
ids), ``FixedEffectDataset`` (all rows, one feature shard), and
``RandomEffectDataset`` — in the reference an RDD keyed by entity id with a
custom partitioner colocating each entity's rows, so per-entity GLMs solve
locally inside ``mapPartitions``.

TPU-first reshape: instead of per-entity JVM objects, entities are

1. **grouped** (all rows of an entity gathered together),
2. **projected** — each entity's rows only reference the feature columns that
   entity actually observes, so tiny per-entity problems don't carry the
   global dimensionality (the reference's ``LinearSubspaceProjector``), and
3. **bucketed by size** — entities with similar row counts / active-feature
   counts share one dense padded block ``(E, R, D)`` that a ``vmap``'d
   solver minimizes in one jitted program (SURVEY.md §7 step 6).

Padding discipline matches the rest of the framework: padding rows carry
weight 0; padding columns map to global column -1 and carry value 0; padding
*entities* (to fill a bucket) have all-zero weights and solve to w=0 under
any L2.

Row bookkeeping: each block row remembers its global row index so coordinate
descent can gather per-row offsets in and scatter per-row scores out
(the analogue of the reference's score joins on unique id).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.dataset import GlmData

Array = jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["X", "labels", "weights", "col_map", "row_index"],
    meta_fields=["n_entities", "rows_per_entity", "block_dim"],
)
@dataclasses.dataclass
class EntityBlock:
    """One size-bucket of entities as a dense padded batch.

    ``X[e, r, k]`` is the value of local feature k in row r of entity e;
    ``col_map[e, k]`` maps local feature k to its global column (or -1).
    ``row_index[e, r]`` is the row's index in the global dataset (or the
    sentinel ``n_global_rows`` for padding — callers gather from arrays
    padded with one trailing zero slot).
    """

    X: Array  # (E, R, D) float
    labels: Array  # (E, R)
    weights: Array  # (E, R) — 0 for padding rows / entities
    col_map: Array  # (E, D) int32 — global column ids, -1 pad
    row_index: Array  # (E, R) int32 — global row ids, sentinel pad
    n_entities: int
    rows_per_entity: int
    block_dim: int


@dataclasses.dataclass
class RandomEffectDataset:
    """All buckets for one random-effect coordinate + host-side id maps.

    ``entity_ids[b][e]`` is the entity key of lane e in bucket b;
    ``entity_to_slot`` maps entity key → (bucket, lane).

    ``passive_blocks[b]`` (None when no entity in bucket b exceeds the
    active-set cap) holds the rows beyond ``max_rows_per_entity`` — the
    reference's active/passive split: passive rows are never TRAINED on, but
    they must still be SCORED during coordinate descent or the other
    coordinates would train against offsets missing this coordinate's
    contribution for those rows.  Lanes align with the active block (same
    entity order, same col_map), so the trained (E, D) coefficients apply
    directly; passive-row features outside the entity's active subspace drop,
    as the reference's projector-based scoring does.
    """

    blocks: list[EntityBlock]
    entity_ids: list[list]
    entity_to_slot: dict
    n_global_rows: int
    n_features: int  # global feature-space width of this coordinate's shard
    passive_blocks: list[Optional[EntityBlock]] = dataclasses.field(
        default_factory=list
    )
    # Padding accounting from build time (docs/performance.md
    # "Hierarchical execution"): padded = Σ_blocks E·R·D over the
    # realized block shapes, exact = Σ_entities r·max(d, 1).  Their
    # ratio is the `game_bucket_padding_ratio` gauge and the repacker
    # A/B's objective; 0 means the dataset predates the accounting
    # (host-rebuilt scoring paths).
    padded_flops: int = 0
    exact_flops: int = 0

    @property
    def n_entities(self) -> int:
        return len(self.entity_to_slot)

    @property
    def padding_ratio(self) -> float:
        """Padded/exact FLOPs of the realized bucket ladder (>= 1.0)."""
        return (
            self.padded_flops / self.exact_flops if self.exact_flops else 1.0
        )


@dataclasses.dataclass
class FixedEffectDataset:
    """All rows against one feature shard (reference: FixedEffectDataset)."""

    data: GlmData
    n_global_rows: int


@dataclasses.dataclass
class GameData:
    """Per-coordinate datasets over one global row space (the analogue of the
    reference's per-coordinate dataset map inside GameEstimator).

    labels/weights are global row arrays shared by every coordinate;
    ``base_offsets`` are the user-supplied per-row offsets (GameDatum.offset).
    """

    coordinates: dict  # name -> FixedEffectDataset | RandomEffectDataset
    labels: np.ndarray
    weights: np.ndarray
    base_offsets: np.ndarray

    @property
    def n_rows(self) -> int:
        return len(self.labels)


def _round_up_geometric(n: int, growth: float, floor: int = 1) -> int:
    """Smallest bucket size >= n on the geometric grid floor·growth^k.

    growth=2.0 reproduces the pow2 grid; larger growth consolidates the
    long tail into fewer buckets — fewer compiled block programs and fewer
    per-pass dispatches, at the cost of more padding FLOPs (the
    shape-consolidation policy knob; VERDICT round 1, weak #6)."""
    if growth <= 1.0:
        raise ValueError(f"bucket growth must be > 1, got {growth}")
    n = max(n, floor)
    v = floor
    while v < n:
        v = max(v + 1, int(math.ceil(v * growth)))
    return v


@dataclasses.dataclass(frozen=True)
class RepackPlan:
    """A cost-model bucket plan: K bucket shapes + the entity→bucket map.

    ``shapes`` is ``(K, 2)`` int64 ``(rows, dims)`` sorted ascending;
    ``assignment[e]`` is entity e's bucket.  ``padded_flops`` is the
    plan's Σ n·R·D cost over PLAN shapes (realized blocks pad tighter —
    to member maxima — so the realized ratio only improves on this).
    """

    shapes: np.ndarray  # (K, 2) int64
    assignment: np.ndarray  # (n_entities,) int64
    padded_flops: int
    exact_flops: int


#: Distinct (rows, dims) shapes above which the repacker pre-quantizes
#: on a fine geometric grid before the O(K²)-per-merge greedy runs.
_REPACK_MAX_DISTINCT = 256


def plan_entity_buckets(
    row_counts,
    col_counts,
    program_budget: int = 16,
    seed: int = 0,
) -> RepackPlan:
    """Cost-model entity repacker: pick ≤ ``program_budget`` bucket
    shapes minimizing padded FLOPs for the observed per-entity sizes.

    Replaces the static geometric ladder with a plan driven by the
    actual (row count, active-feature count) distribution
    (data/stats.py ``entity_shape_histogram``).  Greedy agglomeration:
    start from every distinct shape as its own bucket (zero padding,
    too many compiled programs), then repeatedly merge the pair whose
    merged bucket (elementwise-max shape) adds the fewest padded FLOPs,
    until the compiled-program-count budget holds.  Fully
    deterministic: shapes are processed in sorted order, ties break on
    the first (lexicographically smallest) pair, and ``seed`` only
    feeds the optional entity subsample for very large populations
    (``entity_shape_histogram``).
    """
    from photon_ml_tpu.data.stats import entity_shape_histogram

    if program_budget < 1:
        raise ValueError(
            f"program_budget must be >= 1, got {program_budget}"
        )
    shapes, counts, inverse = entity_shape_histogram(
        row_counts, col_counts, seed=seed
    )
    exact = int(
        np.sum(
            np.asarray(row_counts, np.int64)
            * np.maximum(np.asarray(col_counts, np.int64), 1)
        )
    )
    if len(shapes) == 0:
        return RepackPlan(
            shapes=np.zeros((0, 2), np.int64),
            assignment=np.zeros(0, np.int64),
            padded_flops=0, exact_flops=0,
        )

    # Pre-quantize a pathologically diverse shape population so each
    # greedy step stays a small dense matrix: snap to a fine geometric
    # grid (far finer than the ladder this replaces) and re-unique.
    shape_to_slot = np.arange(len(shapes))
    if len(shapes) > _REPACK_MAX_DISTINCT:
        growth = 1.05
        while True:
            q = np.stack(
                [
                    [_round_up_geometric(int(r), growth) for r in shapes[:, 0]],
                    [_round_up_geometric(int(c), growth) for c in shapes[:, 1]],
                ],
                axis=1,
            )
            qshapes, qinv = np.unique(q, axis=0, return_inverse=True)
            if len(qshapes) <= _REPACK_MAX_DISTINCT:
                break
            growth *= 1.1
        qcounts = np.bincount(
            qinv, weights=counts.astype(np.float64), minlength=len(qshapes)
        ).astype(np.int64)
        shape_to_slot = qinv
        shapes, counts = qshapes.astype(np.int64), qcounts

    # Greedy agglomeration over (R, D, n, cost) bucket rows.  `members`
    # tracks which initial slots each surviving bucket absorbed.
    R = shapes[:, 0].astype(np.int64)
    D = shapes[:, 1].astype(np.int64)
    N = counts.astype(np.int64)
    C = N * R * D
    members: list[list[int]] = [[i] for i in range(len(shapes))]
    alive = np.ones(len(shapes), bool)

    def _merge_pass(free_only: bool) -> None:
        nonlocal R, D, N, C
        while True:
            idx = np.flatnonzero(alive)
            if len(idx) <= 1 or (
                not free_only and len(idx) <= program_budget
            ):
                break
            Ra, Da, Na, Ca = R[idx], D[idx], N[idx], C[idx]
            Rm = np.maximum(Ra[:, None], Ra[None, :])
            Dm = np.maximum(Da[:, None], Da[None, :])
            delta = (Na[:, None] + Na[None, :]) * Rm * Dm \
                - Ca[:, None] - Ca[None, :]
            iu = np.triu_indices(len(idx), k=1)
            flat = delta[iu]
            if free_only and flat.min() > 0:
                break
            # argmin over the upper triangle is (i, j)-lexicographic on
            # ties — buckets were built from SORTED shapes, so the
            # winner is deterministic.
            k = int(np.argmin(flat))
            a, b = idx[iu[0][k]], idx[iu[1][k]]
            R[a] = max(R[a], R[b])
            D[a] = max(D[a], D[b])
            N[a] += N[b]
            C[a] = N[a] * R[a] * D[a]
            members[a].extend(members[b])
            alive[b] = False

    # Paid merges down to the program budget, then a free coalesce:
    # merging can leave two buckets with IDENTICAL shapes (distinct
    # ancestors growing to the same maxima) — folding those costs zero
    # padding and saves a compiled program, so always take them.
    _merge_pass(free_only=False)
    _merge_pass(free_only=True)

    kept = np.flatnonzero(alive)
    order = np.lexsort((D[kept], R[kept]))
    kept = kept[order]
    plan_shapes = np.stack([R[kept], D[kept]], axis=1)
    slot_to_bucket = np.empty(
        int(shape_to_slot.max()) + 1 if len(shape_to_slot) else 0, np.int64
    )
    for bi, ki in enumerate(kept):
        for slot in members[ki]:
            slot_to_bucket[slot] = bi
    assignment = slot_to_bucket[shape_to_slot[inverse]]
    padded = int(np.sum(C[kept]))
    return RepackPlan(
        shapes=plan_shapes,
        assignment=assignment,
        padded_flops=padded,
        exact_flops=exact,
    )


def build_random_effect_dataset(
    entity_keys: Sequence,
    rows_csr,  # scipy CSR (n_rows, d) — this coordinate's feature shard
    labels: np.ndarray,
    weights: np.ndarray,
    max_rows_per_entity: Optional[int] = None,
    dtype=jnp.float32,
    device: bool = True,
    bucket_growth: float = 2.0,
    allow_missing: bool = False,
    repack: str = "geometric",
    program_budget: int = 16,
    repack_seed: int = 0,
) -> RandomEffectDataset:
    """Group rows by entity, project to per-entity subspaces, bucket by size.

    ``max_rows_per_entity`` is the reference's active-set cap: entities with
    more rows train on a uniformly-spaced subset; the remaining (passive)
    rows land in score-only ``passive_blocks``.

    ``bucket_growth`` sets the geometric bucket grid (2.0 = pow2; larger
    values consolidate long-tailed size distributions into fewer buckets —
    fewer compiled programs / dispatches per CD pass, more padding).

    Entity keys are canonicalized to STRINGS — the on-disk model format
    (Avro entityId) is string-keyed, so training with int keys and scoring
    after reload must agree.  ``device=False`` keeps blocks as host numpy
    arrays (pure-host scoring paths avoid the device round trip).
    """
    import scipy.sparse as sp

    rows_csr = sp.csr_matrix(rows_csr)
    rows_csr.sum_duplicates()
    n_rows, d = rows_csr.shape
    entity_keys = np.asarray(entity_keys)
    assert entity_keys.shape[0] == n_rows
    if entity_keys.dtype == object:
        missing = sum(1 for k in entity_keys if k is None)
        if missing and not allow_missing:
            # TRAINING: a row with no entity id is a data error (it would
            # silently train some entity on foreign rows).
            raise ValueError(
                f"{missing} of {n_rows} rows have no entity id for this "
                "random effect (records missing the id column?)"
            )
        if missing:
            # SCORING (allow_missing): id-less rows simply get no
            # contribution from this coordinate — the reference's
            # join-miss semantics.  Drop them from the grouping; the
            # score scatter covers only grouped rows, everything else
            # stays 0.
            keep = np.array([k is not None for k in entity_keys])
            rows_kept = np.flatnonzero(keep)
            if rows_kept.size == 0:
                # Every row id-less (e.g. one streamed scoring block):
                # this coordinate contributes nothing to any row.
                return RandomEffectDataset(
                    blocks=[],
                    entity_ids=[],
                    entity_to_slot={},
                    n_global_rows=n_rows,
                    n_features=d,
                    passive_blocks=[],
                )
            ds = build_random_effect_dataset(
                entity_keys[keep], rows_csr[rows_kept], labels[keep],
                weights[keep], max_rows_per_entity=max_rows_per_entity,
                dtype=dtype, device=device, bucket_growth=bucket_growth,
                repack=repack, program_budget=program_budget,
                repack_seed=repack_seed,
            )
            # Re-point every block's row indices at the ORIGINAL row
            # space (scatter targets), keeping the sentinel padding slot.
            remap = np.concatenate([rows_kept, [n_rows]]).astype(np.int64)
            kept_n = int(keep.sum())

            def _repoint(block):
                if block is None:  # bucket with no passive rows
                    return None
                ri = np.asarray(block.row_index)
                ri = np.where(ri >= kept_n, kept_n, ri)  # sentinel slot
                new_ri = (
                    jnp.asarray(remap[ri])
                    if isinstance(block.row_index, jax.Array)
                    else remap[ri]
                )
                return dataclasses.replace(block, row_index=new_ri)

            return dataclasses.replace(
                ds,
                blocks=[_repoint(b) for b in ds.blocks],
                passive_blocks=(
                    [_repoint(b) for b in ds.passive_blocks]
                    if ds.passive_blocks else ds.passive_blocks
                ),
                n_global_rows=n_rows,
            )
    entity_keys = entity_keys.astype(str)
    _asarray = (lambda x, dt=None: jnp.asarray(x, dt)) if device else (
        lambda x, dt=None: np.asarray(x, dt) if dt else np.asarray(x)
    )

    # Group rows by entity — FLAT-ARRAY pipeline throughout.  A previous
    # version sliced scipy CSR per entity (rows_csr[ridx] then
    # sub[:, active]); at 100k entities those 200k __getitem__ calls
    # spent ~26 s in scipy index validation for ~2 s of real work.
    # Everything below runs on the raw indptr/indices/data arrays of ONE
    # bulk row gather, with per-bucket flat scatters filling the blocks.
    order = np.argsort(entity_keys, kind="stable")
    n_sorted = len(order)
    if n_sorted == 0:
        return RandomEffectDataset(
            blocks=[], entity_ids=[], entity_to_slot={},
            n_global_rows=n_rows, n_features=d, passive_blocks=[],
        )
    sorted_keys = entity_keys[order]
    starts = np.flatnonzero(
        np.concatenate([[True], sorted_keys[1:] != sorted_keys[:-1]])
    )
    ends = np.append(starts[1:], n_sorted)
    span_sizes = ends - starts
    n_ent = len(starts)
    ent_keys = sorted_keys[starts]

    # Active-set cap (the reference's split): capped entities keep a
    # uniformly-spaced row subset, the rest become score-only passive
    # rows.  keep is over SORTED positions; only capped entities loop.
    keep = np.ones(n_sorted, bool)
    if max_rows_per_entity is not None:
        for g in np.flatnonzero(span_sizes > max_rows_per_entity):
            m = np.zeros(span_sizes[g], bool)
            m[np.linspace(
                0, span_sizes[g] - 1, max_rows_per_entity
            ).astype(int)] = True
            keep[starts[g]:ends[g]] = m

    ent_of_pos = np.repeat(np.arange(n_ent), span_sizes)
    # Local row index within the entity's kept (resp. passive) rows.
    kept_counts = np.bincount(ent_of_pos, weights=keep, minlength=n_ent
                              ).astype(np.int64)
    kept_before = np.concatenate([[0], np.cumsum(kept_counts)[:-1]])
    local_kept = (np.cumsum(keep) - 1) - kept_before[ent_of_pos]
    psv = ~keep
    psv_counts = np.bincount(ent_of_pos, weights=psv, minlength=n_ent
                             ).astype(np.int64)
    psv_before = np.concatenate([[0], np.cumsum(psv_counts)[:-1]])
    local_psv = (np.cumsum(psv) - 1) - psv_before[ent_of_pos]

    sorted_csr = rows_csr[order]  # one bulk row gather
    nnz_per_row = np.diff(sorted_csr.indptr)
    ent_of_nnz = np.repeat(ent_of_pos, nnz_per_row)
    pos_of_nnz = np.repeat(np.arange(n_sorted), nnz_per_row)
    nnz_keep = keep[pos_of_nnz]

    # Per-entity ACTIVE columns (from kept rows only, as the reference's
    # projector sees them): one global unique over (entity, column) keys.
    # upair is sorted entity-major, so each entity's active columns come
    # out ascending — the same order np.unique(sub.indices) produced.
    pair = ent_of_nnz.astype(np.int64) * d + sorted_csr.indices
    upair, inv_kept = np.unique(pair[nnz_keep], return_inverse=True)
    act_ent = (upair // d).astype(np.int64)
    act_col = (upair % d).astype(np.int32)
    act_counts = np.bincount(act_ent, minlength=n_ent).astype(np.int64)
    act_before = np.concatenate([[0], np.cumsum(act_counts)[:-1]])

    # GROUP entities into buckets, PADDING each block only to its
    # members' actual maxima: the grouping key bounds the bucket COUNT
    # (compile count per dataset), while the per-bucket entity count E
    # already makes every block shape unique — so tight padding costs
    # no extra compiles and cuts the padded bytes every objective
    # evaluation touches.
    #
    # Two grouping policies (docs/performance.md "Hierarchical
    # execution"):
    #  - "geometric" (default): the static ladder — key by
    #    (geo(rows), geo(dims)) on the floor·growth^k grid.
    #  - "cost_model": plan_entity_buckets fits ≤ program_budget bucket
    #    shapes to the OBSERVED size distribution, minimizing padded
    #    FLOPs.  Same downstream machinery; only the membership map
    #    changes.  NOTE: regrouping changes realized block shapes, and
    #    XLA reduction tiling varies with padded length — repacked
    #    coefficients are the same math but not bit-for-bit the
    #    ladder's (unlike sharding/pipelining, which preserve the plan
    #    and are bitwise; measured in docs/performance.md).
    if repack == "cost_model":
        from photon_ml_tpu.chaos import core as chaos_mod

        chaos_mod.maybe_fail(
            "game.repack", n_entities=n_ent, budget=program_budget
        )
        plan = plan_entity_buckets(
            kept_counts, act_counts, program_budget=program_budget,
            seed=repack_seed,
        )
        buckets: dict[tuple[int, int], list[int]] = {}
        for g in range(n_ent):
            bi = int(plan.assignment[g])
            key = (int(plan.shapes[bi, 0]), int(plan.shapes[bi, 1]))
            buckets.setdefault(key, []).append(g)
    elif repack == "geometric":
        geo = {}

        def _geo(v: int) -> int:
            if v not in geo:
                geo[v] = _round_up_geometric(v, bucket_growth)
            return geo[v]

        buckets = {}
        for g in range(n_ent):
            key = (_geo(int(kept_counts[g])), _geo(int(act_counts[g])))
            buckets.setdefault(key, []).append(g)
    else:
        raise ValueError(
            f"repack must be 'geometric' or 'cost_model', got {repack!r}"
        )

    # lane_of_ent/block_of_ent drive every flat scatter below.
    lane_of_ent = np.empty(n_ent, np.int64)
    block_of_ent = np.full(n_ent, -1, np.int64)
    ordered_buckets = []
    for bi, (_key, members) in enumerate(sorted(buckets.items())):
        m = np.asarray(members, np.int64)
        ordered_buckets.append(m)
        lane_of_ent[m] = np.arange(len(m))
        block_of_ent[m] = bi

    labels = np.asarray(labels)
    weights = np.asarray(weights)
    row_of_pos = order  # global row id of each sorted position
    blocks: list[EntityBlock] = []
    passive_blocks: list[Optional[EntityBlock]] = []
    ids_per_block: list[list] = []
    entity_to_slot: dict = {}
    for bi, m in enumerate(ordered_buckets):
        E = len(m)
        R = int(kept_counts[m].max())
        D = max(1, int(act_counts[m].max()))
        in_b = np.zeros(n_ent, bool)
        in_b[m] = True

        # Row-level fills: labels/weights/row_index at (lane, local_row).
        sel = in_b[ent_of_pos] & keep
        lane_r = lane_of_ent[ent_of_pos[sel]]
        lrow = local_kept[sel]
        lab = np.zeros((E, R), np.float32)
        wts = np.zeros((E, R), np.float32)
        rindex = np.full((E, R), n_rows, np.int32)  # sentinel
        rows_sel = row_of_pos[sel]
        lab[lane_r, lrow] = labels[rows_sel]
        wts[lane_r, lrow] = weights[rows_sel]
        rindex[lane_r, lrow] = rows_sel

        # col_map: each unique active (entity, col) lands at its rank
        # within the entity's active list.
        cmap = np.full((E, D), -1, np.int32)
        a_sel = in_b[act_ent]
        local_c = (np.arange(len(upair)) - act_before[act_ent])[a_sel]
        cmap[lane_of_ent[act_ent[a_sel]], local_c] = act_col[a_sel]

        # X: every kept nnz of the bucket scatters to
        # (lane, local_row, local_col); duplicates were pre-summed.
        n_sel = in_b[ent_of_nnz] & nnz_keep
        n_sel_k = n_sel[nnz_keep]  # same nnz, indexed in kept-nnz space
        e_n = ent_of_nnz[n_sel]
        X = np.zeros((E, R, D), np.float32)
        X[
            lane_of_ent[e_n],
            local_kept[pos_of_nnz[n_sel]],
            inv_kept[n_sel_k] - act_before[e_n],
        ] = sorted_csr.data[n_sel]

        ids = list(ent_keys[m])
        for lane, key in enumerate(ids):
            entity_to_slot[key] = (bi, lane)
        blocks.append(
            EntityBlock(
                X=_asarray(X, dtype),
                labels=_asarray(lab),
                weights=_asarray(wts),
                col_map=_asarray(cmap),
                row_index=_asarray(rindex),
                n_entities=E,
                rows_per_entity=R,
                block_dim=D,
            )
        )
        ids_per_block.append(ids)

        # Score-only passive companion block, lane-aligned with the
        # active block (same entity order and col_map).
        Rp = int(psv_counts[m].max()) if len(m) else 0
        if Rp == 0:
            passive_blocks.append(None)
            continue
        selp = in_b[ent_of_pos] & psv
        lane_p = lane_of_ent[ent_of_pos[selp]]
        lrow_p = local_psv[selp]
        rows_p = row_of_pos[selp]
        labp = np.zeros((E, Rp), np.float32)
        wtsp = np.zeros((E, Rp), np.float32)
        rindexp = np.full((E, Rp), n_rows, np.int32)
        labp[lane_p, lrow_p] = labels[rows_p]
        wtsp[lane_p, lrow_p] = weights[rows_p]
        rindexp[lane_p, lrow_p] = rows_p

        # Passive features project onto the ACTIVE subspace (features the
        # entity never trained on drop, as in the reference's projected
        # scoring): locate each passive nnz's (entity, col) in the sorted
        # unique-pair table; misses drop.
        Xp = np.zeros((E, Rp, D), np.float32)
        np_sel = in_b[ent_of_nnz] & ~nnz_keep
        if len(upair):  # no active pairs at all → every passive nnz drops
            p_pair = pair[np_sel]
            ss = np.searchsorted(upair, p_pair)
            hit = (ss < len(upair)) & (
                upair[np.minimum(ss, len(upair) - 1)] == p_pair
            )
            e_p = ent_of_nnz[np_sel][hit]
            Xp[
                lane_of_ent[e_p],
                local_psv[pos_of_nnz[np_sel][hit]],
                ss[hit] - act_before[e_p],
            ] = sorted_csr.data[np_sel][hit]
        passive_blocks.append(
            EntityBlock(
                X=_asarray(Xp, dtype),
                labels=_asarray(labp),
                weights=_asarray(wtsp),
                col_map=blocks[-1].col_map,
                row_index=_asarray(rindexp),
                n_entities=E,
                rows_per_entity=Rp,
                block_dim=D,
            )
        )

    padded_flops = int(
        sum(b.n_entities * b.rows_per_entity * b.block_dim for b in blocks)
    )
    exact_flops = int(np.sum(kept_counts * np.maximum(act_counts, 1)))
    ds = RandomEffectDataset(
        blocks=blocks,
        entity_ids=ids_per_block,
        entity_to_slot=entity_to_slot,
        n_global_rows=n_rows,
        n_features=d,
        passive_blocks=passive_blocks,
        padded_flops=padded_flops,
        exact_flops=exact_flops,
    )
    from photon_ml_tpu import telemetry as telemetry_mod

    telemetry_mod.current().gauge("game_bucket_padding_ratio").set(
        ds.padding_ratio
    )
    return ds
