"""Distributed GAME coordinates: multi-chip fixed and random effects.

The reference scales GAME with Spark (SURVEY.md §2 "Parallelism strategies"):
rows sharded across executors for the fixed effect (`treeAggregate`
reductions), entities hash-partitioned across executors for random effects
(communication-free per-entity solves).  The TPU mapping
[CONFIRMED-BASELINE north star]:

- ``DistributedFixedEffectCoordinate`` — rows sharded over the mesh's
  ``DATA_AXIS``; the whole L-BFGS/OWL-QN/TRON loop runs inside ``shard_map``
  with one fused ``psum`` per objective evaluation over ICI.
- ``EntityShardedRandomEffectCoordinate`` — the "expert parallelism"
  analogue: each block's ENTITY axis is sharded over the mesh
  (``NamedSharding``), and because the vmap'd batched solver is elementwise
  across entities, XLA partitions it with zero communication in the solve —
  exactly the reference's communication-free ``mapPartitions`` property.
  Only the per-row score scatter crosses shards.

Both run multi-host unchanged: mesh devices may span hosts; XLA routes
collectives over ICI within a slice and DCN across slices.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from photon_ml_tpu.parallel.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_ml_tpu.game.coordinates import (
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.data import EntityBlock, RandomEffectDataset
from photon_ml_tpu.game.model import FixedEffectModel
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.ops import losses as losses_lib
from photon_ml_tpu.optim.problem import GlmOptimizationConfig
from photon_ml_tpu.parallel.distributed import (
    DATA_AXIS,
    DistributedGlmData,
    shard_glm_data,
)

Array = jax.Array


class DistributedFixedEffectCoordinate(FixedEffectCoordinate):
    """Row-sharded fixed-effect coordinate (SURVEY.md §3.1 hot loop on a
    mesh).  Constructed from HOST data; sharding happens once here, like the
    reference persisting its row-partitioned RDD."""

    def __init__(
        self,
        name: str,
        X_host,
        labels: np.ndarray,
        mesh,
        task: str,
        config: GlmOptimizationConfig,
        reg_weight: float = 0.0,
        feature_shard: str = "global",
        weights: Optional[np.ndarray] = None,
        dist: Optional[DistributedGlmData] = None,
    ):
        from photon_ml_tpu.optim.problem import GlmOptimizationProblem

        # Deliberately NOT calling super().__init__: the dataset lives as
        # DistributedGlmData and train/score are shard_map programs.
        self.name = name
        self.task = losses_lib.get(task).name
        self.problem = GlmOptimizationProblem(task, config)
        self.reg_weight = reg_weight
        self.feature_shard = feature_shard
        self.mesh = mesh
        self.n_rows = X_host.shape[0]
        self.n_features = X_host.shape[1]
        # A prebuilt sharded dataset (grid points differing only in the
        # optimizer config reuse it — re-sharding/re-uploading the training
        # matrix per point is the expensive part).
        self.dist = (
            dist if dist is not None
            else shard_glm_data(X_host, labels, mesh, weights=weights)
        )
        self._rows_per_shard = self.dist.data.labels.shape[1]
        self._n_shards = self.dist.n_shards

        def _train(
            dd: DistributedGlmData,
            offsets_blocked: Array,
            w0: Array,
            reg_weight: Array,
        ):
            local = dd.local()
            local = dataclasses.replace(local, offsets=offsets_blocked[0])
            return self.problem.solve(
                local, reg_weight, w0, axis_name=DATA_AXIS
            ).w

        def _score(dd: DistributedGlmData, w: Array) -> Array:
            return dd.local().features.matvec(w)[None, :]

        self._train_sm = jax.jit(
            shard_map(
                _train,
                mesh=mesh,
                in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P()),
                out_specs=P(),
                check_vma=False,
            )
        )
        self._score_sm = jax.jit(
            shard_map(
                _score,
                mesh=mesh,
                in_specs=(P(DATA_AXIS), P()),
                out_specs=P(DATA_AXIS),
                check_vma=False,
            )
        )

        def _variances(
            dd: DistributedGlmData,
            offsets_blocked: Array,
            w: Array,
            reg_weight: Array,
        ):
            local = dd.local()
            local = dataclasses.replace(local, offsets=offsets_blocked[0])
            return self.problem.coefficient_variances(
                w, local, reg_weight, axis_name=DATA_AXIS
            )

        self._var_sm = jax.jit(
            shard_map(
                _variances,
                mesh=mesh,
                in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P()),
                out_specs=P(),
                check_vma=False,
            )
        )

    def _block_offsets(self, offsets: Array) -> Array:
        total = self._n_shards * self._rows_per_shard
        padded = jnp.concatenate(
            [offsets, jnp.zeros((total - self.n_rows,), offsets.dtype)]
        )
        blocked = padded.reshape(self._n_shards, self._rows_per_shard)
        return jax.device_put(blocked, NamedSharding(self.mesh, P(DATA_AXIS)))

    def train(self, offsets: Array, warm_state: Optional[Array] = None) -> Array:
        w0 = (
            jnp.zeros((self.n_features,), jnp.float32)
            if warm_state is None
            else warm_state
        )
        # reg_weight is traced (not closed over) so hyperparameter tuning can
        # mutate self.reg_weight between runs without a stale compiled value.
        return self._train_sm(
            self.dist,
            self._block_offsets(offsets),
            w0,
            jnp.asarray(self.reg_weight, jnp.float32),
        )

    def score(self, state: Array) -> Array:
        blocked = self._score_sm(self.dist, state)
        return blocked.reshape(-1)[: self.n_rows]

    def finalize(self, state: Array, offsets=None) -> FixedEffectModel:
        variances = None
        if self.problem.config.compute_variances and offsets is None:
            import logging

            logging.getLogger(__name__).warning(
                "coordinate %s: compute_variances requires finalize(...,"
                " offsets=...) (the estimator passes residual offsets); "
                "the model will carry no variances",
                self.name,
            )
        if self.problem.config.compute_variances and offsets is not None:
            # One psum'd squared-column reduction over the mesh, with the
            # Hessian evaluated at the full final margins (residual offsets
            # included) — same semantics as the single-device path.
            variances = self._var_sm(
                self.dist,
                self._block_offsets(jnp.asarray(offsets, jnp.float32)),
                state,
                jnp.asarray(self.reg_weight, jnp.float32),
            )
        return FixedEffectModel(
            GeneralizedLinearModel(Coefficients(state, variances), self.task),
            self.feature_shard,
        )


def _pad_block_entities(block: EntityBlock, multiple: int, sentinel: int):
    """Pad the entity axis to a multiple of the mesh size.  Padding lanes
    carry zero weights (solve to 0 under L2) and sentinel row indices
    (scatter into the discarded trailing slot)."""
    E = block.n_entities
    target = ((E + multiple - 1) // multiple) * multiple
    pad = target - E
    if pad == 0:
        return block
    return EntityBlock(
        X=jnp.pad(block.X, ((0, pad), (0, 0), (0, 0))),
        labels=jnp.pad(block.labels, ((0, pad), (0, 0))),
        weights=jnp.pad(block.weights, ((0, pad), (0, 0))),
        col_map=jnp.pad(block.col_map, ((0, pad), (0, 0)), constant_values=-1),
        row_index=jnp.pad(
            block.row_index, ((0, pad), (0, 0)), constant_values=sentinel
        ),
        n_entities=target,
        rows_per_entity=block.rows_per_entity,
        block_dim=block.block_dim,
    )


def shard_dataset_entities(
    dataset: RandomEffectDataset, mesh
) -> RandomEffectDataset:
    """The dataset with every block's ENTITY axis padded to the mesh size
    and placed sharded over it — the one placement both the plain and the
    factored entity-sharded coordinates build on."""
    n_dev = mesh.devices.size
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    sentinel = dataset.n_global_rows

    def place(block):
        if block is None:
            return None
        padded = _pad_block_entities(block, n_dev, sentinel)
        return jax.tree.map(
            lambda x: jax.device_put(x, sharding), padded
        )

    return dataclasses.replace(
        dataset,
        blocks=[place(b) for b in dataset.blocks],
        passive_blocks=[place(b) for b in dataset.passive_blocks],
    )


class EntityShardedRandomEffectCoordinate(RandomEffectCoordinate):
    """Random-effect coordinate with entity-axis sharding over a mesh."""

    def __init__(
        self,
        name: str,
        dataset: RandomEffectDataset,
        mesh,
        task: str,
        config: GlmOptimizationConfig,
        reg_weight: float = 0.0,
        feature_shard: str = "global",
        entity_key: str = "",
    ):
        dataset = shard_dataset_entities(dataset, mesh)
        super().__init__(
            name, dataset, task, config, reg_weight,
            feature_shard=feature_shard, entity_key=entity_key,
        )
        self.mesh = mesh

    def finalize(self, state, offsets=None):
        # Drop padding lanes (entity_ids lists are shorter than padded E);
        # the base implementation iterates entity_ids, so padding lanes are
        # skipped naturally.
        return super().finalize(state, offsets=offsets)


def entity_sharded_factored_coordinate(
    name: str,
    dataset: RandomEffectDataset,
    mesh,
    task: str,
    config: GlmOptimizationConfig,
    rank: int,
    **kwargs,
):
    """Factored random effect with entity-axis sharding over a mesh.

    The factored coordinate's training program is ONE jitted alternation
    over block pytrees, so sharded placement is all the distribution it
    needs: the latent step's vmapped per-entity solves are elementwise
    across lanes (XLA partitions them with zero communication — the
    ``mapPartitions`` property), and the projection step's gradient
    scatter from sharded ``(E, D, rank)`` contributions into the
    REPLICATED ``V`` gradient is exactly the cross-shard psum the shared
    projection fit needs — GSPMD inserts it; no hand-written collective.
    A factory (placement + delegation), not a subclass: the factored
    constructor's jit closures must see only ready-sharded blocks.
    """
    from photon_ml_tpu.game.factored import FactoredRandomEffectCoordinate

    coord = FactoredRandomEffectCoordinate(
        name, shard_dataset_entities(dataset, mesh), task, config,
        rank, **kwargs,
    )
    coord.mesh = mesh
    return coord
