"""Native (C++) ingest components, loaded via ctypes.

The shared library builds lazily from the checked-in source with the
system ``g++`` the first time it is needed (no pybind11 in this
environment; the C ABI + ctypes needs no Python headers).  The build is
cached next to the source and invalidated on source change.  Everything
here degrades gracefully: ``load_game_decoder()`` returns None when a
compiler is unavailable or the build fails, and callers fall back to the
pure-Python decoders.

Set ``PHOTON_NO_NATIVE=1`` to force the Python paths (used by parity
tests).
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "game_decoder.cpp")
_LOCK = threading.Lock()
_CACHE: dict = {}

logger = logging.getLogger(__name__)


def _compile_cached(src: str, prefix: str, what: str) -> Optional[str]:
    """Lazy shared-library build: hash-tagged .so next to the source,
    atomic install (concurrent builders race safely), None + a warning on
    ANY failure (missing source/toolchain, compile error) — callers fall
    back to their pure-Python paths."""
    try:
        with open(src, "rb") as f:
            tag = hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError as e:
        logger.warning("native %s source unreadable (%s)", what, e)
        return None
    so_path = os.path.join(_DIR, f"{prefix}_{tag}.so")
    if os.path.exists(so_path):
        return so_path
    tmp = f"{so_path}.build.{os.getpid()}"  # unique per builder: no
    # interleaved writes; the os.replace below is the atomic install
    base_cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp, src,
    ]
    # Try with OpenMP (the layout sorter parallelizes; sources guard with
    # #ifdef _OPENMP), then without — a toolchain missing libgomp must
    # degrade to a single-threaded native build, not to the Python path.
    last_err = None
    for extra in (["-fopenmp"], []):
        cmd = base_cmd[:-3] + extra + base_cmd[-3:]
        try:
            subprocess.run(
                cmd, check=True, capture_output=True, timeout=240
            )
            os.replace(tmp, so_path)
            return so_path
        except (OSError, subprocess.SubprocessError) as e:
            last_err = e
    detail = getattr(last_err, "stderr", b"") or b""
    logger.warning(
        "native %s build failed (%s): %s — using the Python path",
        what, last_err, detail.decode(errors="replace")[:500],
    )
    return None


def _build() -> Optional[str]:
    return _compile_cached(_SRC, "_game_decoder", "game decoder")


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c_void = ctypes.c_void_p
    c_i64 = ctypes.c_int64
    c_char_p = ctypes.c_char_p
    sig = {
        "gd_new": ([ctypes.c_int], c_void),
        "gd_free": ([c_void], None),
        "gd_preload_shard": (
            [c_void, c_char_p, ctypes.POINTER(c_char_p), c_i64], None),
        "gd_decode_block": ([c_void, ctypes.c_char_p, c_i64, c_i64], c_i64),
        "gd_error": ([c_void], c_char_p),
        "gd_n_rows": ([c_void], c_i64),
        "gd_copy_row_data": (
            [c_void, ctypes.POINTER(ctypes.c_double),
             ctypes.POINTER(ctypes.c_double),
             ctypes.POINTER(ctypes.c_double)], None),
        "gd_uid_blob_len": ([c_void], c_i64),
        "gd_copy_uids": (
            [c_void, ctypes.c_char_p, ctypes.POINTER(c_i64),
             ctypes.POINTER(c_i64)], None),
        "gd_n_id_cols": ([c_void], c_i64),
        "gd_id_col_name": ([c_void, c_i64], c_char_p),
        "gd_id_col_blob_len": ([c_void, c_i64], c_i64),
        "gd_copy_id_col": (
            [c_void, c_i64, ctypes.c_char_p, ctypes.POINTER(c_i64),
             ctypes.POINTER(c_i64)], None),
        "gd_n_shards": ([c_void], c_i64),
        "gd_shard_name": ([c_void, c_i64], c_char_p),
        "gd_shard_nnz": ([c_void, c_i64], c_i64),
        "gd_shard_dropped": ([c_void, c_i64], c_i64),
        "gd_shard_unknown": ([c_void, c_i64], c_i64),
        "gd_shard_seen": ([c_void, c_i64], c_i64),
        "gd_copy_shard_coo": (
            [c_void, c_i64, ctypes.POINTER(c_i64), ctypes.POINTER(c_i64),
             ctypes.POINTER(ctypes.c_float)], None),
        "gd_shard_nkeys": ([c_void, c_i64], c_i64),
        "gd_shard_keys_blob_len": ([c_void, c_i64], c_i64),
        "gd_copy_shard_keys": (
            [c_void, c_i64, ctypes.c_char_p, ctypes.POINTER(c_i64)], None),
    }
    for name, (argtypes, restype) in sig.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype
    return lib


def load_game_decoder() -> Optional[ctypes.CDLL]:
    """The bound shared library, building it if needed; None on failure or
    when ``PHOTON_NO_NATIVE=1``."""
    if os.environ.get("PHOTON_NO_NATIVE") == "1":
        return None
    with _LOCK:
        if "lib" in _CACHE:
            return _CACHE["lib"]
        so_path = _build()
        lib = None
        if so_path is not None:
            try:
                lib = _bind(ctypes.CDLL(so_path))
            except OSError as e:
                logger.warning("native game decoder load failed: %s", e)
        _CACHE["lib"] = lib
        return lib


# ---------------------------------------------------------------------------
# Layout sorter (the hot passes of the Pallas slot-layout build)
# ---------------------------------------------------------------------------

_SORT_SRC = os.path.join(_DIR, "layout_sort.cpp")


def _build_sorter() -> Optional[str]:
    return _compile_cached(_SORT_SRC, "_layout_sort", "layout sorter")


def _bind_sorter(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64 = ctypes.c_int64
    p_i64 = ctypes.POINTER(i64)
    p_i32 = ctypes.POINTER(ctypes.c_int32)
    p_f32 = ctypes.POINTER(ctypes.c_float)
    lib.pl_sort_orientation.argtypes = [
        p_i64, p_i64, i64, i64, i64, i64, p_i32, p_i32, p_i64,
    ]
    lib.pl_sort_orientation.restype = i64
    lib.pl_scatter.argtypes = [
        p_i64, p_i64, p_f32, p_i32, p_i32, p_i32,
        i64, i64, i64, i64, i64, i64, i64,
        ctypes.c_void_p, p_f32, p_i64,
    ]
    lib.pl_scatter.restype = i64
    lib.pl_observed_team.argtypes = []
    lib.pl_observed_team.restype = i64
    return lib


def load_layout_sorter() -> Optional[ctypes.CDLL]:
    """The layout-sorter library, building it if needed; None on failure
    or when ``PHOTON_NO_NATIVE=1`` (numpy fallback — bit-identical
    output, parity-tested)."""
    if os.environ.get("PHOTON_NO_NATIVE") == "1":
        return None
    with _LOCK:
        if "sorter" in _CACHE:
            return _CACHE["sorter"]
        so_path = _build_sorter()
        lib = None
        if so_path is not None:
            try:
                lib = _bind_sorter(ctypes.CDLL(so_path))
            except OSError as e:
                logger.warning("native layout sorter load failed: %s", e)
        _CACHE["sorter"] = lib
        return lib


# ---------------------------------------------------------------------------
# Scoring-result Avro encoder (the write-side mirror of the decoder)
# ---------------------------------------------------------------------------

_ENC_SRC = os.path.join(_DIR, "score_encoder.cpp")


def _build_encoder() -> Optional[str]:
    return _compile_cached(_ENC_SRC, "_score_encoder", "score encoder")


def _bind_encoder(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64 = ctypes.c_int64
    p_i64 = ctypes.POINTER(i64)
    p_u8 = ctypes.POINTER(ctypes.c_uint8)
    p_f64 = ctypes.POINTER(ctypes.c_double)
    lib.se_encode.argtypes = [
        i64,
        ctypes.c_char_p, p_i64, p_u8,
        p_f64,
        p_f64, p_u8,
        i64,
        ctypes.c_char_p, p_i64, p_u8,
        ctypes.c_char_p, p_i64,
        ctypes.c_char_p, i64,
    ]
    lib.se_encode.restype = i64
    return lib


def load_score_encoder() -> Optional[ctypes.CDLL]:
    """The scoring-result encoder library, building it if needed; None on
    failure or when ``PHOTON_NO_NATIVE=1`` (pure-Python fallback —
    bit-identical output, parity-tested)."""
    if os.environ.get("PHOTON_NO_NATIVE") == "1":
        return None
    with _LOCK:
        if "encoder" in _CACHE:
            return _CACHE["encoder"]
        so_path = _build_encoder()
        lib = None
        if so_path is not None:
            try:
                lib = _bind_encoder(ctypes.CDLL(so_path))
            except OSError as e:
                logger.warning("native score encoder load failed: %s", e)
        _CACHE["encoder"] = lib
        return lib
