// Native Avro encoder for ScoringResultAvro blocks (io/schemas.py
// SCORING_RESULT) — the write-side mirror of game_decoder.cpp.
//
// The scoring drivers' write path was the last pure-Python hot loop:
// per-record dict building + recursive write_datum measured ~130k rec/s,
// an order of magnitude under the scoring rate, so out-of-core scoring
// at BASELINE scale would be WRITE-bound (VERDICT r4 weak #5).  This
// encoder takes one COLUMNAR block (uid blob+offsets, score/label
// arrays, id columns as value blobs + null masks) and emits the Avro
// binary record body in one C++ pass; Python wraps framing/compression
// (zlib is already native there).
//
// Byte-level contract (kept bit-for-bit identical to the Python
// write_datum path; tests/test_io.py pins it):
//   uid:   union [null, string] -> zigzag index 0|1, then len+bytes
//   predictionScore: 8-byte little-endian double
//   label: union [null, double] -> zigzag index, then double
//   ids:   map<string> -> varint(count), entries, varint(0); count>0
//          entries iterate the given column order (caller sorts keys)
//
// C ABI only (ctypes; no pybind11 in this image).

#include <cstdint>
#include <cstring>

namespace {

inline int64_t put_varint(uint8_t* out, uint64_t v) {
    int64_t n = 0;
    while (v & ~0x7FULL) {
        out[n++] = static_cast<uint8_t>((v & 0x7F) | 0x80);
        v >>= 7;
    }
    out[n++] = static_cast<uint8_t>(v);
    return n;
}

inline int64_t put_long(uint8_t* out, int64_t v) {
    // Avro zigzag
    return put_varint(out, (static_cast<uint64_t>(v) << 1) ^
                           static_cast<uint64_t>(v >> 63));
}

inline int64_t put_double(uint8_t* out, double v) {
    std::memcpy(out, &v, 8);  // little-endian hosts only (x86/ARM)
    return 8;
}

}  // namespace

extern "C" {

// Returns bytes written, or -(bytes needed) when out_cap is too small
// (caller reallocates and retries).  Offsets arrays have n+1 entries
// (and n_cols*n+1 for the column-major value offsets); is-null masks
// are 1 byte per entry.
int64_t se_encode(
    int64_t n,
    const char* uid_blob, const int64_t* uid_off,
    const uint8_t* uid_is_null,
    const double* scores,
    const double* labels, const uint8_t* label_is_null,
    int64_t n_cols,
    const char* vals_blob, const int64_t* vals_off,
    const uint8_t* val_is_null,
    const char* keys_blob, const int64_t* keys_off,
    char* out_c, int64_t out_cap) {
    // Upper bound: per row uid(10+len) + score(9) + label(10) +
    // map header/terminator(20) + per entry key+val lens + 20.
    int64_t need = 0;
    for (int64_t r = 0; r < n; ++r) {
        need += 10 + (uid_off[r + 1] - uid_off[r]) + 9 + 10 + 20;
    }
    for (int64_t c = 0; c < n_cols; ++c) {
        int64_t klen = keys_off[c + 1] - keys_off[c];
        for (int64_t r = 0; r < n; ++r) {
            int64_t i = c * n + r;
            if (!val_is_null[i]) {
                need += 20 + klen + (vals_off[i + 1] - vals_off[i]);
            }
        }
    }
    if (need > out_cap) return -need;

    uint8_t* out = reinterpret_cast<uint8_t*>(out_c);
    int64_t p = 0;
    for (int64_t r = 0; r < n; ++r) {
        if (uid_is_null[r]) {
            p += put_long(out + p, 0);
        } else {
            p += put_long(out + p, 1);
            int64_t len = uid_off[r + 1] - uid_off[r];
            p += put_long(out + p, len);
            std::memcpy(out + p, uid_blob + uid_off[r], len);
            p += len;
        }
        p += put_double(out + p, scores[r]);
        if (label_is_null[r]) {
            p += put_long(out + p, 0);
        } else {
            p += put_long(out + p, 1);
            p += put_double(out + p, labels[r]);
        }
        int64_t count = 0;
        for (int64_t c = 0; c < n_cols; ++c) {
            if (!val_is_null[c * n + r]) ++count;
        }
        if (count > 0) {
            p += put_long(out + p, count);
            for (int64_t c = 0; c < n_cols; ++c) {
                int64_t i = c * n + r;
                if (val_is_null[i]) continue;
                int64_t klen = keys_off[c + 1] - keys_off[c];
                p += put_long(out + p, klen);
                std::memcpy(out + p, keys_blob + keys_off[c], klen);
                p += klen;
                int64_t vlen = vals_off[i + 1] - vals_off[i];
                p += put_long(out + p, vlen);
                std::memcpy(out + p, vals_blob + vals_off[i], vlen);
                p += vlen;
            }
        }
        p += put_long(out + p, 0);  // map terminator
    }
    return p;
}

}  // extern "C"
