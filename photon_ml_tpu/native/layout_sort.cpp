// Native core of the Pallas slot-layout build (ops/sparse_pallas.py).
//
// The host-side layout build is the ingest bottleneck once transfers run
// at PCIe rates: numpy spends its time in argsort + run-length + fancy
// scatter passes over tens of millions of entries.  This file implements
// exactly those passes in C++ — a stable LSD radix argsort by the
// (tile, gather-window, lane) key, the per-cell depth positions and
// per-(tile, window) max lane loads in one sequential scan, and the
// final slot scatter — leaving the (tiny) cost model and bin-packing in
// numpy.  The radix sort is stable with the same tie order as
// np.argsort(key, kind="stable"), so the produced layout is
// BIT-IDENTICAL to the Python path (tests assert array equality).
//
// C ABI + ctypes (no pybind11 in this environment); the loader in
// native/__init__.py compiles this lazily with the system g++ and falls
// back to the numpy path on any failure.

#include <cstdint>
#include <cstring>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

// Field extraction shared by both passes.  tile_edge is the (square)
// tile size; WIN is fixed at 128 lanes.
struct Fields {
  int64_t nbc;
  int64_t tile_edge;
  int64_t wins;  // tile_edge / 128

  inline int64_t tile(int64_t r, int64_t c) const {
    return (r / tile_edge) * nbc + (c / tile_edge);
  }
  inline int64_t gwin(int64_t c) const { return (c % tile_edge) >> 7; }
  inline int64_t lane(int64_t r) const { return r & 127; }
  inline int64_t key(int64_t r, int64_t c) const {
    return (tile(r, c) * wins + gwin(c)) * 128 + lane(r);
  }
};

}  // namespace

namespace {

// Actual deliverable team size: observed from a real parallel region with
// dynamic adjustment disabled.  Every later region requests exactly this
// size; a region body ADDITIONALLY verifies its own team and degrades to
// sequential (thread 0 owns everything) on any mismatch — range math from
// a team size the runtime did not deliver would silently drop elements.
inline int observed_team() {
#ifdef _OPENMP
  omp_set_dynamic(0);
  int team = 1;
#pragma omp parallel
  {
#pragma omp single
    team = omp_get_num_threads();
  }
  return team;
#else
  return 1;
#endif
}

inline void my_range(int64_t nnz, int team, int64_t* lo, int64_t* hi) {
#ifdef _OPENMP
  const int actual = omp_get_num_threads();
  const int tid = omp_get_thread_num();
#else
  const int actual = 1, tid = 0;
#endif
  if (actual != team) {  // degraded team: thread 0 does everything
    *lo = (tid == 0) ? 0 : nnz;
    *hi = (tid == 0) ? nnz : nnz;
    return;
  }
  *lo = nnz * tid / team;
  *hi = nnz * (tid + 1) / team;
}

inline int my_row(int team) {
#ifdef _OPENMP
  if (omp_get_num_threads() != team) return 0;
  return omp_get_thread_num();
#else
  (void)team;
  return 0;
#endif
}

}  // namespace

extern "C" {

// Stable argsort of entries by (tile, gwin, lane) key + one sequential
// scan emitting per-entry depth positions and per-(tile, window) max
// lane loads.  order_out/depth_pos_out: nnz int32 (caller-allocated);
// M_out: nt*wins int64, caller-zeroed.  Returns 0, or -1 when nnz
// exceeds int32 indexing.
int64_t pl_sort_orientation(
    const int64_t* rows, const int64_t* cols, int64_t nnz,
    int64_t nbc, int64_t tile_edge, int64_t nt,
    int32_t* order_out, int32_t* depth_pos_out, int64_t* M_out) {
  if (nnz > INT32_MAX) return -1;
  const Fields F{nbc, tile_edge, tile_edge >> 7};
  const int64_t key_span = nt * F.wins * 128;

  std::vector<int64_t> keys(static_cast<size_t>(nnz));
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < nnz; ++i) keys[i] = F.key(rows[i], cols[i]);

  // Parallel LSD radix argsort, 16-bit digits — STABLE with numpy's
  // kind="stable" tie order: each thread owns a CONTIGUOUS input range,
  // per-(thread, bucket) counts are prefix-summed bucket-major then
  // thread-major, so equal keys keep their original relative order.
  int bits = 1;
  while ((int64_t(1) << bits) < key_span) ++bits;
  const int DIGIT = 16;
  const int n_buckets = 1 << DIGIT;
  const int n_threads = observed_team();
  std::vector<int32_t> idx_a(static_cast<size_t>(nnz));
  std::vector<int32_t> idx_b(static_cast<size_t>(nnz));
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < nnz; ++i) idx_a[i] = static_cast<int32_t>(i);
  std::vector<int64_t> counts(
      static_cast<size_t>(n_threads) * n_buckets);
  int32_t* src = idx_a.data();
  int32_t* dst = idx_b.data();
  for (int shift = 0; shift < bits; shift += DIGIT) {
    std::memset(counts.data(), 0,
                sizeof(int64_t) * counts.size());
    // Histogram + prefix + stable scatter in ONE parallel region: the
    // two per-thread phases see the SAME team by construction (a
    // degraded team degrades both), so range/row math can never mix
    // team sizes.
#pragma omp parallel num_threads(n_threads)
    {
      int64_t lo, hi;
      my_range(nnz, n_threads, &lo, &hi);
      int64_t* my =
          counts.data() + static_cast<size_t>(my_row(n_threads)) * n_buckets;
      for (int64_t i = lo; i < hi; ++i)
        ++my[(keys[src[i]] >> shift) & (n_buckets - 1)];
#ifdef _OPENMP
#pragma omp barrier
#pragma omp single
#endif
      {
        // Exclusive prefix over (bucket, thread) pairs, bucket-major:
        // thread t's entries in bucket b land after every thread's
        // smaller buckets and earlier threads' bucket b — stability.
        int64_t run = 0;
        for (int b = 0; b < n_buckets; ++b) {
          for (int t = 0; t < n_threads; ++t) {
            int64_t& slot =
                counts[static_cast<size_t>(t) * n_buckets + b];
            int64_t c = slot;
            slot = run;
            run += c;
          }
        }
      }
      for (int64_t i = lo; i < hi; ++i) {
        int32_t e = src[i];
        dst[my[(keys[e] >> shift) & (n_buckets - 1)]++] = e;
      }
    }
    std::swap(src, dst);
  }
  std::memcpy(order_out, src, sizeof(int32_t) * nnz);

  // Sequential scan: depth position within each (tile, window, lane)
  // cell and the max lane load per (tile, window).
  int64_t prev_key = -1;
  int32_t run_len = 0;
  for (int64_t i = 0; i < nnz; ++i) {
    const int64_t k = keys[order_out[i]];
    if (k == prev_key) {
      ++run_len;
    } else {
      prev_key = k;
      run_len = 0;
    }
    depth_pos_out[i] = run_len;
    const int64_t tw = k >> 7;  // tile*wins + gwin
    if (run_len + 1 > M_out[tw]) M_out[tw] = run_len + 1;
  }
  return 0;
}

// Scatter kept entries into the slot grids; overflow indices (positions
// into the ORIGINAL entry arrays) go to spill_out.  code_out is int16
// when code_bytes == 2 else int32; base is the per-(tile, window)
// exclusive sublane offset.  Returns the spill count.
int64_t pl_scatter(
    const int64_t* rows, const int64_t* cols, const float* vals,
    const int32_t* order, const int32_t* depth_pos, const int32_t* base,
    int64_t nnz, int64_t nbc, int64_t tile_edge,
    int64_t depth, int64_t a, int64_t win_shift, int64_t code_bytes,
    void* code_out, float* val_out, int64_t* spill_out) {
  const Fields F{nbc, tile_edge, tile_edge >> 7};
  int16_t* code16 = static_cast<int16_t*>(code_out);
  int32_t* code32 = static_cast<int32_t*>(code_out);

  // Parallel over contiguous sorted ranges: slot targets are unique per
  // kept entry (disjoint writes), and per-thread spill segments are laid
  // out in thread order, which IS sorted order — identical spill
  // ordering to the sequential loop (and the numpy path).
  const int n_threads = observed_team();
  std::vector<int64_t> spill_base(n_threads + 1, 0);
  // Count + prefix + write in ONE region: both phases share the same
  // team by construction (see the sort loop).
#pragma omp parallel num_threads(n_threads)
  {
    int64_t lo, hi;
    my_range(nnz, n_threads, &lo, &hi);
    const int row = my_row(n_threads);
    int64_t n = 0;
    for (int64_t i = lo; i < hi; ++i)
      if (depth_pos[i] >= depth) ++n;
    // Atomic: in a degraded team every thread maps to row 0, and an
    // empty-range thread's plain "= 0" store could clobber the total.
#ifdef _OPENMP
#pragma omp atomic
#endif
    spill_base[row + 1] += n;
#ifdef _OPENMP
#pragma omp barrier
#pragma omp single
#endif
    {
      for (int t = 0; t < n_threads; ++t)
        spill_base[t + 1] += spill_base[t];
    }
    int64_t cursor = spill_base[row];
    for (int64_t i = lo; i < hi; ++i) {
      const int32_t e = order[i];
      if (depth_pos[i] >= depth) {
        spill_out[cursor++] = e;
        continue;
      }
      const int64_t r = rows[e], c = cols[e];
      const int64_t t = F.tile(r, c);
      const int64_t g = F.gwin(c);
      const int64_t sub = base[t * F.wins + g] + depth_pos[i];
      const int64_t flat = (t * a + sub) * 128 + F.lane(r);
      const int64_t ohi = (r % tile_edge) >> 7;
      const int64_t code =
          (g << win_shift) | (ohi << 7) | (c & 127);
      if (code_bytes == 2) {
        code16[flat] = static_cast<int16_t>(code);
      } else {
        code32[flat] = static_cast<int32_t>(code);
      }
      val_out[flat] = vals[e];
    }
  }
  return spill_base[n_threads];
}

// Test introspection: the ACTUAL deliverable team size.  The multi-thread
// partition paths only execute when this exceeds 1 (a single-CPU host
// still delivers a >1 team under OMP_NUM_THREADS), and the team-coverage
// test asserts it rather than passing vacuously at team=1.
int64_t pl_observed_team() { return observed_team(); }

}  // extern "C"
