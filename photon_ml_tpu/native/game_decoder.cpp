// Native GAME Avro block decoder.
//
// The TPU-native analogue of the reference's JVM ingest layer (its Avro
// decoding runs as compiled Java inside Spark executors — SURVEY.md §2
// "Avro IO"; the Python flat decoder in data/game_reader.py is the
// fallback, this is the fast path).  A session object consumes decompressed
// Avro block payloads (GAME example schema, validated Python-side) and
// accumulates COLUMNAR results entirely in C++:
//
//  - response / weight / offset as double columns;
//  - uids and per-id-column entity keys as string blobs + offset tables
//    (-1 offset = missing / null);
//  - per-shard feature triples ALREADY index-mapped: the name"\x01"term →
//    column-id hash map lives here, so the per-feature hot path (the
//    dominant ingest cost in Python) never crosses the language boundary.
//    Building mode assigns fresh ids; scoring mode is preloaded from the
//    Python index maps and counts dropped unseen features/shards.
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).  All
// output copies happen once, at the end, into NumPy-owned buffers.

#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct ShardAcc {
  std::unordered_map<std::string, int64_t> index;  // key -> column id
  std::vector<std::string> keys;                   // id -> key (insertion order)
  std::vector<int64_t> rows;
  std::vector<int64_t> cols;
  std::vector<float> vals;
  int64_t dropped = 0;
  bool preloaded = false;  // scoring mode: never grow the index
  bool unknown = false;    // scoring mode: shard absent from index maps
  bool seen = false;       // shard actually appeared in the data
};

struct IdCol {
  // Offsets into blob per row; -1 = missing.  Lazily extended to the
  // current row count on first touch of a late-appearing column.
  std::vector<int64_t> start;
  std::vector<int64_t> end;
  std::string blob;
};

struct Session {
  bool building;
  int64_t n_rows = 0;
  std::vector<double> response, weight, offset;
  std::string uid_blob;
  std::vector<int64_t> uid_start, uid_end;  // -1 = null uid
  std::vector<std::string> shard_order;
  std::unordered_map<std::string, ShardAcc> shards;
  std::vector<std::string> id_order;
  std::unordered_map<std::string, IdCol> id_cols;
  std::string error;
};

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  int64_t read_long() {
    uint64_t acc = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      acc |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) {
        return static_cast<int64_t>(acc >> 1) ^
               -static_cast<int64_t>(acc & 1);
      }
      shift += 7;
      if (shift > 63) break;
    }
    ok = false;
    return 0;
  }

  double read_double() {
    if (end - p < 8) { ok = false; return 0.0; }
    double v;
    std::memcpy(&v, p, 8);
    p += 8;
    return v;
  }

  bool read_str(const char** s, int64_t* len) {
    int64_t n = read_long();
    if (!ok || n < 0 || end - p < n) { ok = false; return false; }
    *s = reinterpret_cast<const char*>(p);
    *len = n;
    p += n;
    return true;
  }
};

void touch_id_col(Session* s, const std::string& name, IdCol** out) {
  auto it = s->id_cols.find(name);
  if (it == s->id_cols.end()) {
    s->id_order.push_back(name);
    IdCol col;
    col.start.assign(s->n_rows, -1);  // backfill rows before first sight
    col.end.assign(s->n_rows, -1);
    it = s->id_cols.emplace(name, std::move(col)).first;
  }
  *out = &it->second;
}

ShardAcc* touch_shard(Session* s, const std::string& name) {
  auto it = s->shards.find(name);
  if (it != s->shards.end()) {
    it->second.seen = true;
    return &it->second;
  }
  {
    s->shard_order.push_back(name);
    it = s->shards.emplace(name, ShardAcc{}).first;
    if (!s->building) {
      // Scoring: a shard absent from the supplied index maps drops every
      // feature (empty frozen index) and is excluded from the output —
      // it exists only to carry the drop count.
      it->second.preloaded = true;
      it->second.unknown = true;
    }
    it->second.seen = true;
    return &it->second;
  }
}

}  // namespace

extern "C" {

void* gd_new(int building) {
  auto* s = new Session();
  s->building = building != 0;
  return s;
}

void gd_free(void* h) { delete static_cast<Session*>(h); }

// Scoring mode: preload one shard's index map (keys in column order).
void gd_preload_shard(void* h, const char* shard, const char* const* keys,
                      int64_t nkeys) {
  auto* s = static_cast<Session*>(h);
  std::string name(shard);
  auto it = s->shards.find(name);
  if (it == s->shards.end()) {
    s->shard_order.push_back(name);
    it = s->shards.emplace(name, ShardAcc{}).first;
  }
  ShardAcc& acc = it->second;
  acc.preloaded = true;
  acc.keys.reserve(nkeys);
  for (int64_t i = 0; i < nkeys; ++i) {
    acc.keys.emplace_back(keys[i]);
    acc.index.emplace(acc.keys.back(), i);
  }
}

// Decode one decompressed block payload holding `count` records.
// Returns 0 on success, -1 on malformed input (see gd_error).
int64_t gd_decode_block(void* h, const uint8_t* payload, int64_t len,
                        int64_t count) {
  auto* s = static_cast<Session*>(h);
  Reader r{payload, payload + len};
  std::string key_buf;
  for (int64_t rec = 0; rec < count && r.ok; ++rec) {
    const int64_t row = s->n_rows;
    // uid: union [null, string]
    if (r.read_long() == 1) {
      const char* us; int64_t ul;
      if (!r.read_str(&us, &ul)) break;
      s->uid_start.push_back(static_cast<int64_t>(s->uid_blob.size()));
      s->uid_blob.append(us, ul);
      s->uid_end.push_back(static_cast<int64_t>(s->uid_blob.size()));
    } else {
      s->uid_start.push_back(-1);
      s->uid_end.push_back(-1);
    }
    s->response.push_back(r.read_double());
    s->weight.push_back(r.read_long() == 1 ? r.read_double() : 1.0);
    s->offset.push_back(r.read_long() == 1 ? r.read_double() : 0.0);

    // ids map
    for (;;) {
      int64_t c = r.read_long();
      if (!r.ok || c == 0) break;
      if (c < 0) { c = -c; r.read_long(); }
      for (int64_t i = 0; i < c && r.ok; ++i) {
        const char* ks; int64_t kl;
        const char* vs; int64_t vl;
        if (!r.read_str(&ks, &kl) || !r.read_str(&vs, &vl)) break;
        IdCol* col;
        touch_id_col(s, std::string(ks, kl), &col);
        if (static_cast<int64_t>(col->start.size()) < row) {
          col->start.resize(row, -1);
          col->end.resize(row, -1);
        }
        col->start.push_back(static_cast<int64_t>(col->blob.size()));
        col->blob.append(vs, vl);
        col->end.push_back(static_cast<int64_t>(col->blob.size()));
      }
    }

    // features map: shard -> [ {name, term, value} ]
    for (;;) {
      int64_t c = r.read_long();
      if (!r.ok || c == 0) break;
      if (c < 0) { c = -c; r.read_long(); }
      for (int64_t i = 0; i < c && r.ok; ++i) {
        const char* ss; int64_t sl;
        if (!r.read_str(&ss, &sl)) break;
        ShardAcc* acc = touch_shard(s, std::string(ss, sl));
        for (;;) {
          int64_t fc = r.read_long();
          if (!r.ok || fc == 0) break;
          if (fc < 0) { fc = -fc; r.read_long(); }
          for (int64_t j = 0; j < fc && r.ok; ++j) {
            const char* ns; int64_t nl;
            const char* ts; int64_t tl;
            if (!r.read_str(&ns, &nl) || !r.read_str(&ts, &tl)) break;
            double v = r.read_double();
            // feature_key semantics (data/index_map.py): empty term → the
            // bare name, else name + "\x01" + term.
            key_buf.assign(ns, nl);
            if (tl > 0) {
              key_buf.push_back('\x01');
              key_buf.append(ts, tl);
            }
            auto it = acc->index.find(key_buf);
            int64_t idx;
            if (it == acc->index.end()) {
              if (acc->preloaded || !s->building) {
                acc->dropped += 1;
                continue;
              }
              idx = static_cast<int64_t>(acc->keys.size());
              acc->keys.push_back(key_buf);
              acc->index.emplace(key_buf, idx);
            } else {
              idx = it->second;
            }
            acc->rows.push_back(row);
            acc->cols.push_back(idx);
            acc->vals.push_back(static_cast<float>(v));
          }
        }
      }
    }
    s->n_rows += 1;
  }
  if (!r.ok) {
    s->error = "malformed avro block payload";
    return -1;
  }
  return 0;
}

const char* gd_error(void* h) {
  return static_cast<Session*>(h)->error.c_str();
}

int64_t gd_n_rows(void* h) { return static_cast<Session*>(h)->n_rows; }

void gd_copy_row_data(void* h, double* response, double* weight,
                      double* offset) {
  auto* s = static_cast<Session*>(h);
  std::memcpy(response, s->response.data(), s->n_rows * sizeof(double));
  std::memcpy(weight, s->weight.data(), s->n_rows * sizeof(double));
  std::memcpy(offset, s->offset.data(), s->n_rows * sizeof(double));
}

int64_t gd_uid_blob_len(void* h) {
  return static_cast<int64_t>(static_cast<Session*>(h)->uid_blob.size());
}

void gd_copy_uids(void* h, char* blob, int64_t* start, int64_t* end) {
  auto* s = static_cast<Session*>(h);
  std::memcpy(blob, s->uid_blob.data(), s->uid_blob.size());
  std::memcpy(start, s->uid_start.data(), s->n_rows * sizeof(int64_t));
  std::memcpy(end, s->uid_end.data(), s->n_rows * sizeof(int64_t));
}

int64_t gd_n_id_cols(void* h) {
  return static_cast<int64_t>(static_cast<Session*>(h)->id_order.size());
}

const char* gd_id_col_name(void* h, int64_t i) {
  return static_cast<Session*>(h)->id_order[i].c_str();
}

int64_t gd_id_col_blob_len(void* h, int64_t i) {
  auto* s = static_cast<Session*>(h);
  return static_cast<int64_t>(s->id_cols[s->id_order[i]].blob.size());
}

void gd_copy_id_col(void* h, int64_t i, char* blob, int64_t* start,
                    int64_t* end) {
  auto* s = static_cast<Session*>(h);
  IdCol& col = s->id_cols[s->id_order[i]];
  if (static_cast<int64_t>(col.start.size()) < s->n_rows) {
    col.start.resize(s->n_rows, -1);  // trailing rows missing this column
    col.end.resize(s->n_rows, -1);
  }
  std::memcpy(blob, col.blob.data(), col.blob.size());
  std::memcpy(start, col.start.data(), s->n_rows * sizeof(int64_t));
  std::memcpy(end, col.end.data(), s->n_rows * sizeof(int64_t));
}

int64_t gd_n_shards(void* h) {
  return static_cast<int64_t>(static_cast<Session*>(h)->shard_order.size());
}

const char* gd_shard_name(void* h, int64_t i) {
  return static_cast<Session*>(h)->shard_order[i].c_str();
}

int64_t gd_shard_nnz(void* h, int64_t i) {
  auto* s = static_cast<Session*>(h);
  return static_cast<int64_t>(s->shards[s->shard_order[i]].rows.size());
}

int64_t gd_shard_dropped(void* h, int64_t i) {
  auto* s = static_cast<Session*>(h);
  return s->shards[s->shard_order[i]].dropped;
}

int64_t gd_shard_unknown(void* h, int64_t i) {
  auto* s = static_cast<Session*>(h);
  return s->shards[s->shard_order[i]].unknown ? 1 : 0;
}

int64_t gd_shard_seen(void* h, int64_t i) {
  auto* s = static_cast<Session*>(h);
  return s->shards[s->shard_order[i]].seen ? 1 : 0;
}

void gd_copy_shard_coo(void* h, int64_t i, int64_t* rows, int64_t* cols,
                       float* vals) {
  auto* s = static_cast<Session*>(h);
  ShardAcc& acc = s->shards[s->shard_order[i]];
  std::memcpy(rows, acc.rows.data(), acc.rows.size() * sizeof(int64_t));
  std::memcpy(cols, acc.cols.data(), acc.cols.size() * sizeof(int64_t));
  std::memcpy(vals, acc.vals.data(), acc.vals.size() * sizeof(float));
}

int64_t gd_shard_nkeys(void* h, int64_t i) {
  auto* s = static_cast<Session*>(h);
  return static_cast<int64_t>(s->shards[s->shard_order[i]].keys.size());
}

int64_t gd_shard_keys_blob_len(void* h, int64_t i) {
  auto* s = static_cast<Session*>(h);
  int64_t total = 0;
  for (const auto& k : s->shards[s->shard_order[i]].keys) {
    total += static_cast<int64_t>(k.size());
  }
  return total;
}

void gd_copy_shard_keys(void* h, int64_t i, char* blob, int64_t* offsets) {
  auto* s = static_cast<Session*>(h);
  ShardAcc& acc = s->shards[s->shard_order[i]];
  int64_t pos = 0;
  int64_t k = 0;
  for (const auto& key : acc.keys) {
    std::memcpy(blob + pos, key.data(), key.size());
    pos += static_cast<int64_t>(key.size());
    offsets[k++] = pos;
  }
}

}  // extern "C"
