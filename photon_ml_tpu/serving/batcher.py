"""Micro-batching request coalescer for the online scoring path.

One dispatch thread drains a bounded queue: it blocks for the first
pending request, then coalesces more until ``max_batch_size`` rows are in
hand or ``max_wait_us`` has elapsed, pads to the runtime's nearest bucket,
dispatches ONE kernel call, and scatters results back to per-request
futures.  The shape follows the batching/caching discipline of
hierarchical ML runtimes (Snap ML, arXiv:1803.06333) and the
pipeline-overlap serving designs of arXiv:1702.07005: fixed-shape
pre-compiled kernels + request coalescing turn many tiny latency-bound
calls into few device-efficient ones.

Failure semantics ride :mod:`photon_ml_tpu.utils.watchdog`'s
classification vocabulary so clients can reuse its retry discipline:

- **Admission control**: a full queue rejects at submit time with
  :class:`RejectedError` ("UNAVAILABLE: ..." — transient, retry later).
- **Deadlines**: a request that waited past its ``timeout_ms`` fails with
  :class:`DeadlineExceededError` ("DEADLINE_EXCEEDED: ..." — transient).
- Every failure is classified through the batcher's ``RetryPolicy``
  (``classify(exc)``) and counted as transient vs permanent.

**Tiered load shedding** — the binary queue-full reject is only the
backstop.  Admission is evaluated per submit against three tiers driven
by queue depth (watermark fractions of ``max_queue``) and the observed
p99 of the live ``serving_request_latency_seconds`` histogram:

- tier 0 **accept** — depth below ``shed_watermark``: everything admits.
- tier 1 **shed** — depth ≥ ``shed_watermark``, or observed p99 over
  ``p99_slo_ms``: ``priority="low"`` rows and over-deadline work (a
  deadline budget smaller than the current p99 — it would expire in the
  queue anyway) are rejected; normal/high traffic still admits.
- tier 2 **reject** — depth ≥ ``reject_watermark``: everything but
  supervisor health probes (``bypass_admission=True``) is rejected.

Shed rejections raise :class:`RejectedError` (UNAVAILABLE → HTTP 429,
transient) and count on ``serving_shed_total`` (+ per-reason counters);
every tier TRANSITION is journaled as a ``serving.shed_tier`` telemetry
event with the depth/p99 evidence that drove it (docs/serving.md).

Counting has ONE source of truth: with a telemetry hub enabled the
registry carries every count (``stats()`` derives the /stats view from
the same snapshot /metrics exposes); only with telemetry disabled does
the batcher maintain its own minimal mirror so /stats still answers.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Optional

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.utils.watchdog import RetryPolicy


class RejectedError(RuntimeError):
    """Admission control: the bounded request queue is full.

    The message carries watchdog's UNAVAILABLE marker — transient by
    classification, the client should back off and retry."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before (or while) it was scored."""


#: admission tiers, in escalation order (module docstring).
TIER_ACCEPT, TIER_SHED, TIER_REJECT = 0, 1, 2
TIER_NAMES = ("accept", "shed", "reject")


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    """Coalescing knobs (model/bucket knobs live on RuntimeConfig)."""

    #: rows one dispatch coalesces at most; capped by the runtime's top
    #: bucket at construction.
    max_batch_size: int = 64
    #: how long the dispatcher waits for more rows after the first one.
    #: 0 disables coalescing (every request scores alone — highest
    #: throughput cost, lowest latency under no load).
    max_wait_us: int = 2000
    #: bounded queue depth; submissions beyond it are REJECTED, not
    #: buffered (explicit backpressure beats silent latency collapse).
    max_queue: int = 256
    #: default per-request deadline; None = no deadline.
    default_timeout_ms: Optional[float] = None
    #: queue-depth fraction at which tier 1 (shed low-priority /
    #: over-deadline work) engages.
    shed_watermark: float = 0.5
    #: queue-depth fraction at which tier 2 (reject everything but
    #: probes) engages; the queue-full RejectedError stays the backstop.
    reject_watermark: float = 0.9
    #: latency SLO: an observed request p99 above this escalates
    #: admission to at least tier 1.  None disables the latency signal
    #: (depth watermarks still apply); it also needs an enabled
    #: telemetry hub — the p99 is read from the live
    #: ``serving_request_latency_seconds`` histogram.
    p99_slo_ms: Optional[float] = None
    #: how often (seconds) the p99 estimate is refreshed; between
    #: refreshes a submit pays one queue-depth read and comparisons.
    admission_interval_s: float = 0.1


@dataclasses.dataclass
class _Pending:
    row: object
    future: Future
    t_submit: float
    deadline: Optional[float]  # perf_counter seconds, None = no deadline
    #: submitter's trace context — the dispatch thread's serving.batch
    #: span parents to it, so a request's wait + batch execution nest
    #: under the span that submitted it (cross-thread tracing).
    ctx: Optional[tuple] = None


_STOP = object()


class MicroBatcher:
    """Bounded-queue request coalescer in front of a ScoringRuntime."""

    def __init__(
        self,
        runtime,
        config: Optional[BatcherConfig] = None,
        policy: Optional[RetryPolicy] = None,
    ):
        cfg = config or BatcherConfig()
        if cfg.max_batch_size > runtime.buckets[-1]:
            cfg = dataclasses.replace(
                cfg, max_batch_size=runtime.buckets[-1]
            )
        if not (0.0 < cfg.shed_watermark <= cfg.reject_watermark <= 1.0):
            raise ValueError(
                "need 0 < shed_watermark <= reject_watermark <= 1, got "
                f"{cfg.shed_watermark} / {cfg.reject_watermark}"
            )
        # NOTE ``self.runtime`` is re-read at every dispatch: plain
        # attribute assignment is the hot-swap commit point
        # (serving/swap.py) — atomic under the GIL, no lock needed.
        self.runtime = runtime
        self.config = cfg
        self.policy = policy or RetryPolicy()
        self._queue: "queue.Queue" = queue.Queue(maxsize=cfg.max_queue)
        self._thread: Optional[threading.Thread] = None
        self._lock = sanitizers.tracked(
            threading.Lock(), "serving.batcher"
        )
        # Admission-control state: current tier + the cached p99 read
        # (refreshed at most every admission_interval_s).
        self._tier = TIER_ACCEPT
        self._p99_ms: Optional[float] = None
        self._p99_refresh_t = 0.0
        # Internal counters exist ONLY for the telemetry-disabled path:
        # with a hub enabled, the registry is the single source of truth
        # and stats() derives every count from it (mirror drift is
        # structurally impossible because the mirror is never written).
        self._counts = {
            "submitted": 0,
            "completed": 0,
            "rejected": 0,
            "shed": 0,
            "shed_low_priority": 0,
            "shed_deadline": 0,
            "tier_transitions": 0,
            "expired": 0,
            "failed": 0,
            "failed_transient": 0,
            "failed_permanent": 0,
            "batches": 0,
            "max_batch_rows": 0,
        }

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "MicroBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="scoring-dispatch", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        if self._thread is None:
            return
        self._queue.put(_STOP)
        self._thread.join(timeout=timeout)
        self._thread = None
        # Fail anything that raced past admission after the _STOP went
        # in — nothing will ever dispatch it.  Transient vocabulary, not
        # RejectedError: a supervisor treats this as the BATCHER's fault
        # and resubmits the row to a peer replica.
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is _STOP:
                continue
            if item.future.set_running_or_notify_cancel():
                item.future.set_exception(RuntimeError(
                    "UNAVAILABLE: batcher stopped before dispatch; "
                    "retry with backoff"
                ))

    # -- admission control (any thread) ------------------------------------
    def _observed_p99_ms(self, now: float) -> Optional[float]:
        """Cached read of the live request-latency p99, in ms; None when
        the SLO signal is off, the hub is disabled, or no observations
        exist yet."""
        if self.config.p99_slo_ms is None:
            return None
        if now >= self._p99_refresh_t:
            self._p99_refresh_t = now + self.config.admission_interval_s
            hist = telemetry_mod.current().histogram(
                "serving_request_latency_seconds"
            )
            quantile = getattr(hist, "quantile", None)
            p99_s = None if quantile is None else quantile(0.99)
            self._p99_ms = None if p99_s is None else p99_s * 1e3
        return self._p99_ms

    def admission_tier(self, now: Optional[float] = None) -> int:
        """The current admission tier (module docstring): max of the
        depth-watermark tier and the p99-SLO tier."""
        if now is None:
            now = time.perf_counter()
        frac = self._queue.qsize() / self.config.max_queue
        if frac >= self.config.reject_watermark:
            tier = TIER_REJECT
        elif frac >= self.config.shed_watermark:
            tier = TIER_SHED
        else:
            tier = TIER_ACCEPT
        p99 = self._observed_p99_ms(now)
        if (
            tier < TIER_SHED
            and p99 is not None
            and p99 > self.config.p99_slo_ms
        ):
            tier = TIER_SHED
        return tier

    def _note_tier(self, tier: int) -> None:
        """Journal a tier transition: gauge + counter + telemetry event
        carrying the evidence (depth, p99) that drove it."""
        with self._lock:
            prev = self._tier
            if tier == prev:
                return
            self._tier = tier
        self._count("tier_transitions")
        tel = telemetry_mod.current()
        tel.counter("serving_tier_transitions_total").inc()
        tel.gauge("serving_shed_tier").set(tier)
        tel.event(
            "serving.shed_tier",
            tier=TIER_NAMES[tier],
            previous=TIER_NAMES[prev],
            queue_depth=self._queue.qsize(),
            max_queue=self.config.max_queue,
            p99_ms=self._p99_ms,
        )

    def _shed(self, reason: str, detail: str) -> RejectedError:
        self._count("shed")
        tel = telemetry_mod.current()
        tel.counter("serving_shed_total").inc()
        if reason == "reject_tier":
            # The reject tier refuses ALL non-probe traffic — that is
            # the same verdict the pre-tier queue-full backstop gave, so
            # it keeps feeding the legacy rejection counters.
            self._count("rejected")
            tel.counter("serving_rejected_total").inc()
        if reason == "low_priority":
            self._count("shed_low_priority")
            tel.counter("serving_shed_low_priority_total").inc()
        elif reason == "deadline":
            self._count("shed_deadline")
            tel.counter("serving_shed_deadline_total").inc()
        exc = RejectedError(
            f"UNAVAILABLE: load shed ({detail}); retry with backoff"
        )
        self._classify(exc)
        return exc

    # -- submission (any thread) -------------------------------------------
    def submit(
        self,
        row,
        timeout_ms: Optional[float] = None,
        bypass_admission: bool = False,
    ) -> Future:
        """Enqueue one request; returns its future.

        Raises :class:`RejectedError` immediately when the tiered
        admission controller sheds the row or the queue is full —
        admission control is synchronous so the caller can shed load
        (HTTP 429) without waiting on a future.  ``bypass_admission``
        skips the tier check (NOT the queue-full backstop): supervisor
        health probes must keep flowing under overload, or shedding
        would read as replica death and trigger a restart storm.
        """
        tel = telemetry_mod.current()
        timeout = (
            timeout_ms
            if timeout_ms is not None
            else getattr(row, "timeout_ms", None)
        )
        if timeout is None:
            timeout = self.config.default_timeout_ms
        now = time.perf_counter()
        tier = self.admission_tier(now)
        self._note_tier(tier)
        if tier > TIER_ACCEPT and not bypass_admission:
            if tier >= TIER_REJECT:
                raise self._shed(
                    "reject_tier",
                    f"admission tier {TIER_NAMES[tier]}, queue "
                    f"{self._queue.qsize()}/{self.config.max_queue}",
                )
            priority = getattr(row, "priority", "normal")
            if priority == "low":
                raise self._shed(
                    "low_priority",
                    "low-priority request at admission tier shed",
                )
            if (
                timeout is not None
                and self._p99_ms is not None
                and timeout < self._p99_ms
            ):
                raise self._shed(
                    "deadline",
                    f"deadline budget {timeout:.0f} ms is under the "
                    f"observed p99 {self._p99_ms:.0f} ms; it would "
                    "expire in the queue",
                )
        pending = _Pending(
            row=row,
            future=Future(),
            t_submit=now,
            deadline=None if timeout is None else now + timeout / 1e3,
            ctx=tel.current_context(),
        )
        try:
            self._queue.put_nowait(pending)
        except queue.Full:
            self._count("rejected")
            tel.counter("serving_rejected_total").inc()
            exc = RejectedError(
                f"UNAVAILABLE: serving queue full "
                f"({self.config.max_queue} pending); retry with backoff"
            )
            self._classify(exc)
            raise exc
        self._count("submitted")
        tel.counter("serving_requests_total").inc()
        tel.gauge("serving_queue_depth").set(self._queue.qsize())
        return pending.future

    # -- dispatch loop (one thread) ----------------------------------------
    def _loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is _STOP:
                return
            batch = [item]
            stop_after = False
            wait_s = self.config.max_wait_us / 1e6
            t_close = time.perf_counter() + wait_s
            while len(batch) < self.config.max_batch_size:
                remaining = t_close - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop_after = True
                    break
                batch.append(nxt)
            self._dispatch(batch)
            if stop_after:
                return

    def _dispatch(self, batch: list) -> None:
        tel = telemetry_mod.current()
        # One read per dispatch: the whole batch scores against a single
        # runtime even if a hot-swap commits mid-dispatch (swap.py).
        runtime = self.runtime
        tel.gauge("serving_queue_depth").set(self._queue.qsize())
        now = time.perf_counter()
        live = []
        for p in batch:
            if p.deadline is not None and now > p.deadline:
                waited_ms = (now - p.t_submit) * 1e3
                self._count("expired")
                tel.counter("serving_deadline_expired_total").inc()
                self._fail(p, DeadlineExceededError(
                    f"DEADLINE_EXCEEDED: request waited {waited_ms:.1f} ms "
                    "past its deadline before dispatch"
                ))
            else:
                live.append(p)
        if not live:
            return
        # Cross-thread trace propagation: the batch executes on the
        # dispatch thread, but its span parents to the FIRST live
        # request's submitting span (batch-mates ride along as the rows
        # count) — a request's end-to-end latency reads as one nested
        # tree in Perfetto instead of orphaned root spans.
        ctx = next((p.ctx for p in live if p.ctx is not None), None)
        try:
            with tel.attach(ctx), tel.span(
                "serving.batch", rows=len(live)
            ):
                chaos_mod.maybe_fail("serving.batch", rows=len(live))
                margins, means = runtime.score_rows(
                    [p.row for p in live]
                )
        except Exception as exc:  # noqa: BLE001 — classified + surfaced
            for p in live:
                self._fail(p, exc)
            return
        done = time.perf_counter()
        bucket = runtime.bucket_for(len(live))
        if not tel.enabled:
            with self._lock:
                self._counts["batches"] += 1
                self._counts["completed"] += len(live)
                self._counts["max_batch_rows"] = max(
                    self._counts["max_batch_rows"], len(live)
                )
        tel.histogram("serving_batch_rows").observe(len(live))
        tel.gauge("serving_batch_occupancy").set(len(live) / bucket)
        for i, p in enumerate(live):
            latency = done - p.t_submit
            tel.histogram("serving_request_latency_seconds").observe(latency)
            if not p.future.set_running_or_notify_cancel():
                continue  # client cancelled while queued
            p.future.set_result({
                "score": float(margins[i]),
                "mean": float(means[i]),
                "latency_ms": latency * 1e3,
            })

    # -- failure plumbing --------------------------------------------------
    def _classify(self, exc: BaseException):
        """Watchdog-vocabulary classification of a request failure; feeds
        the transient/permanent split in stats and telemetry."""
        verdict = self.policy.classify(exc)
        self._count(
            "failed_transient" if verdict.transient else "failed_permanent"
        )
        telemetry_mod.current().counter(
            "serving_failures_transient_total" if verdict.transient
            else "serving_failures_permanent_total"
        ).inc()
        return verdict

    def _fail(self, p: _Pending, exc: BaseException) -> None:
        self._count("failed")
        telemetry_mod.current().counter(
            "serving_failed_requests_total"
        ).inc()
        self._classify(exc)
        if p.future.set_running_or_notify_cancel():
            p.future.set_exception(exc)

    def _count(self, key: str, n: int = 1) -> None:
        # Disabled-hub mirror only — see __init__; with a hub installed
        # the registry carries the count and this is a no-op.
        if telemetry_mod.current().enabled:
            return
        with self._lock:
            self._counts[key] += n

    # -- observability -----------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return self._queue.qsize()

    #: stats key → how to derive it from the telemetry snapshot.  The
    #: batch aggregates come from the serving_batch_rows histogram: one
    #: observation per dispatched batch, value = live rows, so count =
    #: batches, sum = completed rows, max = max_batch_rows.
    _HUB_COUNTERS = {
        "submitted": "serving_requests_total",
        "rejected": "serving_rejected_total",
        "shed": "serving_shed_total",
        "shed_low_priority": "serving_shed_low_priority_total",
        "shed_deadline": "serving_shed_deadline_total",
        "tier_transitions": "serving_tier_transitions_total",
        "expired": "serving_deadline_expired_total",
        "failed": "serving_failed_requests_total",
        "failed_transient": "serving_failures_transient_total",
        "failed_permanent": "serving_failures_permanent_total",
    }

    def stats(self) -> dict:
        tel = telemetry_mod.current()
        if tel.enabled:
            # Single source of truth: derive every count from the hub's
            # registry (the same numbers /metrics exposes).  Note the
            # registry is process-wide — two batchers under one hub sum.
            snap = tel.metrics.snapshot()
            counters = snap["counters"]
            hist = snap["histograms"].get("serving_batch_rows") or {}
            counts = {
                key: counters.get(name, 0)
                for key, name in self._HUB_COUNTERS.items()
            }
            counts["batches"] = hist.get("count", 0)
            counts["completed"] = int(hist.get("sum") or 0)
            counts["max_batch_rows"] = int(hist.get("max") or 0)
            counts["source"] = "telemetry"
        else:
            with self._lock:
                counts = dict(self._counts)
            counts["source"] = "internal"
        counts["queue_depth"] = self._queue.qsize()
        counts["max_queue"] = self.config.max_queue
        counts["max_batch_size"] = self.config.max_batch_size
        counts["max_wait_us"] = self.config.max_wait_us
        with self._lock:
            counts["tier"] = TIER_NAMES[self._tier]
        counts["model_version"] = getattr(self.runtime, "model_version", 1)
        return counts
