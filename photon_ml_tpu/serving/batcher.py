"""Micro-batching request coalescer for the online scoring path.

One dispatch thread drains a bounded queue: it blocks for the first
pending request, then coalesces more until ``max_batch_size`` rows are in
hand or ``max_wait_us`` has elapsed, pads to the runtime's nearest bucket,
dispatches ONE kernel call, and scatters results back to per-request
futures.  The shape follows the batching/caching discipline of
hierarchical ML runtimes (Snap ML, arXiv:1803.06333) and the
pipeline-overlap serving designs of arXiv:1702.07005: fixed-shape
pre-compiled kernels + request coalescing turn many tiny latency-bound
calls into few device-efficient ones.

Failure semantics ride :mod:`photon_ml_tpu.utils.watchdog`'s
classification vocabulary so clients can reuse its retry discipline:

- **Admission control**: a full queue rejects at submit time with
  :class:`RejectedError` ("UNAVAILABLE: ..." — transient, retry later).
- **Deadlines**: a request that waited past its ``timeout_ms`` fails with
  :class:`DeadlineExceededError` ("DEADLINE_EXCEEDED: ..." — transient).
- Every failure is classified through the batcher's ``RetryPolicy``
  (``classify(exc)``) and counted as transient vs permanent.

Counting has ONE source of truth: with a telemetry hub enabled the
registry carries every count (``stats()`` derives the /stats view from
the same snapshot /metrics exposes); only with telemetry disabled does
the batcher maintain its own minimal mirror so /stats still answers.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Optional

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.utils.watchdog import RetryPolicy


class RejectedError(RuntimeError):
    """Admission control: the bounded request queue is full.

    The message carries watchdog's UNAVAILABLE marker — transient by
    classification, the client should back off and retry."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before (or while) it was scored."""


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    """Coalescing knobs (model/bucket knobs live on RuntimeConfig)."""

    #: rows one dispatch coalesces at most; capped by the runtime's top
    #: bucket at construction.
    max_batch_size: int = 64
    #: how long the dispatcher waits for more rows after the first one.
    #: 0 disables coalescing (every request scores alone — highest
    #: throughput cost, lowest latency under no load).
    max_wait_us: int = 2000
    #: bounded queue depth; submissions beyond it are REJECTED, not
    #: buffered (explicit backpressure beats silent latency collapse).
    max_queue: int = 256
    #: default per-request deadline; None = no deadline.
    default_timeout_ms: Optional[float] = None


@dataclasses.dataclass
class _Pending:
    row: object
    future: Future
    t_submit: float
    deadline: Optional[float]  # perf_counter seconds, None = no deadline
    #: submitter's trace context — the dispatch thread's serving.batch
    #: span parents to it, so a request's wait + batch execution nest
    #: under the span that submitted it (cross-thread tracing).
    ctx: Optional[tuple] = None


_STOP = object()


class MicroBatcher:
    """Bounded-queue request coalescer in front of a ScoringRuntime."""

    def __init__(
        self,
        runtime,
        config: Optional[BatcherConfig] = None,
        policy: Optional[RetryPolicy] = None,
    ):
        cfg = config or BatcherConfig()
        if cfg.max_batch_size > runtime.buckets[-1]:
            cfg = dataclasses.replace(
                cfg, max_batch_size=runtime.buckets[-1]
            )
        self.runtime = runtime
        self.config = cfg
        self.policy = policy or RetryPolicy()
        self._queue: "queue.Queue" = queue.Queue(maxsize=cfg.max_queue)
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # Internal counters exist ONLY for the telemetry-disabled path:
        # with a hub enabled, the registry is the single source of truth
        # and stats() derives every count from it (mirror drift is
        # structurally impossible because the mirror is never written).
        self._counts = {
            "submitted": 0,
            "completed": 0,
            "rejected": 0,
            "expired": 0,
            "failed": 0,
            "failed_transient": 0,
            "failed_permanent": 0,
            "batches": 0,
            "max_batch_rows": 0,
        }

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "MicroBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="scoring-dispatch", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        if self._thread is None:
            return
        self._queue.put(_STOP)
        self._thread.join(timeout=timeout)
        self._thread = None

    # -- submission (any thread) -------------------------------------------
    def submit(self, row, timeout_ms: Optional[float] = None) -> Future:
        """Enqueue one request; returns its future.

        Raises :class:`RejectedError` immediately when the queue is full
        — admission control is synchronous so the caller can shed load
        (HTTP 429) without waiting on a future.
        """
        tel = telemetry_mod.current()
        timeout = (
            timeout_ms
            if timeout_ms is not None
            else getattr(row, "timeout_ms", None)
        )
        if timeout is None:
            timeout = self.config.default_timeout_ms
        now = time.perf_counter()
        pending = _Pending(
            row=row,
            future=Future(),
            t_submit=now,
            deadline=None if timeout is None else now + timeout / 1e3,
            ctx=tel.current_context(),
        )
        try:
            self._queue.put_nowait(pending)
        except queue.Full:
            self._count("rejected")
            tel.counter("serving_rejected_total").inc()
            exc = RejectedError(
                f"UNAVAILABLE: serving queue full "
                f"({self.config.max_queue} pending); retry with backoff"
            )
            self._classify(exc)
            raise exc
        self._count("submitted")
        tel.counter("serving_requests_total").inc()
        tel.gauge("serving_queue_depth").set(self._queue.qsize())
        return pending.future

    # -- dispatch loop (one thread) ----------------------------------------
    def _loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is _STOP:
                return
            batch = [item]
            stop_after = False
            wait_s = self.config.max_wait_us / 1e6
            t_close = time.perf_counter() + wait_s
            while len(batch) < self.config.max_batch_size:
                remaining = t_close - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop_after = True
                    break
                batch.append(nxt)
            self._dispatch(batch)
            if stop_after:
                return

    def _dispatch(self, batch: list) -> None:
        tel = telemetry_mod.current()
        tel.gauge("serving_queue_depth").set(self._queue.qsize())
        now = time.perf_counter()
        live = []
        for p in batch:
            if p.deadline is not None and now > p.deadline:
                waited_ms = (now - p.t_submit) * 1e3
                self._count("expired")
                tel.counter("serving_deadline_expired_total").inc()
                self._fail(p, DeadlineExceededError(
                    f"DEADLINE_EXCEEDED: request waited {waited_ms:.1f} ms "
                    "past its deadline before dispatch"
                ))
            else:
                live.append(p)
        if not live:
            return
        # Cross-thread trace propagation: the batch executes on the
        # dispatch thread, but its span parents to the FIRST live
        # request's submitting span (batch-mates ride along as the rows
        # count) — a request's end-to-end latency reads as one nested
        # tree in Perfetto instead of orphaned root spans.
        ctx = next((p.ctx for p in live if p.ctx is not None), None)
        try:
            with tel.attach(ctx), tel.span(
                "serving.batch", rows=len(live)
            ):
                chaos_mod.maybe_fail("serving.batch", rows=len(live))
                margins, means = self.runtime.score_rows(
                    [p.row for p in live]
                )
        except Exception as exc:  # noqa: BLE001 — classified + surfaced
            for p in live:
                self._fail(p, exc)
            return
        done = time.perf_counter()
        bucket = self.runtime.bucket_for(len(live))
        if not tel.enabled:
            with self._lock:
                self._counts["batches"] += 1
                self._counts["completed"] += len(live)
                self._counts["max_batch_rows"] = max(
                    self._counts["max_batch_rows"], len(live)
                )
        tel.histogram("serving_batch_rows").observe(len(live))
        tel.gauge("serving_batch_occupancy").set(len(live) / bucket)
        for i, p in enumerate(live):
            latency = done - p.t_submit
            tel.histogram("serving_request_latency_seconds").observe(latency)
            if not p.future.set_running_or_notify_cancel():
                continue  # client cancelled while queued
            p.future.set_result({
                "score": float(margins[i]),
                "mean": float(means[i]),
                "latency_ms": latency * 1e3,
            })

    # -- failure plumbing --------------------------------------------------
    def _classify(self, exc: BaseException):
        """Watchdog-vocabulary classification of a request failure; feeds
        the transient/permanent split in stats and telemetry."""
        verdict = self.policy.classify(exc)
        self._count(
            "failed_transient" if verdict.transient else "failed_permanent"
        )
        telemetry_mod.current().counter(
            "serving_failures_transient_total" if verdict.transient
            else "serving_failures_permanent_total"
        ).inc()
        return verdict

    def _fail(self, p: _Pending, exc: BaseException) -> None:
        self._count("failed")
        telemetry_mod.current().counter(
            "serving_failed_requests_total"
        ).inc()
        self._classify(exc)
        if p.future.set_running_or_notify_cancel():
            p.future.set_exception(exc)

    def _count(self, key: str, n: int = 1) -> None:
        # Disabled-hub mirror only — see __init__; with a hub installed
        # the registry carries the count and this is a no-op.
        if telemetry_mod.current().enabled:
            return
        with self._lock:
            self._counts[key] += n

    # -- observability -----------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return self._queue.qsize()

    #: stats key → how to derive it from the telemetry snapshot.  The
    #: batch aggregates come from the serving_batch_rows histogram: one
    #: observation per dispatched batch, value = live rows, so count =
    #: batches, sum = completed rows, max = max_batch_rows.
    _HUB_COUNTERS = {
        "submitted": "serving_requests_total",
        "rejected": "serving_rejected_total",
        "expired": "serving_deadline_expired_total",
        "failed": "serving_failed_requests_total",
        "failed_transient": "serving_failures_transient_total",
        "failed_permanent": "serving_failures_permanent_total",
    }

    def stats(self) -> dict:
        tel = telemetry_mod.current()
        if tel.enabled:
            # Single source of truth: derive every count from the hub's
            # registry (the same numbers /metrics exposes).  Note the
            # registry is process-wide — two batchers under one hub sum.
            snap = tel.metrics.snapshot()
            counters = snap["counters"]
            hist = snap["histograms"].get("serving_batch_rows") or {}
            counts = {
                key: counters.get(name, 0)
                for key, name in self._HUB_COUNTERS.items()
            }
            counts["batches"] = hist.get("count", 0)
            counts["completed"] = int(hist.get("sum") or 0)
            counts["max_batch_rows"] = int(hist.get("max") or 0)
            counts["source"] = "telemetry"
        else:
            with self._lock:
                counts = dict(self._counts)
            counts["source"] = "internal"
        counts["queue_depth"] = self._queue.qsize()
        counts["max_queue"] = self.config.max_queue
        counts["max_batch_size"] = self.config.max_batch_size
        counts["max_wait_us"] = self.config.max_wait_us
        return counts
