"""Micro-batching request coalescer for the online scoring path.

One dispatch thread drains a bounded queue: it blocks for the first
pending request, then coalesces more until ``max_batch_size`` rows are in
hand or ``max_wait_us`` has elapsed, pads to the runtime's nearest bucket,
dispatches ONE kernel call, and scatters results back to per-request
futures.  The shape follows the batching/caching discipline of
hierarchical ML runtimes (Snap ML, arXiv:1803.06333) and the
pipeline-overlap serving designs of arXiv:1702.07005: fixed-shape
pre-compiled kernels + request coalescing turn many tiny latency-bound
calls into few device-efficient ones.

Failure semantics ride :mod:`photon_ml_tpu.utils.watchdog`'s
classification vocabulary so clients can reuse its retry discipline:

- **Admission control**: a full queue rejects at submit time with
  :class:`RejectedError` ("UNAVAILABLE: ..." — transient, retry later).
- **Deadlines**: a request that waited past its ``timeout_ms`` fails with
  :class:`DeadlineExceededError` ("DEADLINE_EXCEEDED: ..." — transient).
- Every failure is classified through the batcher's ``RetryPolicy``
  (``classify(exc)``) and counted as transient vs permanent.

**Tiered load shedding** — the binary queue-full reject is only the
backstop.  Admission is evaluated per submit against three tiers driven
by queue depth (watermark fractions of ``max_queue``) and the observed
p99 of the live ``serving_request_latency_seconds`` histogram:

- tier 0 **accept** — depth below ``shed_watermark``: everything admits.
- tier 1 **shed** — depth ≥ ``shed_watermark``, or observed p99 over
  ``p99_slo_ms``: ``priority="low"`` rows and over-deadline work (a
  deadline budget smaller than the current p99 — it would expire in the
  queue anyway) are rejected; normal/high traffic still admits.
- tier 2 **reject** — depth ≥ ``reject_watermark``: everything but
  supervisor health probes (``bypass_admission=True``) is rejected.

Shed rejections raise :class:`RejectedError` (UNAVAILABLE → HTTP 429,
transient) and count on ``serving_shed_total`` (+ per-reason counters);
every tier TRANSITION is journaled as a ``serving.shed_tier`` telemetry
event with the depth/p99 evidence that drove it (docs/serving.md).

Counting has ONE source of truth: with a telemetry hub enabled the
registry carries every count (``stats()`` derives the /stats view from
the same snapshot /metrics exposes); only with telemetry disabled does
the batcher maintain its own minimal mirror so /stats still answers.

**Tenancy** — when ``BatcherConfig.tenancy`` carries a
:class:`~photon_ml_tpu.serving.tenancy.TenancyConfig`, every tenant gets
its own isolation boundary IN FRONT of the shared admission controller
(docs/serving.md):

- a **bulkhead queue partition**: the physical queue is sized to the sum
  of all partitions, and a tenant whose partition is full is rejected
  without touching a neighbor's slots;
- a **token-bucket quota** (sustained rps + burst) — over-quota traffic
  sheds with the tenant named in the error;
- its own **admission tiers** (partition-depth watermarks + a per-tenant
  observed p99 from ``serving_tenant_<t>_request_latency_seconds``
  against the tenant's own SLO);
- a **circuit breaker** (chaos/breaker.py) fed by per-tenant dispatch
  outcomes, so a tenant whose model path is failing degrades alone;
- a **tenant route**: dispatch groups rows by tenant and scores each
  group against that tenant's committed runtime (tenant-scoped hot
  swap, serving/swap.py), with the ``serving.tenant`` chaos site
  instrumenting exactly the tenant-routed scoring path.

With ``tenancy=None`` every code path below collapses to the
single-tenant behavior above, byte for byte.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Optional

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.chaos import breaker as breaker_mod
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.serving import tenancy as tenancy_mod
from photon_ml_tpu.utils.watchdog import RetryPolicy


class RejectedError(RuntimeError):
    """Admission control: the bounded request queue is full.

    The message carries watchdog's UNAVAILABLE marker — transient by
    classification, the client should back off and retry."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before (or while) it was scored."""


#: admission tiers, in escalation order (module docstring).
TIER_ACCEPT, TIER_SHED, TIER_REJECT = 0, 1, 2
TIER_NAMES = ("accept", "shed", "reject")


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    """Coalescing knobs (model/bucket knobs live on RuntimeConfig)."""

    #: rows one dispatch coalesces at most; capped by the runtime's top
    #: bucket at construction.
    max_batch_size: int = 64
    #: how long the dispatcher waits for more rows after the first one.
    #: 0 disables coalescing (every request scores alone — highest
    #: throughput cost, lowest latency under no load).  With
    #: ``adaptive_wait`` on this becomes the CEILING the adaptive
    #: policy never exceeds (the static knob stays the override).
    max_wait_us: int = 2000
    #: size the batch wait from the OBSERVED arrival rate instead of the
    #: static ``max_wait_us``: an EWMA over inter-arrival times
    #: estimates how long filling a batch would take; the dispatcher
    #: waits that long when it is under ``max_wait_us`` (traffic dense
    #: enough to fill a batch quickly) and drops to ``min_wait_us`` when
    #: it is not (sparse traffic must not idle a request at the ceiling
    #: for batch-mates that are not coming).  Bounded by
    #: ``slo_wait_fraction`` of the tightest p99 SLO in play.
    adaptive_wait: bool = False
    #: adaptive-mode floor: the wait under sparse traffic (microseconds).
    min_wait_us: int = 100
    #: EWMA smoothing factor over inter-arrival times, in (0, 1];
    #: higher = faster reaction to rate changes, lower = steadier waits.
    wait_ewma_alpha: float = 0.2
    #: adaptive waits never exceed this fraction of the tightest
    #: configured p99 SLO (global ``p99_slo_ms`` and every tenant's) —
    #: queueing time must leave the SLO room for scoring time.
    slo_wait_fraction: float = 0.25
    #: bounded queue depth; submissions beyond it are REJECTED, not
    #: buffered (explicit backpressure beats silent latency collapse).
    max_queue: int = 256
    #: default per-request deadline; None = no deadline.
    default_timeout_ms: Optional[float] = None
    #: queue-depth fraction at which tier 1 (shed low-priority /
    #: over-deadline work) engages.
    shed_watermark: float = 0.5
    #: queue-depth fraction at which tier 2 (reject everything but
    #: probes) engages; the queue-full RejectedError stays the backstop.
    reject_watermark: float = 0.9
    #: latency SLO: an observed request p99 above this escalates
    #: admission to at least tier 1.  None disables the latency signal
    #: (depth watermarks still apply); it also needs an enabled
    #: telemetry hub — the p99 is read from the live
    #: ``serving_request_latency_seconds`` histogram.
    p99_slo_ms: Optional[float] = None
    #: how often (seconds) the p99 estimate is refreshed; between
    #: refreshes a submit pays one queue-depth read and comparisons.
    admission_interval_s: float = 0.1
    #: multi-tenant isolation policy (serving/tenancy.py): per-tenant
    #: bulkhead partitions, quotas, tiers, SLOs, and breakers.  None =
    #: single-tenant behavior, bit-identical to before the field
    #: existed.  Frozen + picklable, so it rides the spawn args into
    #: process-mode workers unchanged (serving/worker.py).
    tenancy: Optional["tenancy_mod.TenancyConfig"] = None

    def __post_init__(self) -> None:
        # Pointed refusals at construction: a bad knob must name itself
        # here, not surface later as a hang (max_batch_size=0 would
        # dispatch nothing), a busy-spin (negative waits), or a queue
        # that admits nothing (inverted watermarks).
        if self.max_batch_size < 1:
            raise ValueError(
                f"max_batch_size must be >= 1, got {self.max_batch_size}"
            )
        if self.max_wait_us < 0:
            raise ValueError(
                f"max_wait_us must be >= 0, got {self.max_wait_us}"
            )
        if self.max_queue < 1:
            raise ValueError(
                f"max_queue must be >= 1, got {self.max_queue}"
            )
        if not (0.0 < self.shed_watermark <= self.reject_watermark <= 1.0):
            raise ValueError(
                "need 0 < shed_watermark <= reject_watermark <= 1, got "
                f"{self.shed_watermark} / {self.reject_watermark}"
            )
        if self.default_timeout_ms is not None \
                and self.default_timeout_ms <= 0:
            raise ValueError(
                "default_timeout_ms must be positive (or None), got "
                f"{self.default_timeout_ms}"
            )
        if self.p99_slo_ms is not None and self.p99_slo_ms <= 0:
            raise ValueError(
                f"p99_slo_ms must be positive (or None), got "
                f"{self.p99_slo_ms}"
            )
        if self.admission_interval_s < 0:
            raise ValueError(
                "admission_interval_s must be >= 0, got "
                f"{self.admission_interval_s}"
            )
        if self.min_wait_us < 0:
            raise ValueError(
                f"min_wait_us must be >= 0, got {self.min_wait_us}"
            )
        if not (0.0 < self.wait_ewma_alpha <= 1.0):
            raise ValueError(
                f"wait_ewma_alpha must be in (0, 1], got "
                f"{self.wait_ewma_alpha}"
            )
        if not (0.0 < self.slo_wait_fraction <= 1.0):
            raise ValueError(
                f"slo_wait_fraction must be in (0, 1], got "
                f"{self.slo_wait_fraction}"
            )


@dataclasses.dataclass
class _Pending:
    row: object
    future: Future
    t_submit: float
    deadline: Optional[float]  # perf_counter seconds, None = no deadline
    #: submitter's trace context — the dispatch thread's serving.batch
    #: span parents to it, so a request's wait + batch execution nest
    #: under the span that submitted it (cross-thread tracing).
    ctx: Optional[tuple] = None
    #: the tenant partition this row occupies (``_TenantState``); None
    #: when tenancy is off.  Dispatch decrements the partition depth
    #: through this reference once the row leaves the queue.
    tenant_state: Optional[object] = None
    #: stage-decomposition timestamps (perf_counter seconds): when
    #: submit finished admission and enqueued the row, and when the
    #: dispatch loop pulled it back out.  Together with the group's
    #: scoring wall they split ``latency_ms`` into admission / queue /
    #: batch-wait / device stages (docs/telemetry.md).
    t_enqueue: float = 0.0
    t_pickup: float = 0.0


_STOP = object()


class _TenantState:
    """One tenant's live enforcement state: partition depth, token
    bucket, tier cache, and circuit breaker.  Named tenants each get
    one; every unknown/absent tenant shares the default spec's state.

    The bucket, depth, tier, and breaker mutate ONLY under the
    batcher's tenancy lock ("serving.tenancy") — the breaker is
    single-writer by design (chaos/breaker.py) and submit runs on many
    threads.  The p99 cache fields are racy-but-benign (worst case a
    duplicate refresh), matching the batcher's global p99 cache."""

    __slots__ = (
        "spec", "slug", "depth", "tier", "bucket", "breaker",
        "p99_ms", "p99_refresh_t",
    )

    def __init__(self, spec: "tenancy_mod.TenantSpec"):
        self.spec = spec
        self.slug = spec.slug
        self.depth = 0
        self.tier = TIER_ACCEPT
        self.bucket = tenancy_mod.TokenBucket(
            spec.quota_rps, spec.effective_burst
        )
        self.breaker = breaker_mod.CircuitBreaker(
            cooldown_seconds=spec.breaker_cooldown_s,
            failure_threshold=spec.breaker_failure_threshold,
        )
        self.p99_ms: Optional[float] = None
        self.p99_refresh_t = 0.0


class MicroBatcher:
    """Bounded-queue request coalescer in front of a ScoringRuntime."""

    def __init__(
        self,
        runtime,
        config: Optional[BatcherConfig] = None,
        policy: Optional[RetryPolicy] = None,
    ):
        cfg = config or BatcherConfig()
        if cfg.max_batch_size > runtime.buckets[-1]:
            cfg = dataclasses.replace(
                cfg, max_batch_size=runtime.buckets[-1]
            )
        # NOTE ``self.runtime`` is re-read at every dispatch: plain
        # attribute assignment is the hot-swap commit point
        # (serving/swap.py) — atomic under the GIL, no lock needed.
        self.runtime = runtime
        self.config = cfg
        self.policy = policy or RetryPolicy()
        self._tenancy = cfg.tenancy
        if self._tenancy is not None:
            # Bulkhead partitions: the physical queue holds the SUM of
            # every tenant partition (plus slack so bypass probes keep
            # flowing at saturation) — a tenant filling its own
            # partition can never consume a neighbor's slots, and
            # _capacity is the denominator every global-tier fraction
            # uses.
            self._tenant_states = {
                t.name: _TenantState(t) for t in self._tenancy.tenants
            }
            self._default_state: Optional[_TenantState] = _TenantState(
                self._tenancy.default
            )
            self._capacity = self._tenancy.partition_total
            self._tenant_lock = sanitizers.tracked(
                threading.Lock(), "serving.tenancy"
            )
        else:
            self._tenant_states = {}
            self._default_state = None
            self._capacity = cfg.max_queue
            self._tenant_lock = None
        self._queue: "queue.Queue" = queue.Queue(
            maxsize=self._capacity + (32 if self._tenancy else 0)
        )
        # tenant -> runtime overriding self.runtime for that tenant's
        # rows (tenant-scoped hot swap, serving/swap.py).  Copy-on-write
        # dict: dispatch reads ONE reference per batch, commit replaces
        # the whole dict — GIL-atomic like the self.runtime commit.
        self._tenant_routes: dict = {}
        self._thread: Optional[threading.Thread] = None
        self._lock = sanitizers.tracked(
            threading.Lock(), "serving.batcher"
        )
        # Admission-control state: current tier + the cached p99 read
        # (refreshed at most every admission_interval_s).
        self._tier = TIER_ACCEPT
        self._p99_ms: Optional[float] = None
        self._p99_refresh_t = 0.0
        # Adaptive-wait state: EWMA over submit inter-arrival times.
        # Written racy-benign from submit threads (GIL-atomic attribute
        # stores; worst case one lost sample) and read by the dispatch
        # loop.  The SLO cap is static: the tightest p99 SLO configured
        # anywhere (global + per-tenant), scaled by slo_wait_fraction.
        self._last_arrival_t: Optional[float] = None
        self._arrival_ewma_s: Optional[float] = None
        slos = [
            s for s in [cfg.p99_slo_ms]
            + ([t.p99_slo_ms for t in cfg.tenancy.tenants]
               + [cfg.tenancy.default.p99_slo_ms]
               if cfg.tenancy is not None else [])
            if s is not None
        ]
        self._adaptive_cap_s: Optional[float] = (
            min(slos) * 1e-3 * cfg.slo_wait_fraction if slos else None
        )
        # Internal counters exist ONLY for the telemetry-disabled path:
        # with a hub enabled, the registry is the single source of truth
        # and stats() derives every count from it (mirror drift is
        # structurally impossible because the mirror is never written).
        self._counts = {
            "submitted": 0,
            "completed": 0,
            "rejected": 0,
            "shed": 0,
            "shed_low_priority": 0,
            "shed_deadline": 0,
            "shed_quota": 0,
            "shed_breaker": 0,
            "tier_transitions": 0,
            "expired": 0,
            "failed": 0,
            "failed_transient": 0,
            "failed_permanent": 0,
            "batches": 0,
            "max_batch_rows": 0,
        }

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "MicroBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="scoring-dispatch", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        if self._thread is None:
            return
        self._queue.put(_STOP)
        self._thread.join(timeout=timeout)
        self._thread = None
        # Fail anything that raced past admission after the _STOP went
        # in — nothing will ever dispatch it.  Transient vocabulary, not
        # RejectedError: a supervisor treats this as the BATCHER's fault
        # and resubmits the row to a peer replica.
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is _STOP:
                continue
            if item.tenant_state is not None:
                with self._tenant_lock:
                    item.tenant_state.depth -= 1
            if item.future.set_running_or_notify_cancel():
                item.future.set_exception(RuntimeError(
                    "UNAVAILABLE: batcher stopped before dispatch; "
                    "retry with backoff"
                ))

    # -- admission control (any thread) ------------------------------------
    def _observed_p99_ms(self, now: float) -> Optional[float]:
        """Cached read of the live request-latency p99, in ms; None when
        the SLO signal is off, the hub is disabled, or no observations
        exist yet."""
        if self.config.p99_slo_ms is None:
            return None
        if now >= self._p99_refresh_t:
            self._p99_refresh_t = now + self.config.admission_interval_s
            hist = telemetry_mod.current().histogram(
                "serving_request_latency_seconds"
            )
            quantile = getattr(hist, "quantile", None)
            p99_s = None if quantile is None else quantile(0.99)
            self._p99_ms = None if p99_s is None else p99_s * 1e3
        return self._p99_ms

    def admission_tier(self, now: Optional[float] = None) -> int:
        """The current admission tier (module docstring): max of the
        depth-watermark tier and the p99-SLO tier."""
        if now is None:
            now = time.perf_counter()
        frac = self._queue.qsize() / self._capacity
        if frac >= self.config.reject_watermark:
            tier = TIER_REJECT
        elif frac >= self.config.shed_watermark:
            tier = TIER_SHED
        else:
            tier = TIER_ACCEPT
        p99 = self._observed_p99_ms(now)
        if (
            tier < TIER_SHED
            and p99 is not None
            and p99 > self.config.p99_slo_ms
        ):
            tier = TIER_SHED
        return tier

    def _note_tier(self, tier: int) -> None:
        """Journal a tier transition: gauge + counter + telemetry event
        carrying the evidence (depth, p99) that drove it."""
        with self._lock:
            prev = self._tier
            if tier == prev:
                return
            self._tier = tier
        self._count("tier_transitions")
        tel = telemetry_mod.current()
        tel.counter("serving_tier_transitions_total").inc()
        tel.gauge("serving_shed_tier").set(tier)
        tel.event(
            "serving.shed_tier",
            tier=TIER_NAMES[tier],
            previous=TIER_NAMES[prev],
            queue_depth=self._queue.qsize(),
            max_queue=self._capacity,
            p99_ms=self._p99_ms,
        )

    def _shed(self, reason: str, detail: str) -> RejectedError:
        self._count("shed")
        tel = telemetry_mod.current()
        tel.counter("serving_shed_total").inc()
        if reason in ("reject_tier", "tenant_reject"):
            # The reject tier refuses ALL non-probe traffic — that is
            # the same verdict the pre-tier queue-full backstop gave, so
            # it keeps feeding the legacy rejection counters.
            self._count("rejected")
            tel.counter("serving_rejected_total").inc()
        if reason == "low_priority":
            self._count("shed_low_priority")
            tel.counter("serving_shed_low_priority_total").inc()
        elif reason == "deadline":
            self._count("shed_deadline")
            tel.counter("serving_shed_deadline_total").inc()
        elif reason == "tenant_quota":
            self._count("shed_quota")
            tel.counter("serving_shed_quota_total").inc()
        elif reason == "tenant_breaker":
            self._count("shed_breaker")
            tel.counter("serving_shed_breaker_total").inc()
        exc = RejectedError(
            f"UNAVAILABLE: load shed ({detail}); retry with backoff"
        )
        self._classify(exc)
        return exc

    # -- tenancy (any thread) ----------------------------------------------
    def _tenant_state_for(self, row) -> Optional[_TenantState]:
        """The partition governing this row: the named tenant's state
        when registered, else the shared default-spec state."""
        if self._tenancy is None:
            return None
        tenant = getattr(row, "tenant", None)
        state = self._tenant_states.get(tenant) if tenant is not None else None
        return state or self._default_state

    def _tenant_counter(self, state: _TenantState, name: str):
        # Dynamic per-tenant metric family; slugs keep every name
        # convention-shaped (<subsystem>_<name>_<unit>).
        return telemetry_mod.current().counter(
            f"serving_tenant_{state.slug}_{name}"
        )

    def _tenant_p99_ms(self, state: _TenantState, now: float):
        """Cached per-tenant p99 read, in ms — the tenant's own latency
        family against the tenant's own SLO.  Racy-but-benign cache
        (see _TenantState); call OUTSIDE the tenancy lock."""
        if state.spec.p99_slo_ms is None:
            return None
        if now >= state.p99_refresh_t:
            state.p99_refresh_t = now + self.config.admission_interval_s
            hist = telemetry_mod.current().histogram(
                f"serving_tenant_{state.slug}_request_latency_seconds"
            )
            quantile = getattr(hist, "quantile", None)
            p99_s = None if quantile is None else quantile(0.99)
            state.p99_ms = None if p99_s is None else p99_s * 1e3
        return state.p99_ms

    def _tenant_admit(
        self,
        state: _TenantState,
        row,
        timeout: Optional[float],
        now: float,
    ) -> None:
        """Per-tenant admission: breaker, quota bucket, then the
        tenant's own tier ladder.  Raises RejectedError on denial —
        always naming the tenant, so a shed client knows it was ITS
        budget (not a neighbor's) that ran out."""
        p99 = self._tenant_p99_ms(state, now)
        with self._tenant_lock:
            if not state.breaker.allow_request():
                verdict = "breaker"
            elif not state.bucket.try_acquire():
                verdict = "quota"
            else:
                frac = state.depth / state.spec.max_queue
                if frac >= state.spec.reject_watermark:
                    tier = TIER_REJECT
                elif frac >= state.spec.shed_watermark:
                    tier = TIER_SHED
                else:
                    tier = TIER_ACCEPT
                if (
                    tier < TIER_SHED
                    and p99 is not None
                    and p99 > state.spec.p99_slo_ms
                ):
                    tier = TIER_SHED
                state.tier = tier
                verdict = tier
        name = state.spec.name
        if verdict == "breaker":
            self._tenant_counter(state, "shed_total").inc()
            raise self._shed(
                "tenant_breaker",
                f"tenant {name!r} circuit open after repeated scoring "
                "failures; cooling down",
            )
        if verdict == "quota":
            self._tenant_counter(state, "shed_total").inc()
            raise self._shed(
                "tenant_quota",
                f"tenant {name!r} over quota "
                f"({state.spec.quota_rps:g} rps sustained)",
            )
        if verdict >= TIER_REJECT:
            self._tenant_counter(state, "shed_total").inc()
            self._tenant_counter(state, "rejected_total").inc()
            raise self._shed(
                "tenant_reject",
                f"tenant {name!r} partition at reject tier "
                f"({state.depth}/{state.spec.max_queue} queued)",
            )
        if verdict == TIER_SHED:
            if getattr(row, "priority", "normal") == "low":
                self._tenant_counter(state, "shed_total").inc()
                raise self._shed(
                    "low_priority",
                    f"tenant {name!r} low-priority request at its shed "
                    "tier",
                )
            if (
                timeout is not None
                and state.p99_ms is not None
                and timeout < state.p99_ms
            ):
                self._tenant_counter(state, "shed_total").inc()
                raise self._shed(
                    "deadline",
                    f"tenant {name!r} deadline budget {timeout:.0f} ms "
                    f"is under its observed p99 {state.p99_ms:.0f} ms; "
                    "it would expire in the queue",
                )

    # -- submission (any thread) -------------------------------------------
    def submit(
        self,
        row,
        timeout_ms: Optional[float] = None,
        bypass_admission: bool = False,
    ) -> Future:
        """Enqueue one request; returns its future.

        Raises :class:`RejectedError` immediately when the tiered
        admission controller sheds the row or the queue is full —
        admission control is synchronous so the caller can shed load
        (HTTP 429) without waiting on a future.  ``bypass_admission``
        skips the tier check (NOT the queue-full backstop): supervisor
        health probes must keep flowing under overload, or shedding
        would read as replica death and trigger a restart storm.
        """
        tel = telemetry_mod.current()
        timeout = (
            timeout_ms
            if timeout_ms is not None
            else getattr(row, "timeout_ms", None)
        )
        if timeout is None:
            timeout = self.config.default_timeout_ms
        now = time.perf_counter()
        if self.config.adaptive_wait:
            last = self._last_arrival_t
            self._last_arrival_t = now
            if last is not None and now > last:
                dt = now - last
                ewma = self._arrival_ewma_s
                alpha = self.config.wait_ewma_alpha
                self._arrival_ewma_s = (
                    dt if ewma is None else alpha * dt + (1 - alpha) * ewma
                )
        state = self._tenant_state_for(row)
        if state is not None:
            self._tenant_counter(state, "requests_total").inc()
            if not bypass_admission:
                # Tenant-scoped admission FIRST: a tenant is judged
                # against its own breaker/quota/partition before the
                # shared controller sees the row, so its denial can
                # never be caused by — or blamed on — a neighbor.
                self._tenant_admit(state, row, timeout, now)
        tier = self.admission_tier(now)
        self._note_tier(tier)
        if tier > TIER_ACCEPT and not bypass_admission:
            if tier >= TIER_REJECT:
                raise self._shed(
                    "reject_tier",
                    f"admission tier {TIER_NAMES[tier]}, queue "
                    f"{self._queue.qsize()}/{self.config.max_queue}",
                )
            priority = getattr(row, "priority", "normal")
            if priority == "low":
                raise self._shed(
                    "low_priority",
                    "low-priority request at admission tier shed",
                )
            if (
                timeout is not None
                and self._p99_ms is not None
                and timeout < self._p99_ms
            ):
                raise self._shed(
                    "deadline",
                    f"deadline budget {timeout:.0f} ms is under the "
                    f"observed p99 {self._p99_ms:.0f} ms; it would "
                    "expire in the queue",
                )
        pending = _Pending(
            row=row,
            future=Future(),
            t_submit=now,
            deadline=None if timeout is None else now + timeout / 1e3,
            ctx=tel.current_context(),
            tenant_state=state,
        )
        if state is not None:
            # Reserve a slot in the tenant's bulkhead partition.  Probes
            # (bypass) still occupy depth so accounting stays exact, but
            # they are never turned away by a full partition.
            with self._tenant_lock:
                full = (
                    not bypass_admission
                    and state.depth >= state.spec.max_queue
                )
                if not full:
                    state.depth += 1
                    depth = state.depth
            if full:
                self._count("rejected")
                tel.counter("serving_rejected_total").inc()
                self._tenant_counter(state, "rejected_total").inc()
                exc = RejectedError(
                    f"UNAVAILABLE: tenant {state.spec.name!r} partition "
                    f"full ({state.spec.max_queue} pending); retry with "
                    "backoff"
                )
                self._classify(exc)
                raise exc
            tel.gauge(
                f"serving_tenant_{state.slug}_queue_depth"
            ).set(depth)
        pending.t_enqueue = time.perf_counter()
        try:
            self._queue.put_nowait(pending)
        except queue.Full:
            if state is not None:
                with self._tenant_lock:
                    state.depth -= 1
            self._count("rejected")
            tel.counter("serving_rejected_total").inc()
            if state is not None:
                self._tenant_counter(state, "rejected_total").inc()
            exc = RejectedError(
                f"UNAVAILABLE: serving queue full "
                f"({self._capacity} pending); retry with backoff"
            )
            self._classify(exc)
            raise exc
        self._count("submitted")
        tel.counter("serving_requests_total").inc()
        tel.gauge("serving_queue_depth").set(self._queue.qsize())
        tel.histogram("serving_stage_admission_seconds").observe(
            max(0.0, pending.t_enqueue - now)
        )
        return pending.future

    # -- dispatch loop (one thread) ----------------------------------------
    def _wait_budget_s(self) -> float:
        """How long this dispatch waits for batch-mates.

        Static mode: ``max_wait_us``, unconditionally.  Adaptive mode
        sizes the wait from the arrival-rate EWMA: the expected time to
        fill the rest of a batch (``ewma × (max_batch_size − 1)``) when
        that is under the ``max_wait_us`` ceiling, else ``min_wait_us``
        — dense traffic waits exactly as long as filling takes, sparse
        traffic stops paying the ceiling for batch-mates that are not
        coming.  Clamped into [min_wait_us, slo_fraction × tightest p99
        SLO] so queueing can never eat a tenant's latency budget.
        """
        cfg = self.config
        if not cfg.adaptive_wait:
            return cfg.max_wait_us / 1e6
        ceiling = cfg.max_wait_us / 1e6
        floor = cfg.min_wait_us / 1e6
        ewma = self._arrival_ewma_s
        if ewma is None:
            wait = ceiling
        else:
            fill = ewma * max(1, cfg.max_batch_size - 1)
            wait = fill if fill <= ceiling else floor
        if self._adaptive_cap_s is not None:
            wait = min(wait, self._adaptive_cap_s)
        wait = max(wait, floor)
        telemetry_mod.current().gauge(
            "serving_adaptive_wait_seconds"
        ).set(wait)
        return wait

    def _loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is _STOP:
                return
            item.t_pickup = time.perf_counter()
            batch = [item]
            stop_after = False
            wait_s = self._wait_budget_s()
            t_close = item.t_pickup + wait_s
            while len(batch) < self.config.max_batch_size:
                remaining = t_close - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop_after = True
                    break
                nxt.t_pickup = time.perf_counter()
                batch.append(nxt)
            self._dispatch(batch)
            if stop_after:
                return

    def _dispatch(self, batch: list) -> None:
        tel = telemetry_mod.current()
        # One read per dispatch: the whole batch scores against a single
        # runtime — and ONE copy-on-write tenant route table — even if a
        # hot-swap commits mid-dispatch (swap.py).
        runtime = self.runtime
        routes = self._tenant_routes
        tel.gauge("serving_queue_depth").set(self._queue.qsize())
        if self._tenancy is not None:
            # Every batch row has left the queue: release its bulkhead
            # partition slot now (expired rows included); publish the
            # new depths outside the lock.
            depths = {}
            with self._tenant_lock:
                for p in batch:
                    st = p.tenant_state
                    if st is not None:
                        st.depth -= 1
                        depths[st.slug] = st.depth
            for slug, depth in depths.items():
                tel.gauge(f"serving_tenant_{slug}_queue_depth").set(depth)
        now = time.perf_counter()
        live = []
        for p in batch:
            if p.deadline is not None and now > p.deadline:
                waited_ms = (now - p.t_submit) * 1e3
                self._count("expired")
                tel.counter("serving_deadline_expired_total").inc()
                self._fail(p, DeadlineExceededError(
                    f"DEADLINE_EXCEEDED: request waited {waited_ms:.1f} ms "
                    "past its deadline before dispatch"
                ))
            else:
                live.append(p)
        if not live:
            return
        # Group rows by tenant route: a tenant with a committed
        # tenant-scoped runtime scores against it; everyone else shares
        # the default runtime in one group.  With no routes this is
        # exactly the old single-group dispatch.
        if routes:
            keyed: dict = {}
            order = []
            for p in live:
                tenant = getattr(p.row, "tenant", None)
                rt = routes.get(tenant) if tenant is not None else None
                key = tenant if rt is not None else None
                if key not in keyed:
                    keyed[key] = (rt or runtime, [])
                    order.append(key)
                keyed[key][1].append(p)
            groups = [(k, keyed[k][0], keyed[k][1]) for k in order]
        else:
            groups = [(None, runtime, live)]
        # Cross-thread trace propagation: the batch executes on the
        # dispatch thread, but its span parents to the FIRST live
        # request's submitting span (batch-mates ride along as the rows
        # count) — a request's end-to-end latency reads as one nested
        # tree in Perfetto instead of orphaned root spans.
        ctx = next((p.ctx for p in live if p.ctx is not None), None)
        outcomes = []
        try:
            with tel.attach(ctx), tel.span(
                "serving.batch", rows=len(live)
            ):
                chaos_mod.maybe_fail("serving.batch", rows=len(live))
                for tenant, rt, rows in groups:
                    t_score = time.perf_counter()
                    try:
                        if tenant is not None:
                            # The tenant-routed scoring path is its own
                            # chaos seam: a fault here degrades exactly
                            # one tenant (docs/robustness.md).
                            chaos_mod.maybe_fail(
                                "serving.tenant",
                                tenant=tenant,
                                rows=len(rows),
                            )
                        margins, means = rt.score_rows(
                            [p.row for p in rows]
                        )
                    except Exception as exc:  # noqa: BLE001 — per-group
                        outcomes.append(
                            (tenant, rt, rows, None, None, exc,
                             t_score, 0.0)
                        )
                    else:
                        outcomes.append(
                            (tenant, rt, rows, margins, means, None,
                             t_score, time.perf_counter() - t_score)
                        )
        except Exception as exc:  # noqa: BLE001 — classified + surfaced
            # A batch-level fault (serving.batch chaos, trace plumbing)
            # fails every live row, exactly like the pre-tenancy single
            # group did.
            outcomes = [
                (tenant, rt, rows, None, None, exc, now, 0.0)
                for tenant, rt, rows in groups
            ]
        done = time.perf_counter()
        failed_states: dict = {}
        ok_states: dict = {}
        for tenant, rt, rows, margins, means, exc, t_score, device_s \
                in outcomes:
            if exc is not None:
                for p in rows:
                    self._fail(p, exc)
                    st = p.tenant_state
                    if st is not None:
                        failed_states[id(st)] = st
                        self._tenant_counter(
                            st, "failed_requests_total"
                        ).inc()
                continue
            bucket = rt.bucket_for(len(rows))
            if not tel.enabled:
                with self._lock:
                    self._counts["batches"] += 1
                    self._counts["completed"] += len(rows)
                    self._counts["max_batch_rows"] = max(
                        self._counts["max_batch_rows"], len(rows)
                    )
            tel.histogram("serving_batch_rows").observe(len(rows))
            tel.gauge("serving_batch_occupancy").set(len(rows) / bucket)
            for i, p in enumerate(rows):
                latency = done - p.t_submit
                tel.histogram(
                    "serving_request_latency_seconds"
                ).observe(latency)
                # Per-request latency decomposition: where inside
                # ``latency_ms`` the time went (docs/telemetry.md
                # "stage decomposition").  admission = submit-side
                # admission control, queue = waiting to be picked up,
                # batch = waiting for batch-mates + grouping, device =
                # this row's group's scoring wall.
                stages = {
                    "admission_s": max(0.0, p.t_enqueue - p.t_submit),
                    "queue_s": max(0.0, p.t_pickup - p.t_enqueue),
                    "batch_s": max(0.0, t_score - p.t_pickup),
                    "device_s": device_s,
                }
                tel.histogram(
                    "serving_stage_queue_seconds"
                ).observe(stages["queue_s"])
                tel.histogram(
                    "serving_stage_batch_seconds"
                ).observe(stages["batch_s"])
                tel.histogram(
                    "serving_stage_device_seconds"
                ).observe(stages["device_s"])
                st = p.tenant_state
                if st is not None:
                    ok_states.setdefault(id(st), st)
                    tel.histogram(
                        f"serving_tenant_{st.slug}"
                        "_request_latency_seconds"
                    ).observe(latency)
                if not p.future.set_running_or_notify_cancel():
                    continue  # client cancelled while queued
                result = {
                    "score": float(margins[i]),
                    "mean": float(means[i]),
                    "latency_ms": latency * 1e3,
                }
                if getattr(p.row, "want_stages", False):
                    # Opt-in response annotation; an extra result key
                    # deliberately leaves the IPC result fast path
                    # (protocol.py keys check) and rides pickle/JSON.
                    result["stages"] = stages
                p.future.set_result(result)
        if self._tenancy is not None and (failed_states or ok_states):
            # Feed each tenant's breaker with this dispatch's outcomes.
            # A state that both failed and succeeded in one dispatch
            # counts the failure (the breaker errs toward opening).
            with self._tenant_lock:
                for key, st in ok_states.items():
                    if key not in failed_states:
                        st.breaker.record_success()
                for st in failed_states.values():
                    st.breaker.record_failure()

    # -- failure plumbing --------------------------------------------------
    def _classify(self, exc: BaseException):
        """Watchdog-vocabulary classification of a request failure; feeds
        the transient/permanent split in stats and telemetry."""
        verdict = self.policy.classify(exc)
        self._count(
            "failed_transient" if verdict.transient else "failed_permanent"
        )
        telemetry_mod.current().counter(
            "serving_failures_transient_total" if verdict.transient
            else "serving_failures_permanent_total"
        ).inc()
        return verdict

    def _fail(self, p: _Pending, exc: BaseException) -> None:
        self._count("failed")
        telemetry_mod.current().counter(
            "serving_failed_requests_total"
        ).inc()
        self._classify(exc)
        if p.future.set_running_or_notify_cancel():
            p.future.set_exception(exc)

    def _count(self, key: str, n: int = 1) -> None:
        # Disabled-hub mirror only — see __init__; with a hub installed
        # the registry carries the count and this is a no-op.
        if telemetry_mod.current().enabled:
            return
        with self._lock:
            self._counts[key] += n

    # -- tenant routes (swap commit path, serving/swap.py) ------------------
    def set_tenant_route(self, tenant: str, runtime) -> None:
        """Commit a tenant-scoped runtime: rows carrying ``tenant``
        score against it instead of ``self.runtime``.  Copy-on-write so
        the dispatch thread's single route-table read stays lock-free —
        the same GIL-atomic commit discipline as ``self.runtime``."""
        routes = dict(self._tenant_routes)
        routes[tenant] = runtime
        self._tenant_routes = routes

    def clear_tenant_route(self, tenant: str) -> None:
        """Drop a tenant back onto the default route."""
        routes = dict(self._tenant_routes)
        routes.pop(tenant, None)
        self._tenant_routes = routes

    def tenant_route(self, tenant: str):
        """The tenant's committed runtime, or None (default route)."""
        return self._tenant_routes.get(tenant)

    # -- tenant quotas (fleet lease apply path, serving/fleet.py) -----------
    def set_tenant_quota(
        self,
        tenant: str,
        rate_rps: Optional[float],
        burst: Optional[float] = None,
    ) -> None:
        """Re-rate one tenant's token bucket in place (a fleet quota
        lease landing on this batcher).  The spec stays immutable — the
        lease overrides only the live bucket, so a rebuilt batcher
        starts back at the static spec until the next lease applies."""
        if self._tenancy is None:
            raise ValueError(
                "tenancy is not enabled on this batcher; no quota to set"
            )
        state = self._tenant_states.get(tenant)
        if state is None and tenant == self._tenancy.default.name:
            state = self._default_state
        if state is None:
            raise ValueError(
                f"unknown tenant {tenant!r}; declare it in TenancyConfig "
                "before leasing it quota"
            )
        with self._tenant_lock:
            state.bucket.reset_rate(rate_rps, burst)

    # -- observability -----------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return self._queue.qsize()

    #: stats key → how to derive it from the telemetry snapshot.  The
    #: batch aggregates come from the serving_batch_rows histogram: one
    #: observation per dispatched batch, value = live rows, so count =
    #: batches, sum = completed rows, max = max_batch_rows.
    _HUB_COUNTERS = {
        "submitted": "serving_requests_total",
        "rejected": "serving_rejected_total",
        "shed": "serving_shed_total",
        "shed_low_priority": "serving_shed_low_priority_total",
        "shed_deadline": "serving_shed_deadline_total",
        "shed_quota": "serving_shed_quota_total",
        "shed_breaker": "serving_shed_breaker_total",
        "tier_transitions": "serving_tier_transitions_total",
        "expired": "serving_deadline_expired_total",
        "failed": "serving_failed_requests_total",
        "failed_transient": "serving_failures_transient_total",
        "failed_permanent": "serving_failures_permanent_total",
    }

    def stats(self) -> dict:
        tel = telemetry_mod.current()
        if tel.enabled:
            # Single source of truth: derive every count from the hub's
            # registry (the same numbers /metrics exposes).  Note the
            # registry is process-wide — two batchers under one hub sum.
            snap = tel.metrics.snapshot()
            counters = snap["counters"]
            hist = snap["histograms"].get("serving_batch_rows") or {}
            counts = {
                key: counters.get(name, 0)
                for key, name in self._HUB_COUNTERS.items()
            }
            counts["batches"] = hist.get("count", 0)
            counts["completed"] = int(hist.get("sum") or 0)
            counts["max_batch_rows"] = int(hist.get("max") or 0)
            counts["source"] = "telemetry"
        else:
            with self._lock:
                counts = dict(self._counts)
            counts["source"] = "internal"
        counts["queue_depth"] = self._queue.qsize()
        counts["max_queue"] = self._capacity
        counts["max_batch_size"] = self.config.max_batch_size
        counts["max_wait_us"] = self.config.max_wait_us
        counts["adaptive_wait"] = self.config.adaptive_wait
        if self.config.adaptive_wait:
            ewma = self._arrival_ewma_s
            counts["arrival_ewma_ms"] = (
                None if ewma is None else ewma * 1e3
            )
        with self._lock:
            counts["tier"] = TIER_NAMES[self._tier]
        counts["model_version"] = getattr(self.runtime, "model_version", 1)
        if self._tenancy is not None:
            routes = self._tenant_routes
            tenants = {}
            with self._tenant_lock:
                states = [self._default_state]
                states.extend(self._tenant_states.values())
                for st in states:
                    tenants[st.spec.name] = {
                        "slug": st.slug,
                        "depth": st.depth,
                        "max_queue": st.spec.max_queue,
                        "tier": TIER_NAMES[st.tier],
                        "quota": st.bucket.snapshot(),
                        "breaker": st.breaker.snapshot(),
                        "p99_slo_ms": st.spec.p99_slo_ms,
                    }
            for tenant, entry in tenants.items():
                rt = routes.get(tenant)
                entry["routed_version"] = (
                    None if rt is None
                    else getattr(rt, "model_version", None)
                )
            counts["tenants"] = tenants
        return counts
