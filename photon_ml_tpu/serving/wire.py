"""Binary wire format for the serving data plane.

JSON-over-HTTP is the compatibility path; this module is the fast one.
A frame is a fixed-layout, versioned container of dtype-tagged COLUMNS
(the ``ChunkCodec`` slot idiom from data/staging.py, applied to request
traffic): a 24-byte header, a 20-byte directory entry per column, a
names blob, then an 8-aligned payload holding each column as one
contiguous typed segment.  Decoding is zero-copy — every column comes
back as a ``np.frombuffer`` view straight over the received bytes, so a
thousand-row request costs a handful of pointer fixups, not a
thousand ``json.loads`` allocations.

Layout (all little-endian)::

    header   <4s magic "PHWF"> <u16 version> <u8 kind> <u8 flags>
             <u16 n_cols> <u16 reserved> <u32 n_rows>
             <u32 names_len> <u32 payload_len>
    dir[i]   <u32 name_off> <u16 name_len> <u8 dtype_tag> <u8 ndim>
             <u32 n0> <u32 n1> <u32 payload_off>
    names    UTF-8 blob, padded to 8 bytes
    payload  column segments, each 8-aligned

Three semantic layers ride the same container:

- **Request frames** (:func:`encode_request` / :func:`decode_request`):
  dense feature shards as ``(n, dim)`` float32 matrices with per-row
  presence masks, entity ids / tenants as offset+blob string columns,
  ``offset`` / ``timeout_ms`` as float64 so the binary path round-trips
  the exact doubles the JSON path carries (bitwise score parity is a
  contract, not an aspiration).  Named sparse features are JSON-only —
  the binary path refuses them at encode time.
- **Response frames** (:func:`encode_response` /
  :func:`decode_response`): float64 score/mean/latency columns plus a
  status byte and an error-string column, mirroring the JSON
  ``{"results": [...]}`` shape row for row.
- **Trusted row frames** (:func:`rows_to_request`): pre-parsed
  :class:`~photon_ml_tpu.serving.runtime.Row` objects encoded for
  process-pool IPC (serving/protocol.py), replacing pickle on the
  score path.

Every decode refuses loudly (:class:`WireFormatError`) on a bad magic,
an unknown version, a truncated frame, a forged length, or an unknown
dtype tag — before trusting a single directory entry, mirroring the
256 MB frame cap discipline of serving/protocol.py.
"""

from __future__ import annotations

import struct
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.data.staging import wire_dtype_from_tag, wire_dtype_tag
from photon_ml_tpu.serving.runtime import PRIORITIES, Row

#: HTTP content type negotiating the binary path on POST /score.
CONTENT_TYPE = "application/x-photon-frame"

#: Hard frame cap, mirroring serving/protocol.py — refuse before
#: believing a forged length.
MAX_WIRE_BYTES = 256 << 20

#: v2 adds OPTIONAL trace-context string columns (``trace:ctx`` on
#: request frames, ``meta:trace`` on worker-IPC frames) — distributed
#: tracing, PR 17.  Decoders accept every version in
#: :data:`COMPAT_VERSIONS`: a v1 frame simply has no trace column, so
#: old senders keep working against new receivers unchanged.
WIRE_VERSION = 2
COMPAT_VERSIONS = frozenset({1, 2})

#: frame kinds (header byte)
KIND_REQUEST = 1
KIND_RESPONSE = 2
#: worker-IPC frames (serving/protocol.py): one score submission with
#: routing metadata, and one successful score result.
KIND_SCORE_IPC = 3
KIND_RESULT_IPC = 4

_HEADER = struct.Struct("<4sHBBHHIII")
_DIR = struct.Struct("<IHBBIII")
_MAGIC = b"PHWF"
_ALIGN = 8

#: response status byte → JSON error kind (0 = success).
RESPONSE_STATUS = ("ok", "rejected", "deadline", "bad_request", "internal")
_STATUS_BY_KIND = {k: i for i, k in enumerate(RESPONSE_STATUS)}


class WireFormatError(ValueError):
    """A frame that must not be trusted: bad magic, unknown version,
    truncated or forged lengths, unknown dtype tag, or a semantic
    column that fails validation."""


def _pad(n: int) -> int:
    return (-n) % _ALIGN


# ---------------------------------------------------------------------------
# Container layer
# ---------------------------------------------------------------------------

def encode_columns(
    columns: dict, kind: int, n_rows: int
) -> bytes:
    """Pack named 1-D/2-D contiguous arrays into one frame.  Column
    order is preserved (decoders see insertion order)."""
    names_blob = bytearray()
    payload = bytearray()
    entries = []
    for name, arr in columns.items():
        arr = np.ascontiguousarray(arr)
        if arr.ndim not in (1, 2):
            raise ValueError(
                f"column {name!r} must be 1-D or 2-D, got {arr.ndim}-D"
            )
        tag = wire_dtype_tag(arr.dtype)
        nb = name.encode("utf-8")
        name_off = len(names_blob)
        names_blob += nb
        payload += b"\0" * _pad(len(payload))
        payload_off = len(payload)
        payload += arr.tobytes()
        n0 = arr.shape[0]
        n1 = arr.shape[1] if arr.ndim == 2 else 0
        entries.append(
            _DIR.pack(name_off, len(nb), tag, arr.ndim, n0, n1, payload_off)
        )
    names_padded = bytes(names_blob) + b"\0" * _pad(len(names_blob))
    header = _HEADER.pack(
        _MAGIC, WIRE_VERSION, kind, 0, len(entries), 0,
        n_rows, len(names_padded), len(payload),
    )
    return b"".join([header, *entries, names_padded, bytes(payload)])


def decode_columns(buf) -> tuple:
    """Decode a frame into ``(kind, n_rows, {name: array view})``.

    Views are zero-copy over ``buf`` (read-only when ``buf`` is
    ``bytes``).  Raises :class:`WireFormatError` before trusting any
    length field that disagrees with the actual byte count.
    """
    buf = memoryview(buf)
    if len(buf) < _HEADER.size:
        raise WireFormatError(
            f"truncated frame: {len(buf)} bytes < {_HEADER.size}-byte header"
        )
    (magic, version, kind, _flags, n_cols, _res, n_rows,
     names_len, payload_len) = _HEADER.unpack_from(buf, 0)
    if magic != _MAGIC:
        raise WireFormatError(
            f"bad magic {bytes(magic)!r}: not a wire frame"
        )
    if version not in COMPAT_VERSIONS:
        raise WireFormatError(
            f"unknown wire version {version} (this build speaks "
            f"{sorted(COMPAT_VERSIONS)})"
        )
    if names_len > MAX_WIRE_BYTES or payload_len > MAX_WIRE_BYTES:
        raise WireFormatError(
            f"forged frame lengths: names={names_len} "
            f"payload={payload_len} exceed the {MAX_WIRE_BYTES}-byte cap"
        )
    names_off = _HEADER.size + n_cols * _DIR.size
    payload_off = names_off + names_len
    total = payload_off + payload_len
    if len(buf) != total:
        raise WireFormatError(
            f"frame length mismatch: header promises {total} bytes, "
            f"got {len(buf)}"
        )
    names = buf[names_off:payload_off]
    payload = buf[payload_off:total]
    columns: dict = {}
    for i in range(n_cols):
        (name_off, name_len, tag, ndim, n0, n1, col_off) = _DIR.unpack_from(
            buf, _HEADER.size + i * _DIR.size
        )
        if name_off + name_len > names_len:
            raise WireFormatError(
                f"column {i} name range [{name_off}, {name_off + name_len}) "
                f"outside the {names_len}-byte names blob"
            )
        name = bytes(names[name_off:name_off + name_len]).decode("utf-8")
        try:
            dt = wire_dtype_from_tag(tag)
        except KeyError as exc:
            raise WireFormatError(
                f"column {name!r}: {exc.args[0]}"
            ) from None
        if ndim not in (1, 2):
            raise WireFormatError(
                f"column {name!r} claims {ndim} dims; frames carry 1-D "
                "or 2-D columns"
            )
        count = n0 * (n1 if ndim == 2 else 1)
        nbytes = count * dt.itemsize
        if col_off + nbytes > payload_len:
            raise WireFormatError(
                f"column {name!r} payload range [{col_off}, "
                f"{col_off + nbytes}) outside the {payload_len}-byte payload"
            )
        arr = np.frombuffer(payload, dt, count=count, offset=col_off)
        if ndim == 2:
            arr = arr.reshape(n0, n1)
        columns[name] = arr
    return kind, n_rows, columns


# ---------------------------------------------------------------------------
# String columns (offset + blob + presence mask)
# ---------------------------------------------------------------------------

def _encode_strings(
    columns: dict, name: str, values: Sequence[Optional[str]]
) -> None:
    offs = np.zeros(len(values) + 1, np.uint32)
    mask = np.zeros(len(values), np.uint8)
    blob = bytearray()
    for i, v in enumerate(values):
        if v is not None:
            mask[i] = 1
            blob += v.encode("utf-8")
        offs[i + 1] = len(blob)
    columns[f"{name}#off"] = offs
    columns[f"{name}#blob"] = np.frombuffer(bytes(blob), np.uint8) \
        if blob else np.zeros(0, np.uint8)
    columns[f"{name}#mask"] = mask


def _decode_strings(
    columns: dict, name: str, n: int
) -> list:
    offs = columns.get(f"{name}#off")
    blob = columns.get(f"{name}#blob")
    mask = columns.get(f"{name}#mask")
    if offs is None or blob is None or mask is None:
        raise WireFormatError(f"frame is missing string column {name!r}")
    if offs.shape != (n + 1,) or mask.shape != (n,):
        raise WireFormatError(
            f"string column {name!r} shaped {offs.shape}/{mask.shape} "
            f"for {n} rows"
        )
    raw = blob.tobytes()
    if len(offs) and int(offs[-1]) > len(raw):
        raise WireFormatError(
            f"string column {name!r} offsets overrun its blob"
        )
    out: list = []
    for i in range(n):
        if not mask[i]:
            out.append(None)
            continue
        lo, hi = int(offs[i]), int(offs[i + 1])
        if hi < lo:
            raise WireFormatError(
                f"string column {name!r} has non-monotone offsets"
            )
        out.append(raw[lo:hi].decode("utf-8"))
    return out


# ---------------------------------------------------------------------------
# Trace-context columns (optional, v2)
# ---------------------------------------------------------------------------

def _encode_trace(columns: dict, name: str, trace: Optional[str]) -> None:
    """Attach the serialized trace context (``TraceContext.
    header_value()``) as a one-entry optional string column.  None
    attaches nothing — an untraced frame is byte-identical to v1 except
    for the version field."""
    if trace is not None:
        _encode_strings(columns, name, [str(trace)])


def _decode_trace(columns: dict, name: str, n: int = 1) -> Optional[str]:
    if f"{name}#off" not in columns:
        return None  # v1 frame, or an untraced v2 frame
    return _decode_strings(columns, name, n)[0]


# ---------------------------------------------------------------------------
# Request layer
# ---------------------------------------------------------------------------

def encode_request(
    requests: Sequence[dict], trace: Optional[str] = None
) -> bytes:
    """Encode JSON-shaped request dicts into one request frame.

    Supports ``dense`` shards, ``ids``, ``offset``, ``timeout_ms``,
    ``priority`` and ``tenant``.  Named sparse ``features`` entries
    need the server-side index map — send those rows as JSON; this
    encoder refuses them so the fallback is explicit, not silent.
    """
    n = len(requests)
    if n == 0:
        raise ValueError("encode_request needs at least one request")
    offsets = np.zeros(n, np.float64)
    timeouts = np.full(n, np.nan, np.float64)
    priority = np.full(n, PRIORITIES.index("normal"), np.uint8)
    shard_vecs: dict = {}
    id_cols: dict = {}
    tenants: list = [None] * n
    for i, req in enumerate(requests):
        if not isinstance(req, dict):
            raise ValueError("each request must be a JSON-shaped dict")
        if req.get("features"):
            raise ValueError(
                "named sparse 'features' need the server-side index map; "
                "send those rows over the JSON path"
            )
        offsets[i] = float(req.get("offset") or 0.0)
        t = req.get("timeout_ms")
        if t is not None:
            timeouts[i] = float(t)
        p = req.get("priority", "normal")
        if p not in PRIORITIES:
            raise ValueError(
                f"priority must be one of {PRIORITIES}, got {p!r}"
            )
        priority[i] = PRIORITIES.index(p)
        tenant = req.get("tenant")
        if tenant is not None and not isinstance(tenant, str):
            raise ValueError(
                f"tenant must be a string, got {type(tenant).__name__}"
            )
        tenants[i] = tenant
        for shard, vec in (req.get("dense") or {}).items():
            arr = np.asarray(vec, np.float32)
            if arr.ndim != 1:
                raise ValueError(
                    f"shard {shard!r} must be a flat vector, got shape "
                    f"{arr.shape}"
                )
            shard_vecs.setdefault(str(shard), {})[i] = arr
        for key, value in (req.get("ids") or {}).items():
            if value is not None:
                id_cols.setdefault(str(key), [None] * n)[i] = str(value)
    columns: dict = {
        "offset": offsets,
        "timeout_ms": timeouts,
        "priority": priority,
    }
    for shard, by_row in shard_vecs.items():
        dim = {a.shape[0] for a in by_row.values()}
        if len(dim) != 1:
            raise ValueError(
                f"shard {shard!r} has inconsistent widths {sorted(dim)} "
                "across rows"
            )
        mat = np.zeros((n, dim.pop()), np.float32)
        mask = np.zeros(n, np.uint8)
        for i, arr in by_row.items():
            mat[i] = arr
            mask[i] = 1
        columns[f"dense:{shard}"] = mat
        columns[f"mask:{shard}"] = mask
    for key, values in id_cols.items():
        _encode_strings(columns, f"ids:{key}", values)
    _encode_strings(columns, "tenant", tenants)
    _encode_trace(columns, "trace:ctx", trace)
    return encode_columns(columns, KIND_REQUEST, n)


def rows_to_request(rows: Sequence[Row]) -> bytes:
    """Encode pre-parsed :class:`Row` objects — the trusted process-pool
    path (serving/protocol.py), where the parent already validated."""
    return encode_columns(
        _row_columns(rows), KIND_REQUEST, len(rows)
    )


def _row_columns(rows: Sequence[Row]) -> dict:
    n = len(rows)
    if n == 0:
        raise ValueError("rows_to_request needs at least one row")
    offsets = np.zeros(n, np.float64)
    timeouts = np.full(n, np.nan, np.float64)
    priority = np.zeros(n, np.uint8)
    shard_vecs: dict = {}
    id_cols: dict = {}
    tenants: list = [None] * n
    for i, row in enumerate(rows):
        offsets[i] = row.offset
        if row.timeout_ms is not None:
            timeouts[i] = row.timeout_ms
        priority[i] = PRIORITIES.index(row.priority)
        tenants[i] = row.tenant
        for shard, vec in row.features.items():
            if vec is None:
                continue
            shard_vecs.setdefault(shard, {})[i] = np.asarray(vec, np.float32)
        for key, value in row.ids.items():
            id_cols.setdefault(key, [None] * n)[i] = value
    columns: dict = {
        "offset": offsets,
        "timeout_ms": timeouts,
        "priority": priority,
    }
    for shard, by_row in shard_vecs.items():
        dim = next(iter(by_row.values())).shape[0]
        mat = np.zeros((n, dim), np.float32)
        mask = np.zeros(n, np.uint8)
        for i, arr in by_row.items():
            mat[i] = arr
            mask[i] = 1
        columns[f"dense:{shard}"] = mat
        columns[f"mask:{shard}"] = mask
    for key, values in id_cols.items():
        _encode_strings(columns, f"ids:{key}", values)
    _encode_strings(columns, "tenant", tenants)
    return columns


def decode_request(buf, parser=None) -> list:
    """Decode a request frame into :class:`Row` objects.

    With ``parser`` (a :class:`~photon_ml_tpu.serving.runtime.
    RequestParser`) each dense shard is validated against the model's
    shard dims — unknown shards and wrong widths refuse exactly like
    the JSON parser.  ``parser=None`` is the trusted IPC path.  Feature
    vectors are zero-copy row views over ``buf``.
    """
    return decode_request_ex(buf, parser)[0]


def decode_request_ex(buf, parser=None) -> tuple:
    """:func:`decode_request` plus the frame's trace context:
    ``(rows, trace_str_or_None)``.  v1 frames and untraced v2 frames
    decode with ``trace=None``."""
    kind, n, columns = decode_columns(buf)
    if kind != KIND_REQUEST:
        raise WireFormatError(
            f"expected a request frame, got kind {kind}"
        )
    return (
        _rows_from_columns(n, columns, parser),
        _decode_trace(columns, "trace:ctx"),
    )


def _rows_from_columns(n: int, columns: dict, parser) -> list:
    if n == 0:
        raise WireFormatError("request frame carries zero rows")
    offsets = columns.get("offset")
    timeouts = columns.get("timeout_ms")
    priority = columns.get("priority")
    for name, col, shape in (
        ("offset", offsets, (n,)),
        ("timeout_ms", timeouts, (n,)),
        ("priority", priority, (n,)),
    ):
        if col is None:
            raise WireFormatError(f"request frame missing column {name!r}")
        if col.shape != shape:
            raise WireFormatError(
                f"column {name!r} shaped {col.shape}, expected {shape}"
            )
    shards: dict = {}
    for name, col in columns.items():
        if not name.startswith("dense:"):
            continue
        shard = name[len("dense:"):]
        if col.ndim != 2 or col.shape[0] != n:
            raise WireFormatError(
                f"shard {shard!r} shaped {col.shape} for {n} rows"
            )
        if parser is not None:
            dim = parser.shard_dims.get(shard)
            if dim is None:
                raise WireFormatError(f"unknown feature shard {shard!r}")
            if col.shape[1] != dim:
                raise WireFormatError(
                    f"shard {shard!r} expects {dim} features, got "
                    f"{col.shape[1]}"
                )
        mask = columns.get(f"mask:{shard}")
        if mask is None or mask.shape != (n,):
            raise WireFormatError(
                f"shard {shard!r} is missing its presence mask"
            )
        shards[shard] = (np.asarray(col, np.float32), mask)
    id_keys = sorted({
        name[len("ids:"):].rsplit("#", 1)[0]
        for name in columns if name.startswith("ids:")
    })
    ids_by_key = {
        key: _decode_strings(columns, f"ids:{key}", n) for key in id_keys
    }
    tenants = _decode_strings(columns, "tenant", n)
    rows: list = []
    for i in range(n):
        pr = int(priority[i])
        if pr >= len(PRIORITIES):
            raise WireFormatError(
                f"row {i} priority byte {pr} out of range"
            )
        features = {
            shard: mat[i]
            for shard, (mat, mask) in shards.items() if mask[i]
        }
        ids = {
            key: vals[i]
            for key, vals in ids_by_key.items() if vals[i] is not None
        }
        t = float(timeouts[i])
        rows.append(Row(
            features=features,
            ids=ids,
            offset=float(offsets[i]),
            timeout_ms=None if np.isnan(t) else t,
            priority=PRIORITIES[pr],
            tenant=tenants[i],
        ))
    return rows


# ---------------------------------------------------------------------------
# Response layer
# ---------------------------------------------------------------------------

def encode_response(results: Sequence[Optional[dict]]) -> bytes:
    """Encode ``score_many`` result dicts into one response frame.
    Scores ride as float64, so a JSON response and a binary response
    decode to bitwise-identical values."""
    n = len(results)
    score = np.zeros(n, np.float64)
    mean = np.zeros(n, np.float64)
    latency = np.zeros(n, np.float64)
    status = np.zeros(n, np.uint8)
    errors: list = [None] * n
    for i, r in enumerate(results):
        if r is None:
            status[i] = _STATUS_BY_KIND["internal"]
            errors[i] = "no result"
        elif "error" in r:
            status[i] = _STATUS_BY_KIND.get(
                r.get("kind", "internal"), _STATUS_BY_KIND["internal"]
            )
            errors[i] = str(r["error"])
        else:
            score[i] = r["score"]
            mean[i] = r["mean"]
            latency[i] = r["latency_ms"]
    columns: dict = {
        "score": score,
        "mean": mean,
        "latency_ms": latency,
        "status": status,
    }
    _encode_strings(columns, "error", errors)
    return encode_columns(columns, KIND_RESPONSE, n)


def decode_response(buf) -> list:
    """Decode a response frame back into the JSON ``results`` shape:
    ``{"score", "mean", "latency_ms"}`` per success row,
    ``{"error", "kind"}`` per failure row."""
    kind, n, columns = decode_columns(buf)
    if kind != KIND_RESPONSE:
        raise WireFormatError(
            f"expected a response frame, got kind {kind}"
        )
    for name in ("score", "mean", "latency_ms", "status"):
        col = columns.get(name)
        if col is None or col.shape != (n,):
            raise WireFormatError(
                f"response frame column {name!r} missing or misshaped"
            )
    errors = _decode_strings(columns, "error", n)
    status = columns["status"]
    out: list = []
    for i in range(n):
        s = int(status[i])
        if s >= len(RESPONSE_STATUS):
            raise WireFormatError(f"row {i} status byte {s} out of range")
        if s == 0:
            out.append({
                "score": float(columns["score"][i]),
                "mean": float(columns["mean"][i]),
                "latency_ms": float(columns["latency_ms"][i]),
            })
        else:
            out.append({
                "error": errors[i] or "",
                "kind": RESPONSE_STATUS[s],
            })
    return out


# ---------------------------------------------------------------------------
# Process-pool IPC layer (serving/protocol.py)
# ---------------------------------------------------------------------------

def encode_score_ipc(
    request_id: int,
    row: Row,
    tenant: Optional[str] = None,
    timeout_ms: Optional[float] = None,
    bypass: bool = False,
    trace: Optional[str] = None,
) -> bytes:
    """Encode one score submission for worker IPC: the parsed row plus
    the frame-level routing metadata that rides beside it."""
    columns = _row_columns([row])
    columns["meta:id"] = np.asarray([request_id], np.int64)
    columns["meta:timeout_ms"] = np.asarray(
        [np.nan if timeout_ms is None else float(timeout_ms)], np.float64
    )
    columns["meta:bypass"] = np.asarray([1 if bypass else 0], np.uint8)
    _encode_strings(columns, "meta:tenant", [tenant])
    _encode_trace(columns, "meta:trace", trace)
    return encode_columns(columns, KIND_SCORE_IPC, 1)


def decode_score_ipc(buf) -> dict:
    """Decode a score IPC frame back into the exact message dict shape
    serving/worker.py consumes."""
    kind, n, columns = decode_columns(buf)
    if kind != KIND_SCORE_IPC:
        raise WireFormatError(f"expected a score IPC frame, got kind {kind}")
    if n != 1:
        raise WireFormatError(f"score IPC frames carry one row, got {n}")
    rid = columns.get("meta:id")
    mt = columns.get("meta:timeout_ms")
    byp = columns.get("meta:bypass")
    for name, col in (("meta:id", rid), ("meta:timeout_ms", mt),
                      ("meta:bypass", byp)):
        if col is None or col.shape != (1,):
            raise WireFormatError(
                f"score IPC column {name!r} missing or misshaped"
            )
    row = _rows_from_columns(
        1, {k: v for k, v in columns.items() if not k.startswith("meta:")},
        None,
    )[0]
    t = float(mt[0])
    out = {
        "kind": "score",
        "id": int(rid[0]),
        "row": row,
        "tenant": _decode_strings(columns, "meta:tenant", 1)[0],
        "timeout_ms": None if np.isnan(t) else t,
        "bypass": bool(byp[0]),
    }
    trace = _decode_trace(columns, "meta:trace")
    if trace is not None:
        out["trace"] = trace
    return out


def encode_result_ipc(
    request_id: int, value: dict, trace: Optional[str] = None
) -> bytes:
    """Encode one successful score result for worker IPC.  Error
    results stay on the pickle path — they are rare and carry
    free-form strings."""
    columns: dict = {
        "meta:id": np.asarray([request_id], np.int64),
        "score": np.asarray([value["score"]], np.float64),
        "mean": np.asarray([value["mean"]], np.float64),
        "latency_ms": np.asarray([value["latency_ms"]], np.float64),
    }
    _encode_trace(columns, "meta:trace", trace)
    return encode_columns(columns, KIND_RESULT_IPC, 1)


def decode_result_ipc(buf) -> dict:
    """Decode a result IPC frame back into the worker's success
    message shape."""
    kind, n, columns = decode_columns(buf)
    if kind != KIND_RESULT_IPC:
        raise WireFormatError(f"expected a result IPC frame, got kind {kind}")
    if n != 1:
        raise WireFormatError(f"result IPC frames carry one row, got {n}")
    for name in ("meta:id", "score", "mean", "latency_ms"):
        col = columns.get(name)
        if col is None or col.shape != (1,):
            raise WireFormatError(
                f"result IPC column {name!r} missing or misshaped"
            )
    out = {
        "kind": "result",
        "id": int(columns["meta:id"][0]),
        "ok": True,
        "value": {
            "score": float(columns["score"][0]),
            "mean": float(columns["mean"][0]),
            "latency_ms": float(columns["latency_ms"][0]),
        },
    }
    trace = _decode_trace(columns, "meta:trace")
    if trace is not None:
        out["trace"] = trace
    return out
