"""ScoringService: the in-process API and the stdlib HTTP endpoint.

``ScoringService`` is the one object callers touch.  It composes either
a single :class:`~photon_ml_tpu.serving.runtime.ScoringRuntime` with a
:class:`~photon_ml_tpu.serving.batcher.MicroBatcher`, or — for
high-availability serving — a :class:`~photon_ml_tpu.serving.supervisor.
ReplicaSupervisor` running N replicas behind the same listener:

    with ScoringService(runtime) as svc:          # single runtime
        fut = svc.submit({"dense": {"global": [...]}, "ids": {...}})
        result = svc.score({...})            # blocking convenience
        many = svc.score_many([{...}, ...])  # coalesces naturally

    sup = ReplicaSupervisor(factory, n_replicas=3)
    with ScoringService(sup) as svc:              # HA: same API
        ...

Either way the service carries a :class:`~photon_ml_tpu.serving.swap.
HotSwapper` — ``svc.reload(model_dir)`` rolls every live runtime onto a
new model version with verified rollback (see serving/swap.py).

``start_http_server(svc, port)`` exposes the same API over a stdlib
``ThreadingHTTPServer`` (one thread per connection; dispatch threads
still own all scoring, so concurrency is safe by construction):

- ``POST /score`` — ``{"rows": [...]}`` or a single request object;
  responds ``{"results": [...]}`` with per-row ``{"score", "mean",
  "latency_ms"}`` or ``{"error", "kind"}``.  A fully-rejected call
  returns 429, a fully-expired one 504, bad input 400.
- ``POST /reload`` — ``{"model_dir": ...}`` swaps to a new model
  (``{"rollback": true}`` is the one-step manual rollback).  200 on
  swap, 409 while another swap runs, 422 when the swap rolled back,
  503 when deferred (degraded target).
- ``GET /healthz`` — the RICH health view: status ``stopped`` /
  ``not_ready`` / ``degraded`` / ``ok``, model version, replica states.
- ``GET /livez`` — pure liveness: 200 whenever the process answers.
- ``GET /readyz`` — pure readiness: 200 only when traffic should route
  here; 503 with ``"not_ready"`` during startup warmup, mid-swap, and
  when no healthy replica exists.  Load balancers watch THIS, not
  /healthz (a warming server is alive but must not receive traffic).
- ``GET /stats`` — runtime/supervisor + batcher + swap counters.  With
  a telemetry hub enabled the batcher block is DERIVED from the hub's
  registry (the ``"source": "telemetry"`` field says so) — one source
  of truth with the /metrics exposition.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import Future
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

from photon_ml_tpu.serving.batcher import (
    BatcherConfig,
    DeadlineExceededError,
    MicroBatcher,
    RejectedError,
)
from photon_ml_tpu.serving.runtime import Row, ScoringRuntime
from photon_ml_tpu.serving.swap import HotSwapper, SwapInProgressError
from photon_ml_tpu.serving.tenancy import TenantRouter
from photon_ml_tpu.serving import wire as wire_mod
from photon_ml_tpu import telemetry as telemetry_mod


class ScoringService:
    """Runtime(+batcher) or supervisor, started/stopped as one unit."""

    def __init__(
        self,
        runtime,
        batcher_config: Optional[BatcherConfig] = None,
        policy=None,
    ):
        from photon_ml_tpu.serving.supervisor import ReplicaSupervisor

        if isinstance(runtime, ReplicaSupervisor):
            self.supervisor: Optional[ReplicaSupervisor] = runtime
            if batcher_config is not None:
                self.supervisor.batcher_config = batcher_config
            self.runtime = None
            self.batcher = None
        else:
            self.supervisor = None
            self.runtime = runtime
            self.batcher = MicroBatcher(
                runtime, batcher_config, policy=policy
            )
        self.swapper = HotSwapper(
            self._swap_targets,
            on_commit=self._on_swap_commit,
            on_kill=self._on_swap_kill,
            on_tenant_commit=self._on_tenant_swap_commit,
        )
        #: tenant → model-version resolution view (serving/tenancy.py);
        #: the swapper owns the route state, this is the read API.
        self.router = TenantRouter(self.swapper)
        #: tenant → offered-request count, PRE-admission (counted even
        #: when the quota then sheds the request): the demand signal the
        #: fleet lease client feeds the QuotaCoordinator
        #: (serving/fleet.py).  Absent tenant ids count under None.
        self._demand: dict = {}
        self._demand_lock = threading.Lock()
        self._started = False

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ScoringService":
        if self.supervisor is not None:
            self.supervisor.start()
        else:
            self.batcher.start()
        self._started = True
        self.swapper.adopt_version(self.current_runtime)
        return self

    def stop(self) -> None:
        if self.supervisor is not None:
            self.supervisor.stop()
        else:
            self.batcher.stop()
        self._started = False

    def __enter__(self) -> "ScoringService":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- hot swap ----------------------------------------------------------
    @property
    def current_runtime(self):
        """The runtime serving NOW (post-swap it differs from the one the
        service was constructed with)."""
        if self.supervisor is not None:
            return self.supervisor._any_runtime()
        return self.batcher.runtime

    def _swap_targets(self) -> list:
        if self.supervisor is not None:
            return self.supervisor.swap_targets()
        return [self.batcher]

    def _on_swap_commit(
        self, model, index_maps, config, version, path
    ) -> None:
        if self.supervisor is not None:
            self.supervisor.on_swap_commit(
                model, index_maps, config, version, path
            )
        else:
            self.runtime = self.batcher.runtime

    def _on_tenant_swap_commit(
        self, tenant, model, index_maps, config, version, path
    ) -> None:
        # Tenant-route durability across replica restarts: the
        # supervisor retains enough to rebuild the route on a fresh
        # replica (thread mode; the pool's tenant-generation registry
        # replays routes in process mode).  Standalone batcher mode
        # needs nothing — the route already lives on the one batcher.
        if self.supervisor is not None:
            self.supervisor.on_tenant_swap_commit(
                tenant, model, index_maps, config, version, path
            )

    def _on_swap_kill(self, batcher, reason: str) -> None:
        # Through the supervisor where there is one: kill_replica marks
        # the replica down in the same call, so the rollback returns
        # with supervisor state already reflecting the convergence kill.
        if self.supervisor is not None:
            self.supervisor.kill_batcher(batcher, reason)
            return
        kill = getattr(batcher, "kill", None)
        if callable(kill):
            kill(reason)

    def reload(
        self,
        model_dir: Optional[str] = None,
        rollback: bool = False,
        mode: str = "full",
        tenant: Optional[str] = None,
    ):
        """Hot-swap to the model at ``model_dir`` (or roll back one
        step).  ``mode="delta"`` treats ``model_dir`` as a delta
        artifact (``freshness/delta.py``) and patches only the changed
        rows of the serving model — ``POST /reload?mode=delta``.
        ``tenant`` scopes the swap (or rollback) to ONE tenant's route
        (``POST /reload?tenant=acme``) — every other tenant and the
        default route are untouched; tenant reloads support
        ``mode="full"`` only.  Returns a
        :class:`~photon_ml_tpu.serving.swap.SwapResult`; raises
        SwapInProgressError on concurrent reloads and ValueError on a
        missing path or unknown mode."""
        if rollback:
            return self.swapper.rollback(tenant=tenant)
        if not model_dir:
            raise ValueError(
                "reload needs 'model_dir' (or 'rollback': true)"
            )
        if mode == "delta":
            if tenant is not None:
                raise ValueError(
                    "tenant-scoped reload supports mode='full' only "
                    "(deltas patch the default route's serving model)"
                )
            return self.swapper.swap_delta(model_dir)
        if mode != "full":
            raise ValueError(
                f"unknown reload mode {mode!r}; expected 'full' or "
                "'delta'"
            )
        return self.swapper.swap(model_dir, tenant=tenant)

    # -- scoring -----------------------------------------------------------
    def submit(
        self,
        request,
        timeout_ms: Optional[float] = None,
        annotate_stages: bool = False,
    ) -> Future:
        """Parse + enqueue one request (dict or pre-parsed Row); returns
        the future.  Raises RejectedError on a full queue or load shed
        and ValueError on malformed input.  ``annotate_stages`` asks the
        batcher to attach the per-request latency decomposition to the
        result (the opt-in ``stages`` key — docs/telemetry.md)."""
        if isinstance(request, Row):
            row = request
        elif self.supervisor is not None:
            row = self.supervisor.parse_request(request)
        else:
            row = self.current_runtime.parse_request(request)
        if annotate_stages:
            row.want_stages = True
        # Offered demand, counted BEFORE admission: a shed request is
        # still demand — exactly the signal lease rebalancing needs
        # (a host shedding for lack of lease must report the pressure).
        tenant = getattr(row, "tenant", None)
        with self._demand_lock:
            self._demand[tenant] = self._demand.get(tenant, 0) + 1
        if self.supervisor is not None:
            return self.supervisor.submit(row, timeout_ms=timeout_ms)
        return self.batcher.submit(row, timeout_ms=timeout_ms)

    def score(self, request, timeout: Optional[float] = 30.0) -> dict:
        """Blocking single-request convenience."""
        return self.submit(request).result(timeout=timeout)

    def request_parser(self):
        """The :class:`~photon_ml_tpu.serving.runtime.RequestParser`
        validating this service's requests — what the binary wire path
        decodes against (shard dims; the JSON path reads the same
        object, so both paths refuse identically)."""
        if self.supervisor is not None and self.supervisor.pool is not None:
            return self.supervisor.pool.parser
        runtime = self.current_runtime
        parser = getattr(runtime, "_parser", None)
        if parser is None:
            raise RejectedError(
                "UNAVAILABLE: no runtime available to parse against; "
                "retry with backoff"
            )
        return parser

    def score_many(
        self,
        requests: Sequence,
        timeout: Optional[float] = 30.0,
        annotate_stages: bool = False,
    ) -> list:
        """Submit all, then gather — concurrent submissions coalesce into
        shared batches.  Per-row failures come back as result dicts
        (``{"error", "kind"}``), not exceptions, so one bad row doesn't
        void its batch-mates."""
        slots: list = [None] * len(requests)
        futures: list[tuple[int, Future]] = []
        for i, req in enumerate(requests):
            try:
                futures.append((
                    i,
                    self.submit(req, annotate_stages=annotate_stages),
                ))
            except (RejectedError, ValueError, DeadlineExceededError) as exc:
                slots[i] = _error_result(exc)
        for i, fut in futures:
            try:
                slots[i] = fut.result(timeout=timeout)
            except Exception as exc:  # noqa: BLE001 — per-row reporting
                slots[i] = _error_result(exc)
        return slots

    # -- fleet quota seams (serving/fleet.py) -------------------------------
    def demand_snapshot(self) -> dict:
        """Cumulative per-tenant offered-request counts (pre-admission).
        The fleet LeaseClient differences successive snapshots into
        demand rates for the QuotaCoordinator."""
        with self._demand_lock:
            return {t: n for t, n in self._demand.items() if t is not None}

    def set_tenant_quota(
        self, tenant: str, rate_rps, burst=None
    ) -> None:
        """Apply a quota lease to this host's admission buckets —
        through the supervisor (which splits the host rate across
        replicas and replays it on restart) or straight onto the one
        batcher."""
        if self.supervisor is not None:
            self.supervisor.set_tenant_quota(tenant, rate_rps, burst)
        else:
            self.batcher.set_tenant_quota(tenant, rate_rps, burst)

    # -- observability -----------------------------------------------------
    def readiness(self) -> tuple[bool, str]:
        """The /readyz verdict: should a load balancer route traffic
        here RIGHT NOW?  False during startup warmup, mid-swap, and
        with zero healthy replicas — distinct from liveness (/livez)
        and from degraded (still serving, via the host path)."""
        if not self._started:
            return False, "not started"
        if self.swapper.in_progress:
            return False, "model swap in progress"
        if self.supervisor is not None:
            if not self.supervisor.ready:
                return False, "no healthy ready replica"
            return True, "ok"
        runtime = self.current_runtime
        if not getattr(runtime, "ready", True):
            return False, "runtime warming up"
        return True, "ok"

    def healthz(self) -> dict:
        # "degraded" ≠ down: requests still succeed through the host cold
        # path (runtime docstring); "not_ready" ≠ dead: the process is
        # alive but should not receive NEW traffic (warmup / mid-swap).
        # Statuses stay distinguishable so a load balancer can shed-or-
        # keep by policy, not by guessing.
        runtime = self.current_runtime
        degraded = (
            self.supervisor.degraded if self.supervisor is not None
            else getattr(runtime, "degraded", False)
        )
        ready, ready_reason = self.readiness()
        out = {
            "status": (
                "stopped" if not self._started
                else "not_ready" if not ready
                else "degraded" if degraded
                else "ok"
            ),
            "ready": ready,
            "ready_reason": ready_reason,
            "degraded": degraded,
            "model_version": self.swapper.version,
            "model_path": self.swapper.model_path,
            "swap_in_progress": self.swapper.in_progress,
            "tenant_versions": {
                t: v for t, (v, _) in
                self.swapper.tenant_versions().items()
            },
        }
        if self.supervisor is not None:
            sup = self.supervisor.stats()
            out["replicas"] = sup["replicas"]
            out["healthy_replicas"] = sup["healthy"]
        if runtime is not None and isinstance(runtime, ScoringRuntime):
            out.update({
                "breaker": runtime.breaker.state,
                "task": runtime.task,
                "coordinates": runtime.stats()["coordinates"],
                "buckets": list(runtime.buckets),
            })
        return out

    def stats(self) -> dict:
        out = {
            "swap": self.swapper.stats(),
            "tenancy": self.router.stats(),
        }
        if self.supervisor is not None:
            out["supervisor"] = self.supervisor.stats()
            targets = self.supervisor.swap_targets()
            if targets:
                # NOTE with a telemetry hub the batcher block is derived
                # from the process-wide registry — it aggregates across
                # replicas by construction.
                out["batcher"] = targets[0].stats()
            runtime = self.current_runtime
            if isinstance(runtime, ScoringRuntime):
                out["runtime"] = runtime.stats()
        else:
            out["runtime"] = self.current_runtime.stats()
            out["batcher"] = self.batcher.stats()
        return out


def _error_kind(exc: BaseException) -> str:
    if isinstance(exc, RejectedError):
        return "rejected"
    if isinstance(exc, DeadlineExceededError):
        return "deadline"
    if isinstance(exc, ValueError):
        return "bad_request"
    return "internal"


def _error_result(exc: BaseException) -> dict:
    return {"error": str(exc), "kind": _error_kind(exc)}


# ---------------------------------------------------------------------------
# HTTP endpoint
# ---------------------------------------------------------------------------

_KIND_STATUS = {
    "rejected": 429,
    "deadline": 504,
    "bad_request": 400,
    "internal": 500,
}

#: swap outcome → HTTP status for POST /reload (module docstring).
_SWAP_STATUS = {"swapped": 200, "rolled_back": 422, "deferred": 503}


def _status_for(results: list) -> int:
    """HTTP status for a batch of per-row results: only an ALL-failed
    response surfaces a row error as the status (429 tells a client to
    back off, 504 to re-budget); partial failure reports per-row."""
    errors = [r["kind"] for r in results if r and "error" in r]
    if errors and len(errors) == len(results):
        kinds = set(errors)
        return _KIND_STATUS[errors[0]] if len(kinds) == 1 else 500
    return 200


class _Handler(BaseHTTPRequestHandler):
    service: ScoringService  # set on the server class per instance
    protocol_version = "HTTP/1.1"

    # -- plumbing ----------------------------------------------------------
    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        pass  # request logging rides telemetry, not stderr

    def _send_json(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- routes ------------------------------------------------------------
    def do_GET(self) -> None:  # noqa: N802 — stdlib casing
        service = self.server.service
        if self.path == "/healthz":
            self._send_json(200, service.healthz())
        elif self.path == "/livez":
            self._send_json(200, {"status": "alive"})
        elif self.path == "/readyz":
            ready, reason = service.readiness()
            self._send_json(200 if ready else 503, {
                "status": "ready" if ready else "not_ready",
                "reason": reason,
            })
        elif self.path == "/stats":
            self._send_json(200, service.stats())
        else:
            self._send_json(404, {"error": f"no route {self.path}"})

    def _read_raw(self) -> bytes:
        length = int(self.headers.get("Content-Length", "0"))
        return self.rfile.read(length)

    def _read_body(self) -> dict:
        return json.loads(self._read_raw() or b"{}")

    def _content_type(self) -> str:
        ctype = self.headers.get("Content-Type") or ""
        return ctype.split(";", 1)[0].strip().lower()

    def _trace_context(self):
        """The caller's propagated trace context, from the
        ``X-Photon-Trace`` header (None when absent/malformed — an
        untraceable header must never fail the request)."""
        return telemetry_mod.TraceContext.parse(
            self.headers.get(telemetry_mod.TRACE_HEADER) or ""
        )

    def _want_stages(self) -> bool:
        """Per-request opt-in for the latency-decomposition annotation
        (``X-Photon-Stages: 1``)."""
        value = (self.headers.get("X-Photon-Stages") or "").strip().lower()
        return value in ("1", "true", "yes")

    def do_POST(self) -> None:  # noqa: N802 — stdlib casing
        # Split the query string off before routing: the reload mode
        # rides it (POST /reload?mode=delta).
        path, _, query = self.path.partition("?")
        if path == "/reload":
            self._do_reload(query)
            return
        if path != "/score":
            self._send_json(404, {"error": f"no route {self.path}"})
            return
        # Content-type negotiation (docs/serving.md "Data plane"): a
        # binary frame body takes the wire fast path; everything else is
        # the JSON compatibility path.  Both produce bitwise-identical
        # scores.
        if self._content_type() == wire_mod.CONTENT_TYPE:
            self._do_score_binary()
            return
        try:
            obj = self._read_body()
            rows = obj["rows"] if isinstance(obj, dict) and "rows" in obj \
                else [obj]
            if not isinstance(rows, list) or not rows:
                raise ValueError("'rows' must be a non-empty list")
        except (ValueError, KeyError, TypeError) as exc:
            self._send_json(400, {"error": f"bad request: {exc}"})
            return
        # Distributed tracing, JSON path: adopt the caller's context so
        # this hop's span — and the batcher's serving.batch span behind
        # it — stitch into the caller's trace (docs/telemetry.md).
        tel = telemetry_mod.current()
        with tel.adopt(self._trace_context()), tel.span(
            "serving.http_score", rows=len(rows)
        ):
            results = self.server.service.score_many(
                rows, annotate_stages=self._want_stages()
            )
        t_encode = time.perf_counter()
        self._send_json(_status_for(results), {"results": results})
        tel.histogram("serving_stage_encode_seconds").observe(
            time.perf_counter() - t_encode
        )

    def _do_score_binary(self) -> None:
        """POST /score with a wire-frame body: decode zero-copy into
        Rows, score, answer with a wire response frame — unless the
        client's Accept header explicitly asks for JSON back (the
        fallback matrix in docs/serving.md)."""
        tel = telemetry_mod.current()
        body = self._read_raw()
        tel.counter("serving_wire_rx_bytes").inc(len(body))
        try:
            rows, trace = wire_mod.decode_request_ex(
                body, self.server.service.request_parser()
            )
        except wire_mod.WireFormatError as exc:
            tel.counter("serving_wire_errors_total").inc()
            self._send_json(400, {"error": f"bad frame: {exc}"})
            return
        except RejectedError as exc:
            self._send_json(429, {"error": str(exc)})
            return
        tel.counter("serving_wire_requests_total").inc()
        tel.counter("serving_wire_rows_total").inc(len(rows))
        # Distributed tracing, binary path: the wire v2 trace:ctx column
        # wins (it rode the frame itself); the HTTP header is the
        # fallback for v1 frames POSTed by a traced client.
        ctx = None
        if trace is not None:
            ctx = telemetry_mod.TraceContext.parse(trace)
        if ctx is None:
            ctx = self._trace_context()
        with tel.adopt(ctx), tel.span(
            "serving.http_score", rows=len(rows)
        ):
            results = self.server.service.score_many(
                rows, annotate_stages=self._want_stages()
            )
        status = _status_for(results)
        accept = (self.headers.get("Accept") or "").lower()
        if "application/json" in accept:
            t_encode = time.perf_counter()
            self._send_json(status, {"results": results})
            tel.histogram("serving_stage_encode_seconds").observe(
                time.perf_counter() - t_encode
            )
            return
        t_encode = time.perf_counter()
        frame = wire_mod.encode_response(results)
        tel.histogram("serving_stage_encode_seconds").observe(
            time.perf_counter() - t_encode
        )
        tel.counter("serving_wire_tx_bytes").inc(len(frame))
        self.send_response(status)
        self.send_header("Content-Type", wire_mod.CONTENT_TYPE)
        self.send_header("Content-Length", str(len(frame)))
        self.end_headers()
        self.wfile.write(frame)

    def _do_reload(self, query: str = "") -> None:
        try:
            obj = self._read_body()
            if not isinstance(obj, dict):
                raise ValueError("reload body must be a JSON object")
            # Mode and tenant come from the query string
            # (?mode=delta&tenant=acme) or the body; the body wins when
            # both are present.
            mode = "full"
            tenant = None
            for part in query.split("&"):
                key, _, value = part.partition("=")
                if key == "mode" and value:
                    mode = value
                elif key == "tenant" and value:
                    tenant = value
            mode = obj.get("mode", mode)
            tenant = obj.get("tenant", tenant)
            result = self.server.service.reload(
                model_dir=obj.get("model_dir"),
                rollback=bool(obj.get("rollback")),
                mode=mode,
                tenant=tenant,
            )
        except SwapInProgressError as exc:
            self._send_json(409, {"error": str(exc)})
            return
        except (ValueError, KeyError, TypeError) as exc:
            self._send_json(400, {"error": f"bad request: {exc}"})
            return
        self._send_json(
            _SWAP_STATUS.get(result.status, 500), result.to_dict()
        )


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    service: ScoringService


def start_http_server(
    service: ScoringService, host: str = "127.0.0.1", port: int = 0
) -> tuple[_Server, threading.Thread]:
    """Serve ``service`` over HTTP on a daemon thread; returns
    ``(server, thread)``.  ``port=0`` binds an ephemeral port — read it
    back from ``server.server_address[1]``.  Shut down with
    ``server.shutdown(); server.server_close()``."""
    server = _Server((host, port), _Handler)
    server.service = service
    thread = threading.Thread(
        target=server.serve_forever, name="scoring-http", daemon=True
    )
    thread.start()
    return server, thread
