"""ScoringService: the in-process API and the stdlib HTTP endpoint.

``ScoringService`` composes a :class:`~photon_ml_tpu.serving.runtime.
ScoringRuntime` with a :class:`~photon_ml_tpu.serving.batcher.MicroBatcher`
and is the one object callers touch:

    with ScoringService(runtime) as svc:
        fut = svc.submit({"dense": {"global": [...]}, "ids": {...}})
        result = svc.score({...})            # blocking convenience
        many = svc.score_many([{...}, ...])  # coalesces naturally

``start_http_server(svc, port)`` exposes the same API over a stdlib
``ThreadingHTTPServer`` (one thread per connection; the dispatch thread
still owns all scoring, so concurrency is safe by construction):

- ``POST /score`` — ``{"rows": [...]}`` or a single request object;
  responds ``{"results": [...]}`` with per-row ``{"score", "mean",
  "latency_ms"}`` or ``{"error", "kind"}``.  A fully-rejected call
  returns 429, a fully-expired one 504, bad input 400.
- ``GET /healthz`` — liveness + model identity.
- ``GET /stats`` — runtime + batcher counters.  With a telemetry hub
  enabled the batcher block is DERIVED from the hub's registry (the
  ``"source": "telemetry"`` field says so) — one source of truth with
  the /metrics exposition; with telemetry disabled a minimal internal
  mirror answers instead (``"source": "internal"``).
"""

from __future__ import annotations

import json
import threading
from concurrent.futures import Future
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

from photon_ml_tpu.serving.batcher import (
    BatcherConfig,
    DeadlineExceededError,
    MicroBatcher,
    RejectedError,
)
from photon_ml_tpu.serving.runtime import Row, ScoringRuntime


class ScoringService:
    """Runtime + batcher, started/stopped as one unit."""

    def __init__(
        self,
        runtime: ScoringRuntime,
        batcher_config: Optional[BatcherConfig] = None,
        policy=None,
    ):
        self.runtime = runtime
        self.batcher = MicroBatcher(runtime, batcher_config, policy=policy)
        self._started = False

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ScoringService":
        self.batcher.start()
        self._started = True
        return self

    def stop(self) -> None:
        self.batcher.stop()
        self._started = False

    def __enter__(self) -> "ScoringService":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- scoring -----------------------------------------------------------
    def submit(self, request, timeout_ms: Optional[float] = None) -> Future:
        """Parse + enqueue one request (dict or pre-parsed Row); returns
        the future.  Raises RejectedError on a full queue and ValueError
        on malformed input."""
        row = (
            request
            if isinstance(request, Row)
            else self.runtime.parse_request(request)
        )
        return self.batcher.submit(row, timeout_ms=timeout_ms)

    def score(self, request, timeout: Optional[float] = 30.0) -> dict:
        """Blocking single-request convenience."""
        return self.submit(request).result(timeout=timeout)

    def score_many(
        self, requests: Sequence, timeout: Optional[float] = 30.0
    ) -> list:
        """Submit all, then gather — concurrent submissions coalesce into
        shared batches.  Per-row failures come back as result dicts
        (``{"error", "kind"}``), not exceptions, so one bad row doesn't
        void its batch-mates."""
        slots: list = [None] * len(requests)
        futures: list[tuple[int, Future]] = []
        for i, req in enumerate(requests):
            try:
                futures.append((i, self.submit(req)))
            except (RejectedError, ValueError, DeadlineExceededError) as exc:
                slots[i] = _error_result(exc)
        for i, fut in futures:
            try:
                slots[i] = fut.result(timeout=timeout)
            except Exception as exc:  # noqa: BLE001 — per-row reporting
                slots[i] = _error_result(exc)
        return slots

    # -- observability -----------------------------------------------------
    def healthz(self) -> dict:
        # "degraded" ≠ down: requests still succeed through the host cold
        # path (runtime docstring); status stays distinguishable so a
        # load balancer can shed-or-keep by policy, not by guessing.
        degraded = self.runtime.degraded
        return {
            "status": (
                "stopped" if not self._started
                else "degraded" if degraded
                else "ok"
            ),
            "degraded": degraded,
            "breaker": self.runtime.breaker.state,
            "task": self.runtime.task,
            "coordinates": self.runtime.stats()["coordinates"],
            "buckets": list(self.runtime.buckets),
        }

    def stats(self) -> dict:
        return {
            "runtime": self.runtime.stats(),
            "batcher": self.batcher.stats(),
        }


def _error_kind(exc: BaseException) -> str:
    if isinstance(exc, RejectedError):
        return "rejected"
    if isinstance(exc, DeadlineExceededError):
        return "deadline"
    if isinstance(exc, ValueError):
        return "bad_request"
    return "internal"


def _error_result(exc: BaseException) -> dict:
    return {"error": str(exc), "kind": _error_kind(exc)}


# ---------------------------------------------------------------------------
# HTTP endpoint
# ---------------------------------------------------------------------------

_KIND_STATUS = {
    "rejected": 429,
    "deadline": 504,
    "bad_request": 400,
    "internal": 500,
}


class _Handler(BaseHTTPRequestHandler):
    service: ScoringService  # set on the server class per instance
    protocol_version = "HTTP/1.1"

    # -- plumbing ----------------------------------------------------------
    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        pass  # request logging rides telemetry, not stderr

    def _send_json(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- routes ------------------------------------------------------------
    def do_GET(self) -> None:  # noqa: N802 — stdlib casing
        if self.path == "/healthz":
            self._send_json(200, self.server.service.healthz())
        elif self.path == "/stats":
            self._send_json(200, self.server.service.stats())
        else:
            self._send_json(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 — stdlib casing
        if self.path != "/score":
            self._send_json(404, {"error": f"no route {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            obj = json.loads(self.rfile.read(length) or b"{}")
            rows = obj["rows"] if isinstance(obj, dict) and "rows" in obj \
                else [obj]
            if not isinstance(rows, list) or not rows:
                raise ValueError("'rows' must be a non-empty list")
        except (ValueError, KeyError, TypeError) as exc:
            self._send_json(400, {"error": f"bad request: {exc}"})
            return
        results = self.server.service.score_many(rows)
        errors = [r["kind"] for r in results if r and "error" in r]
        if errors and len(errors) == len(results):
            # Every row failed the same way → surface it as the HTTP
            # status (429 tells a client to back off, 504 to re-budget).
            kinds = set(errors)
            status = _KIND_STATUS[errors[0]] if len(kinds) == 1 else 500
        else:
            status = 200  # partial failure reports per-row
        self._send_json(status, {"results": results})


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    service: ScoringService


def start_http_server(
    service: ScoringService, host: str = "127.0.0.1", port: int = 0
) -> tuple[_Server, threading.Thread]:
    """Serve ``service`` over HTTP on a daemon thread; returns
    ``(server, thread)``.  ``port=0`` binds an ephemeral port — read it
    back from ``server.server_address[1]``.  Shut down with
    ``server.shutdown(); server.server_close()``."""
    server = _Server((host, port), _Handler)
    server.service = service
    thread = threading.Thread(
        target=server.serve_forever, name="scoring-http", daemon=True
    )
    thread.start()
    return server, thread
