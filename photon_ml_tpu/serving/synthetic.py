"""Synthetic GAME serving workload (selfcheck, tests, bench_serving).

Builds an in-memory GAME model with one fixed effect and one per-entity
random effect — the MovieLens shape the training benches use — plus a
request generator with a zipf-tailed entity stream, so the LRU hot set
sees realistic skew: a few heavy entities dominate (hot hits) over a long
cold tail (fallback gathers + promotions).
"""

from __future__ import annotations

import numpy as np

from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel


class SyntheticWorkload:
    """A GAME model + matching request stream.

    ``entity_skew`` > 0 draws request entities zipf(``entity_skew``)
    (rank-1 dominates); 0 draws them uniformly.  Entity ids beyond
    ``n_entities`` never occur, so every request joins (use
    ``unknown_rate`` to mix in never-trained entities).
    """

    def __init__(
        self,
        n_entities: int = 64,
        fixed_dim: int = 8,
        re_dim: int = 4,
        task: str = "logistic",
        entity_key: str = "userId",
        fixed_shard: str = "global",
        re_shard: str = "userFeatures",
        entity_skew: float = 1.4,
        unknown_rate: float = 0.0,
        seed: int = 0,
    ):
        rng = np.random.default_rng(seed)
        self.n_entities = int(n_entities)
        self.fixed_dim = int(fixed_dim)
        self.re_dim = int(re_dim)
        self.entity_key = entity_key
        self.fixed_shard = fixed_shard
        self.re_shard = re_shard
        self.entity_skew = float(entity_skew)
        self.unknown_rate = float(unknown_rate)

        w_fixed = rng.normal(size=fixed_dim).astype(np.float32)
        glm = GeneralizedLinearModel(
            Coefficients(means=np.asarray(w_fixed)), task
        )
        cols = np.arange(re_dim, dtype=np.int32)
        table = {
            f"u{i}": (cols, rng.normal(size=re_dim).astype(np.float32))
            for i in range(self.n_entities)
        }
        self.model = GameModel(
            models={
                "fixed": FixedEffectModel(glm, fixed_shard),
                "per_entity": RandomEffectModel(
                    coefficients=table,
                    feature_shard=re_shard,
                    entity_key=entity_key,
                    task=task,
                    n_features=re_dim,
                ),
            },
            task=task,
        )
        self.index_maps = {
            fixed_shard: IndexMap.build(
                [feature_key(f"g{j}", "") for j in range(fixed_dim)]
            ),
            re_shard: IndexMap.build(
                [feature_key(f"r{j}", "") for j in range(re_dim)]
            ),
        }

    def entity_for(self, i: int, rng: np.random.Generator) -> str:
        if self.unknown_rate > 0 and rng.uniform() < self.unknown_rate:
            return f"unknown{i}"
        if self.entity_skew > 0:
            rank = min(
                int(rng.zipf(1.0 + self.entity_skew)), self.n_entities
            )
            return f"u{rank - 1}"
        return f"u{rng.integers(self.n_entities)}"

    def request(self, i: int) -> dict:
        """Deterministic i-th request (dense features + one entity id)."""
        rng = np.random.default_rng(1_000_003 + i)
        return {
            "dense": {
                self.fixed_shard: rng.normal(
                    size=self.fixed_dim
                ).astype(np.float32).tolist(),
                self.re_shard: rng.normal(
                    size=self.re_dim
                ).astype(np.float32).tolist(),
            },
            "ids": {self.entity_key: self.entity_for(i, rng)},
            "offset": float(rng.normal(scale=0.1)),
        }
